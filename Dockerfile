# containerpilot-tpu container image.
#
# The reference ships as a container init (reference: Dockerfile:1,
# makefile:21-30 — a static binary built inside a container). The
# TPU-host equivalent: a native PID-1 reaper (cpsup) as ENTRYPOINT
# that forks the Python supervisor, which runs jobs/health/discovery/
# telemetry for the host's JAX processes.
#
#   make image                      # build
#   docker run -v $PWD/examples/training-pod.json5:/etc/containerpilot.json5 \
#       containerpilot-tpu          # run the example pod config
#
# The workload extra (jax/optax/orbax) is NOT installed here: TPU
# images layer the matching libtpu+jax wheels on top (they are
# hardware/driver specific). The supervisor half has no jax dependency.

FROM debian:bookworm-slim AS build-sup
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
COPY native/ /src/native/
RUN make -C /src/native cpsup

FROM python:3.12-slim
COPY --from=build-sup /src/native/cpsup /bin/cpsup
WORKDIR /opt/containerpilot-tpu
COPY pyproject.toml README.md ./
COPY containerpilot_tpu/ containerpilot_tpu/
RUN pip install --no-cache-dir .
COPY examples/ /etc/containerpilot/examples/

# PID 1 is the native reaper; it forks the supervisor CLI
# (reference: main.go:23-27 — the Go binary re-execs itself under sup)
ENTRYPOINT ["/bin/cpsup", "python", "-m", "containerpilot_tpu"]
CMD ["-config", "/etc/containerpilot.json5"]
