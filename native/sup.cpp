// cpsup — a minimal container init for the TPU supervisor.
//
// Native-equivalent of the reference's PID-1 layer (reference:
// sup/sup.go): fork the worker command, forward
// SIGINT/SIGTERM/SIGHUP/SIGUSR1/SIGUSR2 to it, and reap every orphan
// that gets reparented onto PID 1 via a waitpid(-1) loop on SIGCHLD —
// without stealing the worker's own child waits (the worker runs in its
// own process; we only ever wait in *this* process, so its internal
// waits are unaffected).
//
// Usage:  cpsup <worker-command> [args...]
// Typical container entrypoint:
//   ENTRYPOINT ["cpsup", "python", "-m", "containerpilot_tpu",
//               "-config", "/etc/containerpilot.json5"]
//
// Exit code: the worker's exit code, or 128+signal if it was killed.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/prctl.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

volatile sig_atomic_t g_worker_pid = 0;
volatile sig_atomic_t g_pending_signal = 0;

void forward_handler(int signum) {
  // async-signal-safe: just record; the main loop forwards
  g_pending_signal = signum;
  if (g_worker_pid > 0) {
    kill(g_worker_pid, signum);
  }
}

void install_forwarding() {
  const int signals[] = {SIGINT, SIGTERM, SIGHUP, SIGUSR1, SIGUSR2};
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = forward_handler;
  sigemptyset(&sa.sa_mask);
  sa.sa_flags = SA_RESTART;
  for (int sig : signals) {
    sigaction(sig, &sa, nullptr);
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <command> [args...]\n", argv[0]);
    return 2;
  }

  // Orphans only reparent onto us automatically when we are literal
  // PID 1. Everywhere else — under systemd on a TPU VM, under a test
  // harness, in a PID namespace where some shim is 1 — we must claim
  // subreaper status or the waitpid(-1) loop below never sees a
  // single orphan and "reaping" silently does nothing (reference
  // proves this arrangement end-to-end:
  // integration_tests/tests/test_reap_zombies/run.sh:24-30).
  // Harmless as real PID 1; best-effort on kernels without it.
  if (prctl(PR_SET_CHILD_SUBREAPER, 1, 0, 0, 0) != 0) {
    perror("cpsup: prctl(PR_SET_CHILD_SUBREAPER)");
  }

  pid_t worker = fork();
  if (worker < 0) {
    perror("cpsup: fork");
    return 1;
  }
  if (worker == 0) {
    // child: become the worker
    execvp(argv[1], &argv[1]);
    fprintf(stderr, "cpsup: exec %s: %s\n", argv[1], strerror(errno));
    _exit(127);
  }

  g_worker_pid = worker;
  install_forwarding();

  // reap loop (reference: sup/sup.go:61-92): a blocking wait on -1
  // collects both our worker and any orphans reparented to us as init.
  int exit_code = 0;
  for (;;) {
    int status = 0;
    pid_t pid = waitpid(-1, &status, 0);
    if (pid < 0) {
      if (errno == EINTR) continue;
      if (errno == ECHILD) break;  // no children left at all
      perror("cpsup: waitpid");
      break;
    }
    if (pid == worker) {
      if (WIFEXITED(status)) {
        exit_code = WEXITSTATUS(status);
      } else if (WIFSIGNALED(status)) {
        exit_code = 128 + WTERMSIG(status);
      }
      break;
    }
    // else: an orphan zombie — reaped, nothing more to do
  }

  // final sweep: reap whatever is left without blocking forever
  for (;;) {
    int status = 0;
    pid_t pid = waitpid(-1, &status, WNOHANG);
    if (pid <= 0) break;
  }
  return exit_code;
}
