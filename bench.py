"""Benchmark: supervisor job-dispatch latency.

The reference supervisor publishes no benchmarks; its documented perf
contract is the expected 20-50ms fork/exec round trip on commodity
container hosts (BASELINE.md; reference docs/30-configuration/
34-jobs.md:126,137,207). This bench measures our equivalent end-to-end
number through the REAL stack: per cycle, a one-shot job is built,
subscribed to a fresh bus, its event loop started, GLOBAL_STARTUP
published, the child process spawned, its exit observed, and the
stopping/stopped cleanup completed.

Prints ONE JSON line:
    {"metric": ..., "value": <median ms>, "unit": "ms", "vs_baseline": r}
vs_baseline = 35ms (the documented expectation's midpoint) / measured —
above 1.0 means faster dispatch than the reference's stated envelope.
"""
from __future__ import annotations

import asyncio
import json
import logging
import statistics
import time

logging.disable(logging.CRITICAL)

from containerpilot_tpu.events import EventBus, GLOBAL_STARTUP  # noqa: E402
from containerpilot_tpu.jobs import Job, JobConfig  # noqa: E402

BASELINE_MS = 35.0  # midpoint of the reference's documented 20-50ms
CYCLES = 60
WARMUP = 5


async def one_cycle() -> float:
    bus = EventBus()
    job = Job(JobConfig({"name": "bench", "exec": "/bin/true"}).validate(None))
    job.subscribe(bus)
    job.register(bus)
    task = job.run()
    start = time.perf_counter()
    bus.publish(GLOBAL_STARTUP)
    await bus.wait()  # full lifecycle: spawn -> exit -> cleanup
    await task
    return (time.perf_counter() - start) * 1e3


async def main() -> None:
    samples = []
    for i in range(CYCLES + WARMUP):
        ms = await one_cycle()
        if i >= WARMUP:
            samples.append(ms)
    median = statistics.median(samples)
    print(
        json.dumps(
            {
                "metric": "supervisor_job_dispatch_latency_p50",
                "value": round(median, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / median, 2),
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(main())
