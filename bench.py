"""Benchmarks: supervisor dispatch latency + TPU workload performance.

Two halves, matching what this framework is:

1. **Supervisor job-dispatch latency** (the BASELINE.md contract).
   The reference supervisor publishes no benchmarks; its documented
   perf contract is the expected 20-50ms fork/exec round trip on
   commodity container hosts (reference docs/30-configuration/
   34-jobs.md:126,137,207). Measured end-to-end through the REAL
   stack: job built, subscribed to a fresh bus, event loop started,
   GLOBAL_STARTUP published, child spawned, exit observed,
   stopping/stopped cleanup completed.

2. **TPU workload performance** (run when a TPU backend is present):
   - a flagship-model training step: tokens/sec and model FLOPs
     utilization (MFU, PaLM-style 6N + 12*L*d*s accounting against
     the chip's bf16 peak);
   - pallas flash attention (fwd+bwd) vs the XLA einsum path at
     2k/4k/8k sequence lengths;
   - int8 weight-quantized GEMM (pallas fused dequant) vs bf16;
   - KV-cache generation throughput at batch 1 vs batch 8 (the
     continuous-batching multiplier).

   One workload bench runs on ANY backend: ``host_overhead_bench``
   measures the slot engine's per-round host overhead (device-
   resident state + lookahead vs the legacy upload-per-round loop)
   on a tiny CPU-sized config, so BENCH_r{N}.json records a real
   serving number even when no TPU is reachable.

Prints ONE JSON line:
    {"metric": ..., "value": <median ms>, "unit": "ms",
     "vs_baseline": r, "extras": {...workload numbers...}}
vs_baseline = 35ms (the documented expectation's midpoint) / measured —
above 1.0 means faster dispatch than the reference's stated envelope.
The workload numbers live in "extras" on the same line so the driver
records them in BENCH_r{N}.json.
"""
from __future__ import annotations

import asyncio
import json
import logging
import statistics
import time

from containerpilot_tpu.events import EventBus, GLOBAL_STARTUP
from containerpilot_tpu.jobs import Job, JobConfig

# Canonical tunnel-aware timing (sync-fetch, floor subtraction, and
# the floor-noise escalation guard) lives with the autotuner so bench
# numbers and autotune block selection share one methodology.
from containerpilot_tpu.ops.autotune import (  # noqa: E402
    _floor_ms as _sync_floor_ms,
    _sync,
    _time_ms,
)

BASELINE_MS = 35.0  # midpoint of the reference's documented 20-50ms
MFU_TARGET = 0.35   # the docs/50-workload.md "MFU target" contract
# (v5e, seq 2048 / batch 8 bench config); training_bench stamps its
# measurement with meets_target so BENCH_r{N}.json self-reports
CYCLES = 60
WARMUP = 5

# MFU denominator lives with the workload half; see
# containerpilot_tpu/workload/flops.py for the per-generation table


async def one_cycle() -> float:
    bus = EventBus()
    job = Job(JobConfig({"name": "bench", "exec": "/bin/true"}).validate(None))
    job.subscribe(bus)
    job.register(bus)
    task = job.run()
    start = time.perf_counter()
    bus.publish(GLOBAL_STARTUP)
    await bus.wait()  # full lifecycle: spawn -> exit -> cleanup
    await task
    return (time.perf_counter() - start) * 1e3


async def dispatch_bench() -> float:
    samples = []
    for i in range(CYCLES + WARMUP):
        ms = await one_cycle()
        if i >= WARMUP:
            samples.append(ms)
    return statistics.median(samples)


# ---------------------------------------------------------------------------
# TPU workload benches
# ---------------------------------------------------------------------------


def _peak_flops(device_kind: str) -> float:
    from containerpilot_tpu.workload.flops import peak_flops

    return peak_flops(device_kind)


def training_bench() -> dict:
    """One-chip flagship training step: tokens/sec + MFU."""
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
    )
    from containerpilot_tpu.parallel import (
        MeshPlan,
        init_train_state,
        make_mesh,
        make_train_step,
    )

    from containerpilot_tpu.workload.flops import train_flops_per_token

    batch, seq = 8, 2048
    base = dict(
        vocab_size=32_768,
        d_model=1024,
        n_heads=8,
        n_layers=8,
        d_ff=4096,
        max_seq_len=seq,
        # AUTO: the measured crossover decides flash vs XLA per shape,
        # and tuned blocks apply (ops/tuning.py) — the MFU recorded
        # here is the framework's best honest number, not a fixed path
        flash_min_seq=-1,
    )
    mesh = make_mesh(jax.devices()[:1], plan=MeshPlan(1, 1))
    device_kind = jax.devices()[0].device_kind
    floor = _sync_floor_ms() / 1e3

    def measure_variant(remat, loss_chunk: int = 0) -> dict:
        cfg = TransformerConfig(
            remat=remat, loss_chunk=loss_chunk, **base
        )
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh)
        n_params = sum(
            p.size for p in jax.tree_util.tree_leaves(state.params)
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq + 1), 0,
            cfg.vocab_size, jnp.int32,
        )
        # warm-up/compile + 2 steps, then timed steps (tunnel
        # roundtrip subtracted once — the sync floor would otherwise
        # inflate every step by floor/n ms)
        for _ in range(2):
            state, loss = step(state, tokens)
        _sync(loss)
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = step(state, tokens)
        _sync(loss)
        step_s = max(time.perf_counter() - t0 - floor, 1e-6) / n
        tokens_per_sec = batch * seq / step_s
        flops_per_token = train_flops_per_token(cfg, n_params, seq)
        return {
            "model_params": n_params,
            "step_ms": round(step_s * 1e3, 2),
            "tokens_per_sec": round(tokens_per_sec, 1),
            # model-FLOPs utilization: remat recompute is NOT billed
            # (standard MFU), so cheaper remat shows up as higher MFU
            "mfu": round(
                flops_per_token * tokens_per_sec
                / _peak_flops(device_kind), 4,
            ),
        }

    # remat policies trade HBM for recompute; measure what fits and
    # headline the best. EVERY per-variant failure is recorded and the
    # loop continues — a transient tunnel RPC error on variant 3 must
    # not discard variants 1-2's measurements (that is exactly how the
    # first round-5 run lost its MFU). If NO variant measured and at
    # least one failure looked transient (not OOM/Value/Type), the
    # last such error re-raises so the caller's subprocess-level
    # wedge retry still applies.
    variants: dict = {}
    transient: Exception | None = None
    for name, remat, loss_chunk in (
        ("full", True, 0),
        ("dots", "dots", 0),
        ("none", False, 0),
        # chunked cross-entropy: the 32k-vocab logits tensor is the
        # single biggest activation at this config (~2 GB f32);
        # streaming the loss head may buy more than it recomputes
        ("dots+xent512", "dots", 512),
    ):
        try:
            variants[name] = measure_variant(remat, loss_chunk)
        except Exception as exc:  # noqa: BLE001
            msg = f"{type(exc).__name__}: {exc}"
            deterministic = (
                "RESOURCE_EXHAUSTED" in msg
                or isinstance(exc, (ValueError, TypeError))
            )
            if not deterministic:
                transient = exc
            variants[name] = {"error": msg[:300]}
    ok = {k: v for k, v in variants.items() if "mfu" in v}
    if not ok and transient is not None:
        raise transient
    # partial run: some variants measured, others died on transient
    # infra errors. Mark it so the artifact can't read as a complete
    # sweep (best_remat/meets_target below cover only what measured).
    partial = {"transient_failures": True} if transient is not None else {}
    if not ok:
        # deliberately NOT the top-level "error" key: per-variant
        # failures here are deterministic (OOM, bad config), and the
        # caller's tunnel-wedge retry must not burn another full run
        # on them (wedges die at the subprocess timeout instead)
        return {
            "all_variants_failed": True, "variants": variants,
        }
    best_name = max(ok, key=lambda k: ok[k]["mfu"])
    best = ok[best_name]
    return {
        "batch": batch,
        "seq": seq,
        "remat_variants": variants,
        "best_remat": best_name,
        **partial,
        **best,
        # the stated perf contract (docs/50-workload.md "MFU target"):
        # the measurement carries its own verdict so the artifact is
        # self-evidencing
        "target_mfu": MFU_TARGET,
        "meets_target": best["mfu"] >= MFU_TARGET,
        "device": device_kind,
    }


def attention_bench() -> dict:
    """pallas flash (fwd + bwd) vs XLA einsum at 2k/4k/8k."""
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.ops import causal_attention, flash_attention
    from containerpilot_tpu.ops import tuning

    out: dict = {}
    b, h, hd = 2, 8, 128
    for s in (2048, 4096, 8192):
        ks = jax.random.split(jax.random.PRNGKey(s), 4)
        q, k, v = (
            jax.random.normal(kk, (b, s, h, hd), jnp.bfloat16)
            for kk in ks[:3]
        )
        cot = jax.random.normal(ks[3], (b, s, h, hd), jnp.bfloat16)

        # blocks from the platform's tuned table (ops/tuning.py;
        # 128/128 when none is shipped) — fwd and train tuned apart
        fq, fk = tuning.pick_blocks("fwd", s)
        tq, tk = tuning.pick_blocks("train", s)
        flash_f = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, block_q=fq, block_k=fk)
        )
        xla_f = jax.jit(causal_attention)
        flash_g = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    (
                        flash_attention(q, k, v, block_q=tq, block_k=tk)
                        * cot
                    ).astype(jnp.float32)
                ),
                argnums=(0, 1, 2),
            )
        )
        xla_g = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    (causal_attention(q, k, v) * cot).astype(jnp.float32)
                ),
                argnums=(0, 1, 2),
            )
        )
        n = 5 if s < 8192 else 3
        out[str(s)] = {
            "blocks_fwd": [fq, fk],
            "blocks_train": [tq, tk],
            "flash_fwd_ms": round(_time_ms(flash_f, q, k, v, n=n), 2),
            "xla_fwd_ms": round(_time_ms(xla_f, q, k, v, n=n), 2),
            "flash_grad_ms": round(
                _time_ms(lambda *a: flash_g(*a)[0], q, k, v, n=n), 2
            ),
            "xla_grad_ms": round(
                _time_ms(lambda *a: xla_g(*a)[0], q, k, v, n=n), 2
            ),
        }
    e8k = out["8192"]
    out["fwd_speedup_8k"] = round(e8k["xla_fwd_ms"] / e8k["flash_fwd_ms"], 2)
    out["grad_speedup_8k"] = round(
        e8k["xla_grad_ms"] / e8k["flash_grad_ms"], 2
    )
    # sliding window at 8k (window 1024): the kernels' kv-grid shrinks
    # to the contributing span, so fwd+bwd cost tracks O(s*window)
    ks = jax.random.split(jax.random.PRNGKey(81920), 3)
    q, k, v = (
        jax.random.normal(kk, (b, 8192, h, hd), jnp.bfloat16)
        for kk in ks
    )
    # full-causal tuned blocks don't transfer to windows: each q
    # block's kv span is window + block_q - 1, so a big block_q
    # inflates windowed work. Sweep a few candidates and report the
    # best (the windowed answer to the tuned table).
    win_ms, win_blocks = None, None
    for wq_b, wk_b in ((128, 128), (128, 512), (256, 512), (512, 512)):
        win_f = jax.jit(
            lambda q, k, v, a=wq_b, b_=wk_b: flash_attention(
                q, k, v, a, b_, None, 1024
            )
        )
        ms = _time_ms(win_f, q, k, v, n=3)
        if win_ms is None or ms < win_ms:
            win_ms, win_blocks = ms, [wq_b, wk_b]
    out["win1024_fwd_8k_ms"] = round(win_ms, 2)
    out["win1024_blocks"] = win_blocks
    # ratio from the unrounded value: the display rounding can hit 0.0
    out["win_fwd_speedup_8k"] = round(e8k["flash_fwd_ms"] / win_ms, 2)
    return out


def int8_bench() -> dict:
    """Fused-dequant int8 pallas GEMM vs the bf16 MXU GEMM.

    Measured at a serving-decode shape (small batch, big weights):
    that regime is weight-streaming bound, which is exactly what int8
    halves. Large-batch GEMMs are MXU-bound and int8 weight-only
    quantization does not speed those up.
    """
    import jax
    import jax.numpy as jnp

    from containerpilot_tpu.ops import int8_matmul_padded, quantize_int8

    m, k, n = 64, 4096, 14336  # decode microbatch through a big FFN
    x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    w_q, scales = quantize_int8(w)
    w_bf = w.astype(jnp.bfloat16)

    bf16_f = jax.jit(
        lambda x, w: jnp.dot(x, w, preferred_element_type=jnp.float32)
    )
    # the padded variant is the serving path for sub-tile microbatches
    # (m=64 < the 128-row tile; rows pad up and slice back)
    int8_f = jax.jit(lambda x, wq, s: int8_matmul_padded(x, wq, s))
    bf16_ms = _time_ms(bf16_f, x, w_bf, n=20)
    int8_ms = _time_ms(int8_f, x, w_q, scales, n=20)
    return {
        "shape": f"{m}x{k}x{n}",
        "bf16_ms": round(bf16_ms, 3),
        "int8_pallas_ms": round(int8_ms, 3),
        "speedup": round(bf16_ms / int8_ms, 2),
    }


def _decode_setup(cfg):
    """(cfg, params, label) for the decode-shaped benches. The default
    is ~1.2B params, ~2.4 GB bf16: decode is weight-streaming bound,
    which is the regime both the throughput and the admission bench
    measure."""
    import jax

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    label = "1.2B bf16"
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=2048, n_heads=16, n_layers=16,
            d_ff=8192, max_seq_len=1024,
        )
    else:
        label = "override"
    return cfg, init_params(jax.random.PRNGKey(0), cfg), label


def decode_bench(cfg=None, max_new: int = 64, prompt_len: int = 128) -> dict:
    """KV-cache generation throughput at serving shapes: batch 1 (the
    latency regime) and batch 8 (the continuous-batching regime).
    Decode streams the model's weights from HBM once per step no
    matter how many rows ride along, so the b8/b1 ratio is the
    throughput multiplier request coalescing buys. Each timed call is
    a full generate(): prefill of the 128-token prompt + 64 greedy
    decode steps through the jitted scan. ``cfg`` override exists for
    the CPU plumbing test; the default is the measured config.

    The slot-admission comparison lives in ``slot_admission_bench``
    (its own subprocess + timeout): the two together were structurally
    over one 900s budget — ~10 heavyweight compiles of the 1.2B
    program set — which timed out the whole bench and lost BOTH
    measurements."""
    import jax.numpy as jnp

    from containerpilot_tpu.models.decode import generate

    cfg, params, label = _decode_setup(cfg)
    max_len = prompt_len + max_new * 2

    def gen(prompt):
        return generate(
            params, prompt, cfg, max_new_tokens=max_new, max_len=max_len
        )

    out: dict = {
        "model": f"{label}, prompt {prompt_len}, {max_new} new tokens"
    }
    for b in (1, 8):
        prompt = jnp.ones((b, prompt_len), jnp.int32)
        ms = _time_ms(gen, prompt, n=3)
        out[f"b{b}_tok_s"] = round(b * max_new / (ms / 1e3), 1)
    out["batch_throughput_x"] = round(
        out["b8_tok_s"] / out["b1_tok_s"], 2
    )
    return out


def slot_admission_bench(cfg=None, max_new: int = 64,
                         prompt_len: int = 128) -> dict:
    """Slot-engine admission latency: a SHORT request arriving while a
    LONG one decodes. Sequentially it waits for the whole long
    generation; through the slot pool it joins at the next chunk
    boundary. Reported: the short request's completion latency both
    ways (the admission win is the ratio)."""
    import jax.numpy as jnp

    from containerpilot_tpu.models.decode import generate
    from containerpilot_tpu.workload.serve_slots import SlotEngine

    cfg, params, label = _decode_setup(cfg)
    out: dict = {"model": label}
    short_new, long_new = 16, max_new * 2
    slot_max_len = prompt_len + long_new
    engine = SlotEngine(
        cfg, params, slot_max_len, slots=2, chunk=8
    )
    try:
        # warm both prompt-length prefills and the chunk program
        engine.submit([1] * prompt_len, max_new=2).result(timeout=600)
        engine.submit([1] * 8, max_new=2).result(timeout=600)
        t0 = time.perf_counter()
        long_fut = engine.submit([1] * prompt_len, max_new=long_new)
        short_fut = engine.submit([2] * 8, max_new=short_new)
        short_fut.result(timeout=600)
        slot_short_ms = (time.perf_counter() - t0) * 1e3
        long_fut.result(timeout=600)
    finally:
        engine.stop()
    # sequential reference: the short request queued behind the long
    # generation pays the whole long run first. generate compiles one
    # program per max_new, so warm with the EXACT max_new values the
    # timed region runs — warming with any other value would leave
    # two compilations inside the timer and fabricate the speedup.
    long_prompt = jnp.ones((1, prompt_len), jnp.int32)
    short_prompt = jnp.full((1, 8), 2, jnp.int32)
    _sync(generate(params, long_prompt, cfg, long_new, slot_max_len))
    _sync(generate(params, short_prompt, cfg, short_new, slot_max_len))
    t0 = time.perf_counter()
    _sync(generate(params, long_prompt, cfg, long_new, slot_max_len))
    _sync(generate(params, short_prompt, cfg, short_new, slot_max_len))
    seq_short_ms = (time.perf_counter() - t0) * 1e3
    out["short_latency_ms_sequential"] = round(seq_short_ms, 1)
    out["short_latency_ms_slots"] = round(slot_short_ms, 1)
    out["admission_speedup_x"] = round(
        seq_short_ms / max(slot_short_ms, 1e-3), 2
    )
    return out


def host_overhead_bench(rounds: int = 40) -> dict:
    """Per-round HOST overhead of the continuous-batching decode loop,
    runnable on ANY backend (tiny CPU-sized config) — the bench that
    finally puts a real number in BENCH_r{N}.json when no TPU is
    reachable.

    Three measurements share one compiled chunk program:

    - ``device``: pure ``decode_slots_chunk`` time, measured SERIALLY
      (dispatch + block per round).
    - ``legacy``: the pre-device-resident-state loop shape — every
      round re-uploads the 12 host numpy knob arrays (step_idx, temp,
      top_k, top_p, eos, pad, min_new, presence, frequency, bias_idx,
      bias_val, done) into the state dict, dispatches, SERIALLY
      fetches the tokens, advances step_idx on the host, then runs
      the append-chunk bookkeeping.
    - ``engine``: the REAL SlotEngine (device-resident state + one-
      round lookahead dispatch), measured through round_times_ms()
      over a long steady decode.

    Host overhead is measured DIRECTLY, in-round, on both sides —
    not inferred by subtracting two separately-run loops. Shared
    small hosts show 2-3x scheduler tail noise per ~100ms round;
    a cross-loop subtraction of ~1-2ms host work under +-50ms noise
    is sign-flips all the way down (observed: the legacy loop's
    median beating the pure-device loop's). Instead:

    - ``legacy_host_overhead_ms``: inside each legacy round, bracket
      the two host segments the old loop serialized with device
      compute — the 12 ``jnp.asarray`` knob uploads + op_state dict
      build before dispatch, and the step-advance + append-chunk
      bookkeeping after the serial fetch. Median of their sum.
    - ``engine_host_overhead_ms``: the engine brackets its own jax
      calls; ``round_host_ms()`` is round wall time minus the time
      inside the chunk dispatches and the token fetch (where any
      device wait lands — CPU's bounded in-flight queue blocks in
      the NEXT dispatch rather than in ``device_get``). What's left
      — queue/cancel checks, token copy-out, bookkeeping, streaming
      callbacks — is the same bracket shape as the legacy measure,
      minus the uploads the device-resident state made unnecessary.
      Median.

    The round wall medians/mins for all three loops are reported as
    context: with lookahead the engine's pipelined rounds track pure
    device time (host work hides under chunk N+1's compute), and
    ``engine_round_min_ms`` is a round whose lookahead chunk had
    already finished — fetch + bookkeeping only, no device wait.
    ``overhead_vs_legacy`` is the headline ratio — the PR's
    acceptance bar is <= 0.5.

    The ``fused`` arm sweeps the device-resident multi-round window
    (K rounds per host dispatch, ``decode_slots_window``) over
    K in {1, 4, 8} on one long steady-state decode each: per K it
    reports ms/round (window wall / K) and dispatches/token off the
    live engine counters, warm-admission dispatches excluded by
    snapshotting after the warm request. The headline is
    ``fused_k8_vs_k1_dispatch_ratio`` — the megakernel bar is
    <= 0.3 (steady-state dispatches/token must fall at least
    ~3.3x when 8 rounds fuse into one dispatch), ANDed into
    ``meets_target`` next to the legacy-vs-engine overhead bar,
    which keeps measuring the classic one-round engine
    (``window=1``) unchanged."""
    import os
    import statistics as stats_mod

    import jax

    # this image's sitecustomize pins every interpreter to the TPU
    # plugin, overriding the env var; when the caller pinned a
    # platform (workload_benches passes JAX_PLATFORMS=cpu when no TPU
    # answers) re-assert it before first backend use — the same
    # post-import, pre-use update the test suite applies
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update(
            "jax_platforms", os.environ["JAX_PLATFORMS"]
        )

    import jax.numpy as jnp
    import numpy as np

    from containerpilot_tpu.models.decode import BIAS_SLOTS_MAX
    from containerpilot_tpu.models.slots import (
        append_chunk,
        decode_slots_chunk,
        init_slot_state,
        slot_cache,
    )
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve_slots import SlotEngine

    slots, chunk = 4, 16
    prompt_len = 8
    max_len = prompt_len + rounds * chunk + chunk
    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_heads=4, n_layers=2,
        d_ff=512, max_seq_len=max_len, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    def fresh():
        return (
            slot_cache(cfg, slots, max_len),
            init_slot_state(cfg, slots),
        )

    # --- pure device time: serial dispatch + block per round (see
    # docstring). A dead pool decodes the IDENTICAL program (done
    # only selects pad vs sampled token), so no admission is needed
    # here.
    pool, state = fresh()
    for _ in range(3):  # compile + settle
        pool, state, toks = decode_slots_chunk(
            params, pool, state, cfg, chunk
        )
    jax.block_until_ready(toks)
    dev_times: list = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        pool, state, toks = decode_slots_chunk(
            params, pool, state, cfg, chunk
        )
        jax.block_until_ready(toks)
        dev_times.append(time.perf_counter() - t0)

    # --- legacy loop: the pre-PR per-round host path, reproduced
    # faithfully against the same chunk program. last/keys/counts
    # stayed device-resident in the old loop too; the other 12 leaves
    # were host numpy re-uploaded via jnp.asarray EVERY round, the
    # token fetch was serial (no lookahead — nothing overlapped), and
    # step_idx advanced on the host.
    pool, state = fresh()
    step_idx = np.zeros((slots,), np.int32)
    temp = np.zeros((slots,), np.float32)
    top_k = np.zeros((slots,), np.int32)
    top_p = np.zeros((slots,), np.float32)
    eos = np.full((slots,), -1, np.int32)
    pad = np.zeros((slots,), np.int32)
    min_new = np.zeros((slots,), np.int32)
    presence = np.zeros((slots,), np.float32)
    frequency = np.zeros((slots,), np.float32)
    bias_idx = np.full((slots, BIAS_SLOTS_MAX), -1, np.int32)
    bias_val = np.zeros((slots, BIAS_SLOTS_MAX), np.float32)
    done = np.zeros((slots,), bool)
    emitted: list = [[] for _ in range(slots)]
    legacy_times: list = []
    legacy_host: list = []

    def legacy_round(record: bool) -> None:
        nonlocal pool, state, step_idx
        t0 = time.perf_counter()
        op_state = dict(
            state,
            step_idx=jnp.asarray(step_idx),
            temperature=jnp.asarray(temp),
            top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p),
            eos_id=jnp.asarray(eos),
            pad_id=jnp.asarray(pad),
            min_new=jnp.asarray(min_new),
            presence=jnp.asarray(presence),
            frequency=jnp.asarray(frequency),
            bias_idx=jnp.asarray(bias_idx),
            bias_val=jnp.asarray(bias_val),
            done=jnp.asarray(done),
        )
        t1 = time.perf_counter()  # host segment A: uploads
        pool, state, toks = decode_slots_chunk(
            params, pool, op_state, cfg, chunk
        )
        toks_host = np.asarray(jax.device_get(toks))  # serial fetch
        t2 = time.perf_counter()
        step_idx = step_idx + chunk  # host-side position bookkeeping
        for i in range(slots):
            append_chunk(
                emitted[i], toks_host[i], rounds * chunk + 1, -1
            )
        t3 = time.perf_counter()  # host segment B: bookkeeping
        if record:
            legacy_times.append(t3 - t0)
            legacy_host.append((t1 - t0) + (t3 - t2))

    for i in range(3):
        legacy_round(record=False)
    for _ in range(rounds):
        legacy_round(record=True)

    # --- the shipped engine: one long greedy request, decode-only
    # round wall times from the worker loop itself (admission rounds
    # excluded there). window=1 pins the CLASSIC one-dispatch-per-
    # round loop so the legacy-vs-engine host-overhead comparison
    # keeps measuring the same thing it always did; the fused sweep
    # below owns the multi-round story.
    engine = SlotEngine(
        cfg, params, max_len, slots=slots, chunk=chunk, window=1
    )
    try:
        # warm the prefill/admit programs so compile never lands in a
        # timed round
        engine.submit([1] * prompt_len, max_new=2).result(timeout=600)
        engine.submit(
            [1] * prompt_len, max_new=rounds * chunk
        ).result(timeout=600)
        engine_times = engine.round_times_ms()[-rounds:]
        engine_host = engine.round_host_ms()[-rounds:]
        # the dispatches/token series (ROADMAP: the megakernel work
        # must drive this DOWN — today it is ~(lookahead-doubled
        # rounds)/(chunk tokens); a device-side multi-round loop
        # collapses the numerator)
        eng_dispatches = engine.dispatches
        eng_tokens = engine.tokens_out
    finally:
        engine.stop()

    # --- fused-rounds sweep: K decode rounds per host dispatch via
    # the device-side window loop; dispatches/token is the headline
    # (ms/round rides along as context). Counters snapshot after the
    # warm request so admissions don't blur the steady-state ratio.
    fused: dict = {}
    for k_rounds in (1, 4, 8):
        eng_k = SlotEngine(
            cfg, params, max_len, slots=slots, chunk=chunk,
            window=k_rounds,
        )
        try:
            eng_k.submit([1] * prompt_len, max_new=2).result(
                timeout=600
            )
            base_d, base_t = eng_k.dispatches, eng_k.tokens_out
            eng_k.submit(
                [1] * prompt_len, max_new=rounds * chunk
            ).result(timeout=600)
            d = eng_k.dispatches - base_d
            t = eng_k.tokens_out - base_t
            window_times = eng_k.round_times_ms()[-rounds:]
            fused[f"k{k_rounds}"] = {
                "dispatches": d,
                "tokens_out": t,
                "dispatches_per_token": round(d / max(1, t), 4),
                # a steady-state window runs all K rounds; the tail
                # window may early-exit, so this slightly overstates
                # ms/round — fine for a trajectory number
                "round_ms": round(
                    stats_mod.median(window_times) / k_rounds, 3
                ),
                "window_ms": round(
                    stats_mod.median(window_times), 3
                ),
            }
        finally:
            eng_k.stop()
    fused_ratio = (
        fused["k8"]["dispatches_per_token"]
        / max(fused["k1"]["dispatches_per_token"], 1e-9)
    )

    device_ms = stats_mod.median(dev_times) * 1e3
    legacy_ms = stats_mod.median(legacy_times) * 1e3
    engine_ms = stats_mod.median(engine_times)
    legacy_over = stats_mod.median(legacy_host) * 1e3
    engine_over = stats_mod.median(engine_host)
    return {
        "backend": jax.default_backend(),
        "config": (
            f"{cfg.n_layers}L d{cfg.d_model} v{cfg.vocab_size}, "
            f"{slots} slots x {chunk}-token chunks, {rounds} rounds"
        ),
        "device_round_ms": round(device_ms, 3),
        "device_round_min_ms": round(min(dev_times) * 1e3, 3),
        "legacy_round_ms": round(legacy_ms, 3),
        "legacy_round_min_ms": round(min(legacy_times) * 1e3, 3),
        # in-round bracketed host segments (uploads + bookkeeping):
        # what the old loop serialized with device compute per round
        "legacy_host_overhead_ms": round(legacy_over, 3),
        "engine_round_ms": round(engine_ms, 3),
        # a lookahead round whose chunk already finished is fetch +
        # bookkeeping ONLY — no device wait
        "engine_round_min_ms": round(min(engine_times), 3),
        # round wall minus the engine's own bracketed jax calls:
        # the host work a shipped-engine round pays outside them
        "engine_host_overhead_ms": round(engine_over, 3),
        # host->device dispatches per emitted token over the engine's
        # whole run (warm admissions included): the megakernel
        # yardstick, recorded so BENCH_r{N}.json shows it falling
        "dispatches": eng_dispatches,
        "tokens_out": eng_tokens,
        "dispatches_per_token": round(
            eng_dispatches / max(1, eng_tokens), 4
        ),
        "overhead_vs_legacy": round(
            engine_over / max(legacy_over, 1e-9), 3
        ),
        # the device-resident multi-round sweep: K rounds fused into
        # one dispatch, dispatches/token falling ~K-fold
        "fused": fused,
        "fused_k8_vs_k1_dispatch_ratio": round(fused_ratio, 3),
        "fused_target_ratio": 0.3,
        # the PR's stated bar: the device-resident-state + lookahead
        # loop must at least halve per-round host overhead
        "target_ratio": 0.5,
        "meets_target": (
            engine_over <= 0.5 * legacy_over
            and fused_ratio <= 0.3
        ),
    }


def gateway_overhead_bench(rounds: int = 60) -> dict:
    """Per-request latency the fleet gateway adds over direct replica
    access — mux vs pooled vs per-dial, runnable on ANY backend (tiny
    CPU-sized config).

    Boots one in-process InferenceServer, registers it in a file
    catalog via a FleetMember, and fronts it with THREE gateways: one
    on the cp-mux/1 multiplexed transport (the default), one on the
    classic keep-alive connection pool (``mux=False``), one with
    reuse disabled entirely (``pool_max_idle=0``, the pre-pool
    behavior). Each round measures /v1/generate five ways,
    interleaved so scheduler drift hits every path equally:

    - direct per-dial (fresh ``Connection: close`` client per request)
    - direct keep-alive (one persistent client connection)
    - via the pool-disabled gateway over a per-dial client
    - via the pooled gateway over a keep-alive client
    - via the mux gateway over a keep-alive client
    - via an UNTRACED mux gateway (``trace=False``) over keep-alive

    ``gateway_added_pooled_ms`` vs ``gateway_added_mux_ms`` is PR 8's
    latency claim: multiplexing must cost nothing at concurrency 1.
    The burst probe after the latency rounds is its concurrency
    claim: C concurrent requests through the pooled gateway need ~C
    upstream sockets (one request per connection), while the mux
    gateway carries all C as interleaved streams on the one warm
    connection it already holds — ≥4x in-flight streams per upstream
    socket at a fixed socket count.

    The traced-vs-untraced pair is PR 9's claim: request tracing is
    ON by default (the ``gateway_mux`` path runs with it) and must be
    effectively free — the paired per-round median of traced minus
    untraced stays within 5% of the untraced median (floored at the
    0.1ms timer-noise tolerance), pinned in ``meets_target``. The
    pair isolates GATEWAY-side tracing (mint/propagate/splice/ring):
    both arms share one replica that always traces, so replica-side
    recording sits in the common baseline, not the measured delta —
    its per-request cost is a handful of float stamps plus one digest
    encode, bounded by the engine-timings no-per-token contract
    (tests) rather than by this bench."""
    import concurrent.futures
    import http.client
    import os
    import tempfile
    import urllib.request

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp

    from containerpilot_tpu.discovery import FileCatalogBackend
    from containerpilot_tpu.fleet import FleetGateway, FleetMember
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=1, d_ff=256,
        max_seq_len=64, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    server = InferenceServer(cfg, params, "127.0.0.1", 0, max_len=64)
    body = json.dumps(
        {"tokens": [[1, 2, 3, 4]], "max_new_tokens": 8}
    ).encode()

    def post_dial(port: int) -> float:
        """urllib dials per request and sends Connection: close —
        exactly the pre-keep-alive client behavior."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=300) as resp:
            resp.read()
        return (time.perf_counter() - t0) * 1e3

    class _KeepAliveClient:
        """One persistent http.client connection, redialed at most
        once per post if the server reaped it between rounds."""

        def __init__(self, port: int) -> None:
            self.port = port
            self.conn = None

        def post(self) -> float:
            t0 = time.perf_counter()
            for _ in range(2):
                if self.conn is None:
                    self.conn = http.client.HTTPConnection(
                        "127.0.0.1", self.port, timeout=300
                    )
                try:
                    self.conn.request(
                        "POST", "/v1/generate", body,
                        {"Content-Type": "application/json"},
                    )
                    resp = self.conn.getresponse()
                    resp.read()
                    if resp.will_close:
                        self.close()
                    return (time.perf_counter() - t0) * 1e3
                except (ConnectionError, http.client.BadStatusLine):
                    self.close()
            raise RuntimeError("keep-alive post failed twice")

        def close(self) -> None:
            if self.conn is not None:
                self.conn.close()
                self.conn = None

    series: dict = {
        "direct_per_dial": [],
        "direct_keepalive": [],
        "gateway_per_dial": [],
        "gateway_pooled": [],
        "gateway_mux": [],
        "gateway_mux_untraced": [],
    }
    BURST_CONCURRENCY = 12
    burst: dict = {}
    with tempfile.TemporaryDirectory() as root:
        backend = FileCatalogBackend(root)

        async def scenario() -> None:
            loop = asyncio.get_event_loop()
            await server.run()
            member = FleetMember(
                server, backend, "bench-infer", ttl=30,
                heartbeat_interval=0.2,
            )
            await member.start()
            gw_mux = FleetGateway(
                backend, "bench-infer", "127.0.0.1", 0,
                poll_interval=0.2, hedge=False,
            )
            gw_mux_untraced = FleetGateway(
                backend, "bench-infer", "127.0.0.1", 0,
                poll_interval=0.2, hedge=False, trace=False,
            )
            gw_pooled = FleetGateway(
                backend, "bench-infer", "127.0.0.1", 0,
                poll_interval=0.2, hedge=False, mux=False,
            )
            gw_dial = FleetGateway(
                backend, "bench-infer", "127.0.0.1", 0,
                poll_interval=0.2, hedge=False, pool_max_idle=0,
                mux=False,
            )
            gateways = (gw_mux, gw_mux_untraced, gw_pooled, gw_dial)
            for gw in gateways:
                await gw.run()
            for _ in range(200):
                if all(gw.replica_count for gw in gateways):
                    break
                await asyncio.sleep(0.05)
            assert all(gw.replica_count == 1 for gw in gateways)
            ka_direct = _KeepAliveClient(server.port)
            ka_pooled = _KeepAliveClient(gw_pooled.port)
            ka_mux = _KeepAliveClient(gw_mux.port)
            ka_untraced = _KeepAliveClient(gw_mux_untraced.port)
            paths = (
                ("direct_per_dial", lambda: post_dial(server.port)),
                ("direct_keepalive", ka_direct.post),
                ("gateway_per_dial", lambda: post_dial(gw_dial.port)),
                ("gateway_pooled", ka_pooled.post),
                ("gateway_mux", ka_mux.post),
                ("gateway_mux_untraced", ka_untraced.post),
            )
            for _ in range(5):  # warm every path (compiles, routes)
                for _name, fn in paths:
                    await loop.run_in_executor(None, fn)
            for _ in range(rounds):
                for name, fn in paths:
                    series[name].append(
                        await loop.run_in_executor(None, fn)
                    )

            # concurrency probe at a FIXED socket count: fire C
            # concurrent requests per gateway and count the upstream
            # sockets the replica saw. Each gateway starts warm (one
            # mux conn / one pooled conn from the rounds above), so
            # the delta is what concurrency itself costs in sockets.
            pool = concurrent.futures.ThreadPoolExecutor(
                BURST_CONCURRENCY
            )
            try:
                http_server = server._server  # noqa: SLF001
                for name, gw in (("mux", gw_mux), ("pooled", gw_pooled)):
                    before = http_server.connections_accepted
                    await asyncio.gather(*[
                        loop.run_in_executor(
                            pool, post_dial, gw.port
                        )
                        for _ in range(BURST_CONCURRENCY)
                    ])
                    # warm conns carried over from the rounds plus
                    # whatever the burst had to dial
                    dialed = http_server.connections_accepted - before
                    sockets = max(1, dialed + 1)
                    burst[name] = {
                        "concurrency": BURST_CONCURRENCY,
                        "upstream_sockets": sockets,
                        "streams_per_socket": round(
                            BURST_CONCURRENCY / sockets, 2
                        ),
                    }
            finally:
                pool.shutdown(wait=False)
            ka_direct.close()
            ka_pooled.close()
            ka_mux.close()
            ka_untraced.close()
            for gw in gateways:
                await gw.stop()
            await member.stop()
            await server.stop()

        asyncio.run(scenario())

    med = {k: statistics.median(v) for k, v in series.items()}
    added_per_dial = med["gateway_per_dial"] - med["direct_per_dial"]
    added_pooled = med["gateway_pooled"] - med["direct_keepalive"]
    added_mux = med["gateway_mux"] - med["direct_keepalive"]
    # mux-vs-pooled at concurrency 1 is judged on PAIRED per-round
    # differences: the two paths run back-to-back inside each
    # interleaved round, so pairing cancels the scheduler drift that
    # dominates a difference of independent medians on a shared box.
    # The parity tolerance is explicit in the output: mux must sit
    # within timer-resolution noise of pooled, not beat it.
    paired = statistics.median([
        m - p
        for m, p in zip(series["gateway_mux"], series["gateway_pooled"])
    ])
    # tracing's cost, same paired discipline: the traced default-mux
    # path against the trace=False control, per interleaved round
    trace_paired = statistics.median([
        t - u
        for t, u in zip(
            series["gateway_mux"], series["gateway_mux_untraced"]
        )
    ])
    trace_tolerance = max(
        0.05 * med["gateway_mux_untraced"], 0.1
    )
    concurrency_ratio = (
        burst["mux"]["streams_per_socket"]
        / burst["pooled"]["streams_per_socket"]
        if burst.get("pooled", {}).get("streams_per_socket") else None
    )
    return {
        "backend": jax.default_backend(),
        "config": (
            f"{cfg.n_layers}L d{cfg.d_model} v{cfg.vocab_size}, "
            f"8 new tokens, {rounds} interleaved rounds"
        ),
        "direct_per_dial_ms": round(med["direct_per_dial"], 3),
        "direct_keepalive_ms": round(med["direct_keepalive"], 3),
        "gateway_per_dial_ms": round(med["gateway_per_dial"], 3),
        "gateway_pooled_ms": round(med["gateway_pooled"], 3),
        "gateway_mux_ms": round(med["gateway_mux"], 3),
        "gateway_mux_untraced_ms": round(
            med["gateway_mux_untraced"], 3
        ),
        "gateway_added_per_dial_ms": round(added_per_dial, 3),
        "gateway_added_pooled_ms": round(added_pooled, 3),
        "gateway_added_mux_ms": round(added_mux, 3),
        "gateway_added_per_dial_min_ms": round(
            min(series["gateway_per_dial"])
            - min(series["direct_per_dial"]), 3
        ),
        "gateway_added_pooled_min_ms": round(
            min(series["gateway_pooled"])
            - min(series["direct_keepalive"]), 3
        ),
        "gateway_added_mux_min_ms": round(
            min(series["gateway_mux"])
            - min(series["direct_keepalive"]), 3
        ),
        # PR 5's bar (recorded for the trajectory; its pass was
        # pinned in r05 and it is not this bench's gating claim)
        "target_ratio": 0.5,
        "pooled_over_per_dial": (
            round(added_pooled / added_per_dial, 3)
            if added_per_dial > 0 else None
        ),
        # PR 8's bars: mux adds no latency at concurrency 1 (paired
        # median within the stated parity tolerance of pooled), and
        # multiplies in-flight streams per upstream socket >= 4x
        "mux_over_pooled": (
            round(added_mux / added_pooled, 3)
            if added_pooled > 0 else None
        ),
        "mux_minus_pooled_paired_ms": round(paired, 3),
        "latency_parity_tolerance_ms": 0.1,
        # PR 9's bar: tracing is ON by default (gateway_mux runs
        # traced) and must be effectively free — paired median within
        # 5% of the untraced control (floored at timer noise)
        "traced_minus_untraced_paired_ms": round(trace_paired, 3),
        "trace_overhead_tolerance_ms": round(trace_tolerance, 3),
        "burst": burst,
        "mux_concurrency_ratio": concurrency_ratio,
        "concurrency_target_ratio": 4.0,
        "meets_target": (
            paired <= 0.1
            and trace_paired <= trace_tolerance
            and concurrency_ratio is not None
            and concurrency_ratio >= 4.0
        ),
    }


def goodput_ledger_bench(requests: int = 6, max_new: int = 96) -> dict:
    """The device-time ledger's accounting bench, runnable on ANY
    backend (tiny CPU-sized config): boot one real InferenceServer
    (slot engine on), drive a handful of buffered generations with a
    deliberate idle gap and one drain/resume cycle, and read the
    ledger back over its REAL surface (``GET /v1/goodput``). Records:

    - ``accounting_error_fraction``: |sum(per-stage seconds) -
      uptime| / uptime. The ledger closes by construction; the bench
      proves the shipped wiring (engine stamps, warmup override,
      drain override, HTTP read path) kept it closed — the
      every-device-second-attributed acceptance bar is 2%.
    - ``dispatches_per_token``: the megakernel yardstick off the
      live engine counters — fused multi-round decode (the default
      window=4 engine) must land well under the old one-dispatch-
      per-chunk floor (chunk=8 x window=4 measures ~0.04-0.1
      depending on admission mix; the pre-fusion loop sat at
      ~0.15-0.45).
    - stage sanity: compile_warmup seconds exist (stamped BEFORE
      /health flipped 200), idle covers the injected gap, drain
      covers the maintenance window, prefill+decode > 0.

    ``meets_target`` pins accounting_error_fraction <= 0.02 AND
    dispatches_per_token <= 0.2 (tightened from 0.5 when the fused
    window landed: the dispatch tax is the thing the megakernel work
    collapses, and the bar must fall with it) — the badput
    trajectory bar release-over-release (``make bench-goodput``)."""
    import asyncio
    import http.client
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=256, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    out: dict = {}

    async def scenario() -> None:
        server = InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=256,
            slots=4, slot_chunk=8,
        )
        await server.run()
        loop = asyncio.get_event_loop()

        def fetch(method: str, path: str, body: bytes = b"") -> bytes:
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=120
            )
            try:
                conn.request(
                    method, path, body or None,
                    {"Content-Type": "application/json"}
                    if body else {},
                )
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    raise RuntimeError(
                        f"{path} -> {resp.status}: {payload[:120]!r}"
                    )
                return payload
            finally:
                conn.close()

        body = json.dumps(
            {"tokens": [[1, 2, 3, 4, 5, 6, 7, 8]],
             "max_new_tokens": max_new}
        ).encode()
        for _ in range(requests):
            await loop.run_in_executor(
                None, fetch, "POST", "/v1/generate", body
            )
        # a deliberate idle gap the ledger must attribute as idle
        await asyncio.sleep(0.5)
        # one drain/resume cycle: the maintenance window is drain
        server.enter_maintenance()
        await asyncio.sleep(0.2)
        server.exit_maintenance()
        gp = json.loads(
            await loop.run_in_executor(None, fetch, "GET", "/v1/goodput")
        )
        await server.stop()
        stages = gp["stages_s"]
        attributed = sum(stages.values())
        uptime = gp["uptime_s"]
        out.update(
            backend=jax.default_backend(),
            config=(
                f"{cfg.n_layers}L d{cfg.d_model} v{cfg.vocab_size}, "
                f"4 slots x 8-token chunks, {requests} x "
                f"{max_new}-token requests"
            ),
            uptime_s=round(uptime, 3),
            stages_s=stages,
            attributed_s=round(attributed, 3),
            accounting_error_fraction=round(
                abs(attributed - uptime) / max(uptime, 1e-9), 5
            ),
            productive_fraction=gp["productive_fraction"],
            dispatches=gp["dispatches"],
            tokens_out=gp["tokens_out"],
            dispatches_per_token=gp["dispatches_per_token"],
            scheduling_gaps=len(gp["scheduling_gaps"]),
            compile_warmup_s=stages["compile_warmup"],
            drain_s=stages["drain"],
        )

    asyncio.run(scenario())
    out["target"] = (
        "accounting_error_fraction <= 0.02 and "
        "dispatches_per_token <= 0.2 and every lifecycle stage "
        "(compile_warmup, idle, drain, prefill+decode) attributed"
    )
    out["meets_target"] = bool(
        out["accounting_error_fraction"] <= 0.02
        and out["dispatches_per_token"] is not None
        and out["dispatches_per_token"] <= 0.2
        and out["compile_warmup_s"] > 0.0
        and out["drain_s"] > 0.0
        and out["stages_s"]["idle"] >= 0.5
        and out["productive_fraction"] > 0.0
    )
    return out


def cold_start_bench(max_new: int = 16) -> dict:
    """The cold-start collapse yardstick (``make bench-coldstart``):
    time-to-first-routed-token for the three scale-up paths, with the
    per-stage attribution from each replica's ``GET /v1/goodput``:

    - **cold**: construct + boot + warmup-compile + first 200. Runs
      FIRST in a fresh interpreter, so it pays the real XLA compiles
      a production cold launch pays.
    - **promoted**: a standby (booted and warmup-compiled OUTSIDE the
      measured window — that is the warm-standby pool's whole
      premise: the compile happened BEFORE the scale event) measured
      from ``POST /v3/standby/promote`` to its first 200.
    - **peer transfer**: a launch whose weights arrive from the warm
      cold-arm replica over cp-mux/1 (``fleet.standby.fetch_params``,
      digest-verified; byte-equality asserted) instead of disk/init,
      then boot + warmup. In-process the jit caches play the shared
      XLA compile cache's role, so this arm isolates the transfer +
      boot cost the way a cache-warm same-host launch sees it.

    ``meets_target`` pins promoted TTFRT <= 0.25x cold TTFRT (the
    promoted path must dodge boot AND compile, not merely shave
    them), every arm's first request answering 200, and the
    transferred weights byte-identical to the peer's."""
    import asyncio
    import http.client
    import os
    import time as time_mod

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import numpy as np

    from containerpilot_tpu.fleet.standby import fetch_params
    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=256, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    out: dict = {}

    async def scenario() -> None:
        loop = asyncio.get_event_loop()

        def request(port: int, method: str, path: str,
                    body: bytes = b"") -> tuple:
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=120
            )
            try:
                conn.request(
                    method, path, body or None,
                    {"Content-Type": "application/json"}
                    if body else {},
                )
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()

        gen_body = json.dumps(
            {"tokens": [[1, 2, 3, 4, 5, 6, 7, 8]],
             "max_new_tokens": max_new}
        ).encode()

        async def first_token(port: int) -> int:
            status, _payload = await loop.run_in_executor(
                None, request, port, "POST", "/v1/generate", gen_body
            )
            return status

        async def stages(port: int) -> dict:
            _status, payload = await loop.run_in_executor(
                None, request, port, "GET", "/v1/goodput"
            )
            gp = json.loads(payload)
            return {
                stage: round(gp["stages_s"].get(stage, 0.0), 3)
                for stage in ("boot", "compile_warmup")
            }

        def server(**kwargs) -> InferenceServer:
            return InferenceServer(
                cfg, params, "127.0.0.1", 0, max_len=256,
                slots=2, slot_chunk=8, **kwargs,
            )

        # -- arm 1: COLD (first in this interpreter: real compiles) --
        t0 = time_mod.monotonic()
        cold = server()
        await cold.run()
        cold_status = await first_token(cold.port)
        cold_ttfrt = time_mod.monotonic() - t0
        cold_stages = await stages(cold.port)

        # -- arm 2: PROMOTED (standby boots OUTSIDE the window) ------
        standby = server(role="standby")
        await standby.run()  # boot + warmup paid before the event
        t0 = time_mod.monotonic()
        promote_status, _ = await loop.run_in_executor(
            None, request, standby.port, "POST",
            "/v3/standby/promote", b"{}",
        )
        promoted_status = await first_token(standby.port)
        promoted_ttfrt = time_mod.monotonic() - t0
        promoted_stages = await stages(standby.port)

        # -- arm 3: PEER-TRANSFER launch (weights over cp-mux/1) -----
        t0 = time_mod.monotonic()
        like = init_params(jax.random.PRNGKey(1), cfg)  # same shapes,
        # different values: byte-equality below proves the transfer
        # actually replaced them
        fetched = await fetch_params("127.0.0.1", cold.port, like)
        transfer_s = time_mod.monotonic() - t0
        transfer_ok = fetched is not None and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(params),
                jax.tree_util.tree_leaves(fetched),
            )
        )
        xfer = InferenceServer(
            cfg, fetched if fetched is not None else params,
            "127.0.0.1", 0, max_len=256, slots=2, slot_chunk=8,
        )
        await xfer.run()
        transfer_status = await first_token(xfer.port)
        transfer_ttfrt = time_mod.monotonic() - t0
        transfer_stages = await stages(xfer.port)

        for s in (standby, xfer, cold):
            await s.stop()

        total_bytes = sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(params)
        )
        out.update(
            backend=jax.default_backend(),
            config=(
                f"{cfg.n_layers}L d{cfg.d_model} v{cfg.vocab_size}, "
                f"2 slots x 8-token chunks, first token = "
                f"{max_new}-token generate"
            ),
            cold={
                "ttfrt_s": round(cold_ttfrt, 3),
                "status": cold_status,
                "stages_s": cold_stages,
            },
            promoted={
                "ttfrt_s": round(promoted_ttfrt, 3),
                "promote_status": promote_status,
                "status": promoted_status,
                "stages_s": promoted_stages,
            },
            peer_transfer={
                "ttfrt_s": round(transfer_ttfrt, 3),
                "transfer_s": round(transfer_s, 3),
                "bytes": int(total_bytes),
                "verified": bool(transfer_ok),
                "status": transfer_status,
                "stages_s": transfer_stages,
            },
            promoted_over_cold=round(
                promoted_ttfrt / max(cold_ttfrt, 1e-9), 4
            ),
        )

    asyncio.run(scenario())
    out["target"] = (
        "promoted TTFRT <= 0.25x cold TTFRT, every arm's first "
        "request 200, peer-transferred weights byte-identical"
    )
    out["meets_target"] = bool(
        out["promoted_over_cold"] <= 0.25
        and out["cold"]["status"] == 200
        and out["promoted"]["status"] == 200
        and out["promoted"]["promote_status"] == 200
        and out["peer_transfer"]["status"] == 200
        and out["peer_transfer"]["verified"]
    )
    return out


def migration_bench(max_new: int = 8) -> dict:
    """The drain-migration yardstick (``make bench-migrate``):
    next-turn latency for a multi-turn session whose first turn ran
    on a replica that then drains, across the three places turn 2
    can land:

    - **warm**: turn 2 back on the SAME replica (KV resident) — the
      ceiling migration is chasing.
    - **migrated**: the drainer pushes its cached prefixes to a
      survivor over the handoff wire (``migrate_sessions`` — the
      same bytes a real drain moves), then turn 2 lands on the
      survivor and reuses the adopted KV.
    - **re-prefill**: turn 2 lands on a replica that never saw the
      session — today's drain-as-eviction behavior, paying the full
      prefill again.

    Every server carries a synthetic ``prefill_floor_s`` standing in
    for the real prefill compute a production prompt costs (CPU-sized
    prompts prefill in microseconds, which would flatten the very
    difference this bench exists to measure); a KV-reuse hit skips
    the floor exactly as real reuse skips real prefill.
    ``meets_target`` pins the migrated arm strictly below the
    re-prefill baseline, near the warm ceiling, with bytes actually
    moved and zero counted fallbacks."""
    import asyncio
    import http.client
    import os
    import time as time_mod

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp

    from containerpilot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from containerpilot_tpu.workload.serve import InferenceServer

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=2, n_layers=2, d_ff=128,
        max_seq_len=256, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    floor_s = 0.25
    out: dict = {}

    async def scenario() -> None:
        loop = asyncio.get_event_loop()

        def request(port: int, method: str, path: str,
                    body: bytes = b"") -> tuple:
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=120
            )
            try:
                conn.request(
                    method, path, body or None,
                    {"Content-Type": "application/json"}
                    if body else {},
                )
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()

        async def generate(port: int, tokens: list) -> tuple:
            body = json.dumps(
                {"tokens": [tokens], "max_new_tokens": max_new}
            ).encode()
            t0 = time_mod.monotonic()
            status, payload = await loop.run_in_executor(
                None, request, port, "POST", "/v1/generate", body
            )
            elapsed = time_mod.monotonic() - t0
            gen = (
                json.loads(payload)["tokens"][0]
                if status == 200 else []
            )
            return status, gen, elapsed

        def server() -> InferenceServer:
            return InferenceServer(
                cfg, params, "127.0.0.1", 0, max_len=128,
                slots=2, slot_chunk=8, prefix_cache_entries=8,
                kv_spill_bytes=4 << 20, prefill_floor_s=floor_s,
            )

        drainer, survivor, fresh = server(), server(), server()
        for s in (drainer, survivor, fresh):
            await s.run()

        # compile-fairness warmup: run the SAME two-turn shape flow
        # on every server with a throwaway token family, so each arm's
        # timed request pays only its floor + decode, never a stray
        # first-shape XLA compile (the floor, not the compiler, is
        # what separates the arms)
        warm_row = [int(t) for t in range(60, 84)]
        for s in (drainer, survivor, fresh):
            st, gen, _ = await generate(s.port, warm_row)
            assert st == 200, f"warmup turn 1 failed: {st}"
            st, _, _ = await generate(
                s.port, warm_row + gen + [3, 5]
            )
            assert st == 200, f"warmup turn 2 failed: {st}"
            # re-issue turn 2: the prompt now FULLY matches the
            # longer stored key, compiling the rewind+extend-1
            # program the migrated arm's reuse hit takes (its adopted
            # keys include the drainer's completed turn-2 entry)
            st, _, _ = await generate(
                s.port, warm_row + gen + [3, 5]
            )
            assert st == 200, f"warmup turn 2 retry failed: {st}"
            # a COLD prompt at turn-2 length (distinct family, no
            # reuse possible): compiles the full-length prefill the
            # re-prefill arm takes, so that arm's number is floor +
            # decode, not floor + a stray XLA compile
            cold_probe = [
                int(t) for t in
                range(90, 90 + len(warm_row) + len(gen) + 2)
            ]
            st, _, _ = await generate(s.port, cold_probe)
            assert st == 200, f"warmup cold probe failed: {st}"

        # the measured session: turn 1 on the drainer (untimed —
        # every arm's story starts from the same resident KV)
        row1 = [int(t) for t in range(1, 25)]
        st1, gen1, _ = await generate(drainer.port, row1)
        row2 = row1 + gen1 + [9, 11]

        # -- arm 1: WARM (turn 2 back on the drainer, KV resident) --
        warm_status, _, warm_s = await generate(drainer.port, row2)

        # -- arm 2: MIGRATED (drain pushes KV, turn 2 on survivor) --
        t0 = time_mod.monotonic()
        summary = await drainer.migrate_sessions(
            [("survivor", "127.0.0.1", survivor.port, frozenset())],
            window_s=30.0,
            authority=f"127.0.0.1:{drainer.port}",
        )
        migrate_wire_s = time_mod.monotonic() - t0
        mig_status, _, migrated_s = await generate(
            survivor.port, row2
        )

        # -- arm 3: RE-PREFILL (turn 2 on a never-seen replica) ------
        base_status, _, baseline_s = await generate(fresh.port, row2)

        for s in (drainer, survivor, fresh):
            await s.stop()

        out.update(
            backend=jax.default_backend(),
            config=(
                f"{cfg.n_layers}L d{cfg.d_model} v{cfg.vocab_size}, "
                f"{len(row2)}-token turn-2 prompt, {max_new} new "
                f"tokens, prefill floor {floor_s}s"
            ),
            warm={
                "next_turn_s": round(warm_s, 3),
                "status": warm_status,
            },
            migrated={
                "next_turn_s": round(migrated_s, 3),
                "status": mig_status,
                "wire_s": round(migrate_wire_s, 3),
                "entries_moved": summary["done"],
                "bytes": summary["bytes"],
                "failed": summary["failed"],
                "timeout": summary["timeout"],
            },
            reprefill={
                "next_turn_s": round(baseline_s, 3),
                "status": base_status,
            },
            seed_status=st1,
            migrated_over_reprefill=round(
                migrated_s / max(baseline_s, 1e-9), 4
            ),
            migrated_over_warm=round(
                migrated_s / max(warm_s, 1e-9), 4
            ),
        )

    asyncio.run(scenario())
    out["target"] = (
        "migrated next-turn latency strictly below the re-prefill "
        "baseline and near the warm ceiling (<= max(2.5x warm, "
        "warm + 0.1s)), bytes moved > 0, zero failed/timed-out "
        "entries, every request 200"
    )
    out["meets_target"] = bool(
        out["seed_status"] == 200
        and out["warm"]["status"] == 200
        and out["migrated"]["status"] == 200
        and out["reprefill"]["status"] == 200
        and out["migrated"]["entries_moved"] >= 1
        and out["migrated"]["bytes"] > 0
        and out["migrated"]["failed"] == 0
        and out["migrated"]["timeout"] == 0
        and out["migrated"]["next_turn_s"]
        < out["reprefill"]["next_turn_s"]
        and out["migrated"]["next_turn_s"]
        <= max(
            2.5 * out["warm"]["next_turn_s"],
            out["warm"]["next_turn_s"] + 0.1,
        )
    )
    return out


def chaos_goodput_bench(seed: int = 0) -> dict:
    """The robustness trajectory: run the QUICK chaos scenarios (a
    real multi-replica fleet + gateway replaying a seeded trace while
    faults fire — replica SIGKILL, wedged health, catalog flap, slow
    replica, and the burst suite: a 10x overload shed by admission
    control, and a kill-under-burst the autoscaler scales through)
    and record each run's SLO-goodput, TTFT/TPOT percentiles, 5xx
    count, shed counts, scale events, and per-fault counts. Host-side
    and CPU-sized, so every bench round records real under-fire (and
    goodput-under-burst) numbers even TPU-less. ``meets_target`` is
    every scenario clearing its invariants (zero client-visible 5xx
    included — sheds are honest 429/504, counted separately) — the
    bar the ROADMAP's multiplexed-transport work will be judged
    against. See docs/80-chaos.md."""
    import logging as logging_mod
    import os
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    logging_mod.disable(logging_mod.CRITICAL)

    from containerpilot_tpu.chaos import quick_scenarios, run_scenario

    scenarios: dict = {}
    all_passed = True
    for name in quick_scenarios():
        with tempfile.TemporaryDirectory(prefix="chaos-bench-") as d:
            report = run_scenario(name, d, seed=seed)
        score = report["score"]
        scenarios[name] = {
            "passed": report["passed"],
            "requests": score["requests"],
            "goodput_rps": score["goodput_rps"],
            "goodput_fraction": score["goodput_fraction"],
            "goodput_fraction_admitted": (
                score["goodput_fraction_admitted"]
            ),
            "sheds": score["sheds"],
            "shed_429": score["shed_429"],
            "shed_504": score["shed_504"],
            "client_retries": score["client_retries"],
            "ttft_p50_ms": score["ttft_ms"]["p50"],
            "ttft_p99_ms": score["ttft_ms"]["p99"],
            "tpot_p95_ms": score["tpot_ms"]["p95"],
            "count_5xx": score["count_5xx"],
            "truncated_streams": score["truncated_streams"],
            # event-loop health (analysis/loopcheck.py): the named
            # form of "the loop hiccuped", tracked release-over-release
            "loop_lag_max_ms": report["loop_lag_max_ms"],
            "loop_task_exceptions": len(
                report["loop"]["task_exceptions"]
            ),
            # device-time ledger (telemetry/goodput.py): the badput
            # trajectory per scenario, tracked release-over-release
            "productive_fraction": (
                report["goodput_ledger"]["productive_fraction"]
            ),
            # per-role cut of the same ledger (disaggregated
            # scenarios split prefill/decode; mixed fleets report
            # one "active" pool) — tracked release-over-release
            "productive_fraction_by_role": {
                role: stats["productive_fraction"]
                for role, stats in report["goodput_ledger"]
                .get("per_role", {}).items()
            },
            "dispatches_per_token": (
                report["goodput_ledger"]["dispatches_per_token"]
            ),
            "scale_up_ttfrt_s": min(
                (
                    e["ttfrt_s"]
                    for e in report["goodput_ledger"]["scale_events"]
                    if e["direction"] == "up"
                    and e.get("ttfrt_s") is not None
                ),
                default=None,
            ),
            "retried": report["gateway"]["retried"],
            "hedged": report["gateway"]["hedged"],
            "catalog_flaps_damped": (
                report["gateway"]["catalog_flaps_damped"]
            ),
            "autoscaler": (
                {
                    "scale_ups": report["autoscaler"]["scale_ups"],
                    "scale_downs": report["autoscaler"]["scale_downs"],
                    "replicas_at_end": report["autoscaler"]["replicas"],
                }
                if report.get("autoscaler") else None
            ),
            "fault_counts": report["fault_counts"],
        }
        all_passed = all_passed and report["passed"]
    return {
        "backend": jax.default_backend(),
        "seed": seed,
        "scenarios": scenarios,
        # the bar: every quick scenario's invariants hold under fire
        "meets_target": all_passed,
    }


def prefix_reuse_bench(seeds: tuple = (0, 1, 2)) -> dict:
    """Fleet-wide KV reuse vs. the session-sticky baseline: replay
    the SAME multi-turn chat trace (growing shared-prefix
    conversations + a replica draining mid-conversation, from
    chaos/trace.py) through two fleets that differ only in routing —
    ``multiturn_rebalance`` (cache-contents-aware ``_pick`` + the
    host-RAM KV spill tier earning readmissions) and
    ``multiturn_sticky_baseline`` (cache_routing off: re-pins land by
    load, blind to where the KV lives). Records fleet-wide
    tokens_reused per prompt token (the ML-goodput yardstick for
    reuse) and shed-free TTFT p50 for both arms, POOLED over the
    seeds (each seed is a different conversation schedule; pooling
    keeps one lucky tie-break concentration from deciding the
    verdict). Every scenario runs in its OWN interpreter — exactly
    the ``python -m containerpilot_tpu.chaos --scenario`` regime the
    tier-1 tests gate on: a shared warm process would amortize every
    jit compile, collapse request latencies to the point where
    conversations never overlap, and hand the blind baseline an
    idle-fleet concentration the policies are not separable under.
    ``meets_target`` = the aware arm clears its strict invariants at
    every seed (zero 5xx, drain absorbed, hint hits, spill
    readmissions) AND reuses STRICTLY more prefix tokens per prompt
    token than the baseline — cache-aware routing must pay for
    itself on the workload it exists for. Host-side and CPU-sized;
    see docs/80-chaos.md."""
    import logging as logging_mod
    import os
    import subprocess
    import sys
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    logging_mod.disable(logging_mod.CRITICAL)

    def run_cold(name: str, seed: int) -> dict:
        with tempfile.TemporaryDirectory(prefix="reuse-bench-") as d:
            out = os.path.join(d, "report.json")
            proc = subprocess.run(
                [
                    sys.executable, "-m", "containerpilot_tpu.chaos",
                    "--scenario", name, "--seed", str(seed),
                    "--json", out,
                ],
                capture_output=True, text=True, timeout=240,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
            )
            try:
                with open(out, encoding="utf-8") as f:
                    return json.load(f)["scenarios"][0]
            except (OSError, ValueError, KeyError, IndexError):
                raise RuntimeError(
                    f"{name} seed {seed} produced no report "
                    f"(exit {proc.returncode}): {proc.stderr[-300:]!r}"
                ) from None

    arms: dict = {}
    for arm, name in (
        ("cache_aware", "multiturn_rebalance"),
        ("session_sticky", "multiturn_sticky_baseline"),
    ):
        runs = []
        for seed in seeds:
            report = run_cold(name, seed)
            score = report["score"]
            kv = report["kv"]
            runs.append({
                "seed": seed,
                "passed": report["passed"],
                "requests": score["requests"],
                "goodput_fraction": score["goodput_fraction"],
                # sheds carry no TTFT sample, so these are shed-free
                "ttft_p50_ms": score["ttft_ms"]["p50"],
                "ttft_p99_ms": score["ttft_ms"]["p99"],
                "count_5xx": score["count_5xx"],
                "tokens_reused": kv["tokens_reused"],
                "prompt_tokens": kv["prompt_tokens"],
                "tokens_reused_per_prompt_token": (
                    kv["tokens_reused_per_prompt_token"]
                ),
                "cache_hint_hits": kv["cache_hint_hits"],
                "cache_hint_misses": kv["cache_hint_misses"],
                "spilled": kv["spilled"],
                "readmitted": kv["readmitted"],
                "sticky_evicted": (
                    report["gateway"]["sticky"]["evicted"]
                ),
            })
        reused = sum(r["tokens_reused"] for r in runs)
        prompts = sum(r["prompt_tokens"] for r in runs)
        arms[arm] = {
            "scenario": name,
            "passed": all(r["passed"] for r in runs),
            "tokens_reused": reused,
            "prompt_tokens": prompts,
            "tokens_reused_per_prompt_token": round(
                reused / max(1, prompts), 4
            ),
            "ttft_p50_ms": round(
                sum(r["ttft_p50_ms"] for r in runs) / len(runs), 2
            ),
            "runs": runs,
        }
    aware = arms["cache_aware"]
    base = arms["session_sticky"]
    return {
        "backend": jax.default_backend(),
        "seeds": list(seeds),
        "arms": arms,
        "reuse_advantage_per_prompt_token": round(
            aware["tokens_reused_per_prompt_token"]
            - base["tokens_reused_per_prompt_token"], 4
        ),
        "ttft_p50_delta_ms": round(
            aware["ttft_p50_ms"] - base["ttft_p50_ms"], 2
        ),
        # the bar: the aware arm holds its invariants at every seed
        # AND reuses strictly more than blind session-sticky on the
        # same pooled traces
        "meets_target": bool(
            aware["passed"]
            and aware["tokens_reused_per_prompt_token"]
            > base["tokens_reused_per_prompt_token"]
        ),
    }


def disagg_bench(seeds: tuple = (0, 1)) -> dict:
    """Disaggregated prefill/decode vs the mixed fleet: replay the
    SAME multi-turn streaming trace (chaos/scenarios.py's
    ``_DISAGG_TRACE``, every cold prefill paying a synthetic
    admission floor that stands in for a production-sized prompt
    occupying the slot worker) through two fleets of the SAME size —
    ``disagg_mixed_baseline`` (3 mixed replicas; cold prefills block
    decode windows) and ``disagg_split`` (1 prefill + 2 decode
    replicas; fresh prompts prefill on the prefill pool and the KV
    prefix ships replica-to-replica over the cp-mux/1 handoff
    stream, readmitted through the same ``reuse_admission`` path a
    local spill takes). Each scenario runs in its OWN interpreter
    (the cold-process regime the tier-1 tests gate on, same as
    prefix_reuse_bench). ``meets_target`` = both arms clear their
    invariants at every seed AND the split arm's TPOT p99 (its
    streams all ride the decode pool) is STRICTLY under the mixed
    arm's AND every split seed completed handoffs with per-transfer
    wall ms recorded AND the decode pool's driven-window productive
    fraction (PR 12 ledger, per-role cut) is >= the mixed fleet's —
    phase specialization must buy tail decode latency without
    idling the pool it carved out. Host-side and CPU-sized; see
    docs/80-chaos.md."""
    import logging as logging_mod
    import os
    import subprocess
    import sys
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    logging_mod.disable(logging_mod.CRITICAL)

    def run_cold(name: str, seed: int) -> dict:
        with tempfile.TemporaryDirectory(prefix="disagg-bench-") as d:
            out = os.path.join(d, "report.json")
            proc = subprocess.run(
                [
                    sys.executable, "-m", "containerpilot_tpu.chaos",
                    "--scenario", name, "--seed", str(seed),
                    "--json", out,
                ],
                capture_output=True, text=True, timeout=240,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
            )
            try:
                with open(out, encoding="utf-8") as f:
                    return json.load(f)["scenarios"][0]
            except (OSError, ValueError, KeyError, IndexError):
                raise RuntimeError(
                    f"{name} seed {seed} produced no report "
                    f"(exit {proc.returncode}): {proc.stderr[-300:]!r}"
                ) from None

    arms: dict = {}
    for arm, name in (
        ("mixed", "disagg_mixed_baseline"),
        ("disagg", "disagg_split"),
    ):
        runs = []
        for seed in seeds:
            report = run_cold(name, seed)
            score = report["score"]
            handoff = report["gateway"]["handoff"]
            per_role = report["goodput_ledger"].get("per_role", {})
            runs.append({
                "seed": seed,
                "passed": report["passed"],
                "requests": score["requests"],
                "goodput_fraction": score["goodput_fraction"],
                "count_5xx": score["count_5xx"],
                "ttft_p50_ms": score["ttft_ms"]["p50"],
                "ttft_p99_ms": score["ttft_ms"]["p99"],
                # the headline: every stream in the split arm decodes
                # on the decode pool, so the arm's TPOT p99 IS the
                # decode pool's under concurrent cold-prefill pressure
                "tpot_p50_ms": score["tpot_ms"]["p50"],
                "tpot_p99_ms": score["tpot_ms"]["p99"],
                "handoffs": handoff["total"],
                "handoff_failed": handoff["failed"],
                "handoff_skipped_warm": handoff["skipped_warm"],
                "handoff_bytes": handoff["bytes"],
                "handoff_mean_ms": round(
                    handoff["ms_sum"] / handoff["total"], 2
                ) if handoff["total"] else None,
                "productive_fraction": (
                    report["goodput_ledger"]["productive_fraction"]
                ),
                "productive_fraction_by_role": {
                    role: stats["productive_fraction"]
                    for role, stats in per_role.items()
                },
                "tokens_reused": report["kv"]["tokens_reused"],
                "readmitted": report["kv"]["readmitted"],
            })
        arms[arm] = {
            "scenario": name,
            "passed": all(r["passed"] for r in runs),
            "tpot_p99_ms": round(
                sum(r["tpot_p99_ms"] for r in runs) / len(runs), 2
            ),
            "ttft_p99_ms": round(
                sum(r["ttft_p99_ms"] for r in runs) / len(runs), 2
            ),
            "runs": runs,
        }
    mixed = arms["mixed"]
    split = arms["disagg"]
    decode_pf = [
        r["productive_fraction_by_role"].get("decode")
        for r in split["runs"]
    ]
    mixed_pf = [r["productive_fraction"] for r in mixed["runs"]]
    split["decode_productive_fraction"] = round(
        sum(decode_pf) / len(decode_pf), 4
    ) if all(f is not None for f in decode_pf) else None
    mixed["productive_fraction"] = round(
        sum(mixed_pf) / len(mixed_pf), 4
    )
    handoffs_every_seed = all(
        r["handoffs"] >= 1 and r["handoff_mean_ms"] is not None
        for r in split["runs"]
    )
    return {
        "backend": jax.default_backend(),
        "seeds": list(seeds),
        "arms": arms,
        "tpot_p99_advantage_ms": round(
            mixed["tpot_p99_ms"] - split["tpot_p99_ms"], 2
        ),
        # the handoff tax, stated next to the win it buys
        "ttft_p99_cost_ms": round(
            split["ttft_p99_ms"] - mixed["ttft_p99_ms"], 2
        ),
        # the bar: both arms hold their invariants at every seed,
        # the decode pool's tail beats the mixed fleet's STRICTLY,
        # KV actually moved (with its cost on the ledger), and the
        # carved-out decode pool out-produces the mixed fleet
        "meets_target": bool(
            mixed["passed"] and split["passed"]
            and split["tpot_p99_ms"] < mixed["tpot_p99_ms"]
            and handoffs_every_seed
            and split["decode_productive_fraction"] is not None
            and split["decode_productive_fraction"]
            >= mixed["productive_fraction"]
        ),
    }


def _bench_subprocess(fn_name: str, timeout_s: int,
                      env: dict | None = None) -> dict:
    """Run one workload bench in its own interpreter with a hard
    timeout: TPU-tunnel wedges and compile-helper crashes then cost a
    bounded slice of the bench budget instead of hanging it, and a
    crashed backend can't poison the next bench. ``env`` overlays the
    inherited environment (the host-overhead bench pins
    JAX_PLATFORMS=cpu when no TPU answers)."""
    import os
    import subprocess
    import sys

    code = (
        "import json, logging, bench; "
        "logging.disable(logging.CRITICAL); "
        f"print('BENCH_RESULT ' + json.dumps(bench.{fn_name}()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=dict(os.environ, **env) if env else None,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s}s"}
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    return {
        "error": f"exit {proc.returncode}: {proc.stderr[-200:]!r}"
    }


def _probe_backend_once(timeout_s: int = 180) -> str:
    """Identify the backend from a THROWAWAY process: the first device
    touch goes through the TPU tunnel and can hang when the tunnel is
    unhealthy — that must never block the dispatch metric."""
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('BACKEND', jax.default_backend()); "
             "import jax.numpy as jnp; "
             "print('OK', float((jnp.ones((8,8)) @ jnp.ones((8,8)))[0,0]))"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return "unreachable"
    except Exception:  # pragma: no cover
        return "unavailable"
    backend = ""
    for line in proc.stdout.splitlines():
        if line.startswith("BACKEND "):
            backend = line.split(None, 1)[1].strip()
    if "OK" not in proc.stdout:
        return "unreachable"
    return backend or "unavailable"


def _probe_backend(attempts: int = 4, timeout_s: int = 180) -> str:
    """Probe with retries + backoff. The axon tunnel wedges
    transiently; round 2's single-attempt probe hit one bad moment and
    zeroed out the entire round's workload evidence. A real tpu that is
    merely slow to wake must not be reported as absent."""
    backoff = 10.0
    last = "unavailable"
    for i in range(attempts):
        last = _probe_backend_once(timeout_s)
        if last not in ("unreachable", "unavailable"):
            return last
        if i + 1 < attempts:
            time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)
    return last


def workload_benches() -> dict:
    backend = _probe_backend()
    extras: dict = {}
    # the host-overhead bench runs on ANY backend (tiny CPU-sized
    # config): even a TPU-less round records a real serving-loop
    # number in BENCH_r{N}.json instead of only {"skipped": ...}
    extras["host_overhead"] = _bench_subprocess(
        "host_overhead_bench", 900,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # the fleet gateway's added per-request latency is a host-side
    # number too: measure it on every backend
    extras["gateway_overhead"] = _bench_subprocess(
        "gateway_overhead_bench", 600,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # device-time ledger accounting + dispatches/token trajectory
    # (the badput decomposition the goodput framing is built on)
    extras["goodput_ledger"] = _bench_subprocess(
        "goodput_ledger_bench", 600,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # robustness trajectory: quick chaos scenarios' SLO-goodput under
    # injected faults, recorded every round (BENCH_r06+)
    extras["chaos_goodput"] = _bench_subprocess(
        "chaos_goodput_bench", 900,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # KV-reuse trajectory: cache-aware routing + host-RAM spill tier
    # vs the session-sticky baseline on the multi-turn chat trace
    # (6 cold scenario subprocesses: 2 arms x 3 seeds)
    extras["prefix_reuse"] = _bench_subprocess(
        "prefix_reuse_bench", 900,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # cold-start collapse trajectory: cold vs promoted vs
    # peer-transfer TTFRT with per-stage ledger attribution — the
    # number the warm-standby pool exists to drive down
    extras["cold_start"] = _bench_subprocess(
        "cold_start_bench", 600,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # disaggregation trajectory: decode-pool TPOT p99 + handoff cost
    # vs the same-size mixed fleet (4 cold scenario subprocesses:
    # 2 arms x 2 seeds)
    extras["disagg"] = _bench_subprocess(
        "disagg_bench", 900,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    # drain-migration trajectory: migrated next-turn latency vs the
    # warm ceiling and the re-prefill floor-paying baseline — the
    # number live session migration exists to drive down
    extras["migration"] = _bench_subprocess(
        "migration_bench", 600,
        env=None if backend == "tpu" else {"JAX_PLATFORMS": "cpu"},
    )
    if backend != "tpu":
        extras["skipped"] = (
            f"backend is {backend}, not a reachable tpu "
            "(host_overhead/gateway_overhead above ran on cpu)"
        )
        return extras
    for name, fn_name, timeout_s in (
        ("attention", "attention_bench", 900),
        ("int8_gemm", "int8_bench", 600),
        # three remat variants = three compiles; budget accordingly
        ("training", "training_bench", 2700),
        # decode timed out at 900s on the first real-chip run even
        # after the admission split (a 1.2B init + two generate
        # compiles over a flaky tunnel); budget generously — the
        # watcher's outer timeout still covers the sum plus one
        # in-bench retry of the largest entry
        ("decode", "decode_bench", 1500),
        ("slot_admission", "slot_admission_bench", 1200),
    ):
        result = _bench_subprocess(fn_name, timeout_s)
        if "error" in result:
            # A wedged tunnel fails one bench without poisoning the
            # rest (each runs in its own process); re-probe until the
            # backend answers again, then retry this bench ONCE.
            if _probe_backend(attempts=3) == "tpu":
                retried = _bench_subprocess(fn_name, timeout_s)
                if "error" not in retried:
                    retried["retried"] = True
                    result = retried
                else:
                    result["retry_error"] = retried["error"]
        extras[name] = result
    return extras


async def main() -> None:
    # silence the supervisor's logging for the timed cycles — set here
    # (not at import) so importing bench for tests has no global
    # side effect on the host process's logging
    logging.disable(logging.CRITICAL)
    median = await dispatch_bench()
    extras = workload_benches()
    print(
        json.dumps(
            {
                "metric": "supervisor_job_dispatch_latency_p50",
                "value": round(median, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / median, 2),
                "extras": extras,
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(main())
