"""One-shot CLI verbs (reference: subcommands/ package).

Each handler loads the config only to find the control socket, then
calls the client (reference: subcommands/subcommands.go:118-128).
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional

from ..client import ControlClient, ControlClientError
from ..config.loader import ConfigError, load_config, render_config_template
from ..version import GIT_HASH, VERSION


class SubcommandError(RuntimeError):
    pass


def _client_for(config_path: Optional[str]) -> ControlClient:
    cfg = load_config(config_path)
    return ControlClient(cfg.control.socket)


def _parse_kv(pairs: List[str], flag: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in pairs:
        key, sep, value = pair.partition("=")
        if not sep or not key:
            raise SubcommandError(f"-{flag} requires 'key=value' format: {pair!r}")
        out[key] = value
    return out


def version_handler(_params: dict) -> int:
    print(f"Version: {VERSION}\nGitHash: {GIT_HASH}")
    return 0


def render_handler(params: dict) -> int:
    """-template [-out path] (reference: subcommands.go:37-56)."""
    try:
        rendered = render_config_template(params["config_path"])
    except (OSError, ConfigError, ValueError) as exc:
        print(f"error rendering template: {exc}", file=sys.stderr)
        return 1
    out = params.get("render_flag") or "-"
    if out == "-":
        sys.stdout.write(rendered)
    else:
        with open(out, "w", encoding="utf-8") as f:
            f.write(rendered)
    return 0


def reload_handler(params: dict) -> int:
    try:
        _client_for(params.get("config_path")).reload()
        return 0
    except (ConfigError, ControlClientError) as exc:
        print(f"reload failed: {exc}", file=sys.stderr)
        return 1


def maintenance_handler(params: dict) -> int:
    flag = params.get("maintenance_flag", "")
    if flag not in ("enable", "disable"):
        print(
            "-maintenance accepts 'enable' or 'disable'", file=sys.stderr
        )
        return 1
    try:
        _client_for(params.get("config_path")).set_maintenance(flag == "enable")
        return 0
    except (ConfigError, ControlClientError) as exc:
        print(f"maintenance failed: {exc}", file=sys.stderr)
        return 1


def put_env_handler(params: dict) -> int:
    try:
        env = _parse_kv(params.get("env", []), "putenv")
        _client_for(params.get("config_path")).put_env(env)
        return 0
    except (ConfigError, ControlClientError, SubcommandError) as exc:
        print(f"putenv failed: {exc}", file=sys.stderr)
        return 1


def put_metrics_handler(params: dict) -> int:
    try:
        metrics = _parse_kv(params.get("metrics", []), "putmetric")
        _client_for(params.get("config_path")).put_metric(metrics)
        return 0
    except (ConfigError, ControlClientError, SubcommandError) as exc:
        print(f"putmetric failed: {exc}", file=sys.stderr)
        return 1


def catalog_server_handler(params: dict) -> int:
    """Run the Consul-compatible catalog daemon until SIGTERM/SIGINT."""
    import asyncio
    import signal as signal_mod

    from ..discovery.catalog_server import CatalogServer

    addr = params.get("catalog_addr", "0.0.0.0:8500")
    host, _, port_str = addr.rpartition(":")
    host = host or "0.0.0.0"
    try:
        port = int(port_str)
    except ValueError:
        print(f"-catalog-server expects HOST:PORT, got {addr!r}",
              file=sys.stderr)
        return 1

    async def serve() -> None:
        server = CatalogServer(
            host, port, snapshot_path=params.get("catalog_snapshot", "")
        )
        await server.run()
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    asyncio.run(serve())
    return 0


def ping_handler(params: dict) -> int:
    try:
        _client_for(params.get("config_path")).get_ping()
        print("ok")
        return 0
    except (ConfigError, ControlClientError) as exc:
        print(f"ping failed: {exc}", file=sys.stderr)
        return 1
