"""Minimal asyncio load-driver client for the chaos harness.

The workload generator needs things stdlib HTTP clients make awkward:
TTFT measured at the first response byte, SSE event accounting, and
deliberately hanging up mid-stream (the abandoned-client fault). This
client speaks just enough HTTP/1.1 for the gateway's two response
shapes (Content-Length-framed JSON and close-delimited SSE) and
records a ``RequestRecord`` per call.

One connection per request, by design: each trace request models an
independent end client, so gateway-side keep-alive pooling (replica
side) is exercised while the client side stays adversarially churny.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, Optional, Tuple

from .slo import RequestRecord
from .trace import TraceRequest

#: generous cap on any single request; scenario wall time is bounded
#: by the runner, this just keeps a wedged read from pinning the run
REQUEST_TIMEOUT_S = 60.0


async def _read_head(
    reader: asyncio.StreamReader,
) -> Tuple[int, Dict[str, str]]:
    blob = await reader.readuntil(b"\r\n\r\n")
    lines = blob.split(b"\r\n")
    parts = lines[0].decode("latin-1").split(None, 2)
    status = int(parts[1])
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        key, _, value = line.decode("latin-1").partition(":")
        headers[key.strip().lower()] = value.strip()
    return status, headers


def _count_tokens(payload: Dict[str, Any]) -> int:
    rows = payload.get("tokens")
    if not isinstance(rows, list):
        return 0
    return sum(len(r) for r in rows if isinstance(r, list))


async def issue_request(
    port: int,
    req: TraceRequest,
    clock_zero: float,
    host: str = "127.0.0.1",
    path: str = "/v1/generate",
) -> RequestRecord:
    """Issue one trace request against the gateway and record the
    outcome. Never raises: transport failures land in ``error`` so the
    scorer can count them (a chaos run WANTS to observe failures)."""
    record = RequestRecord(
        index=req.index,
        session_id=req.session_id,
        started_s=time.monotonic() - clock_zero,
        finished_s=0.0,
        stream=req.stream,
    )
    writer: Optional[asyncio.StreamWriter] = None
    try:
        record_body = json.dumps(req.payload()).encode()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), REQUEST_TIMEOUT_S
        )
        head = (
            f"POST {path} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(record_body)}\r\n"
            f"Connection: close\r\n\r\n"
        )
        writer.write(head.encode() + record_body)
        await writer.drain()
        status, headers = await asyncio.wait_for(
            _read_head(reader), REQUEST_TIMEOUT_S
        )
        record.status = status
        if "text/event-stream" in headers.get("content-type", ""):
            await _consume_stream(reader, req, record, clock_zero)
        else:
            length = int(headers.get("content-length", "0") or "0")
            body = await asyncio.wait_for(
                reader.readexactly(length) if length else reader.read(),
                REQUEST_TIMEOUT_S,
            )
            # buffered TTFT: the whole response IS the first token's
            # arrival (the replica decodes before writing anything)
            record.ttft_s = (
                time.monotonic() - clock_zero
            ) - record.started_s
            if status == 200:
                try:
                    record.tokens_out = _count_tokens(json.loads(body))
                except ValueError:
                    record.error = "unparseable 200 body"
    except (OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError, ValueError) as exc:
        record.error = f"{type(exc).__name__}: {exc}"
    finally:
        if writer is not None:
            writer.close()
    record.finished_s = time.monotonic() - clock_zero
    return record


async def _consume_stream(
    reader: asyncio.StreamReader,
    req: TraceRequest,
    record: RequestRecord,
    clock_zero: float,
) -> None:
    """Read SSE events, marking TTFT at the first data event, hanging
    up after ``abandon_after_events`` when the trace says so, and
    flagging truncation when the stream ends without its terminal
    ``done`` event."""
    events = 0
    saw_done = False
    buffer = b""
    while True:
        chunk = await asyncio.wait_for(
            reader.read(65536), REQUEST_TIMEOUT_S
        )
        if not chunk:
            break
        buffer += chunk
        while b"\n\n" in buffer:
            raw, buffer = buffer.split(b"\n\n", 1)
            if not raw.startswith(b"data: "):
                continue
            try:
                event = json.loads(raw[len(b"data: "):])
            except ValueError:
                continue
            events += 1
            if record.ttft_s is None:
                record.ttft_s = (
                    time.monotonic() - clock_zero
                ) - record.started_s
            if event.get("done"):
                saw_done = True
            else:
                record.tokens_out += len(event.get("tokens") or [])
        if saw_done:
            return
        if (
            req.abandon_after_events is not None
            and events >= req.abandon_after_events
        ):
            record.abandoned = True
            return
    record.truncated = not saw_done
