"""Minimal asyncio load-driver client for the chaos harness.

The workload generator needs things stdlib HTTP clients make awkward:
TTFT measured at the first response byte, SSE event accounting, and
deliberately hanging up mid-stream (the abandoned-client fault). This
client speaks just enough HTTP/1.1 for the gateway's two response
shapes (Content-Length-framed JSON and close-delimited SSE) and
records a ``RequestRecord`` per call.

One connection per request, by design: each trace request models an
independent end client, so gateway-side keep-alive pooling (replica
side) is exercised while the client side stays adversarially churny.

Well-behaved clients honor ``Retry-After``: a 429 (admission shed) or
a maintenance 503 that carries one is retried after that delay times
an equal-jitter factor seeded per request — thousands of clients shed
in the same burst instant must NOT re-arrive in the same instant, or
the retry storm re-creates the spike shedding just absorbed (the
client-side mirror of the gateway's jittered retry backoff). A final
429/504 with Retry-After is recorded as a **shed**: honest overload
refusal the SLO scorer counts apart from failures. A retried 503
still sets ``saw_5xx`` — politeness must not hide a 5xx from the
zero-5xx invariants.
"""
from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Any, Dict, Optional, Tuple

from ..telemetry.tracing import stage_totals
from .slo import RequestRecord
from .trace import TraceRequest

#: generous cap on any single request; scenario wall time is bounded
#: by the runner, this just keeps a wedged read from pinning the run
REQUEST_TIMEOUT_S = 60.0
#: Retry-After honor policy: how many times a polite client re-sends
#: a shed/maintenance answer, and the longest single wait it accepts
MAX_RETRY_AFTER_RETRIES = 2
MAX_RETRY_AFTER_WAIT_S = 5.0
#: statuses worth re-sending when the server quoted a Retry-After:
#: 429 is an admission shed, 503 a draining/overloaded hop. 504 is
#: NEVER retried — the request's deadline already passed.
RETRYABLE_WITH_HINT = frozenset({429, 503})
SHED_STATUSES = frozenset({429, 504})


async def _read_head(
    reader: asyncio.StreamReader,
) -> Tuple[int, Dict[str, str]]:
    blob = await reader.readuntil(b"\r\n\r\n")
    lines = blob.split(b"\r\n")
    parts = lines[0].decode("latin-1").split(None, 2)
    status = int(parts[1])
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        key, _, value = line.decode("latin-1").partition(":")
        headers[key.strip().lower()] = value.strip()
    return status, headers


def _count_tokens(payload: Dict[str, Any]) -> int:
    rows = payload.get("tokens")
    if not isinstance(rows, list):
        return 0
    return sum(len(r) for r in rows if isinstance(r, list))


def _retry_after_s(headers: Dict[str, str]) -> Optional[float]:
    raw = headers.get("retry-after", "")
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value >= 0 else None


async def issue_request(
    port: int,
    req: TraceRequest,
    clock_zero: float,
    host: str = "127.0.0.1",
    path: str = "/v1/generate",
) -> RequestRecord:
    """Issue one trace request against the gateway and record the
    outcome, honoring Retry-After on shed/maintenance answers. Never
    raises: transport failures land in ``error`` so the scorer can
    count them (a chaos run WANTS to observe failures). TTFT runs
    from the FIRST attempt — a retried shed that eventually succeeds
    is only good if the whole dance met the SLO."""
    record = RequestRecord(
        index=req.index,
        session_id=req.session_id,
        started_s=time.monotonic() - clock_zero,
        finished_s=0.0,
        stream=req.stream,
    )
    # per-request jitter stream: seeded so runs replay, distinct per
    # request so a burst's shed victims desynchronize
    rng = random.Random(req.seed * 2654435761 % (2**31) ^ 0x5EED)
    attempts = 0
    retry_wait_s = 0.0
    while True:
        headers = await _attempt(port, req, clock_zero, record, host, path)
        attempts += 1
        if (
            not record.error
            and 500 <= record.status <= 599
            and record.status != 504
        ):
            # a non-shed 5xx was SEEN, even if a polite retry later
            # lands a 200 — zero-5xx invariants must still count it
            record.saw_5xx = True
        if (
            record.error
            or record.status not in RETRYABLE_WITH_HINT
            or attempts > MAX_RETRY_AFTER_RETRIES
        ):
            break
        hint = _retry_after_s(headers)
        if hint is None:
            break
        record.client_retries += 1
        # equal jitter: [hint/2, hint] — the mean backs off with the
        # server's estimate, the spread kills the synchronized wave
        delay = min(hint, MAX_RETRY_AFTER_WAIT_S)
        jittered = delay * (0.5 + 0.5 * rng.random())
        retry_wait_s += jittered
        await asyncio.sleep(jittered)
        # a retry is a fresh exchange; only TTFT's zero point persists
        record.ttft_s = None
        record.tokens_out = 0
        record.truncated = False
    # a transport failure on the LAST attempt leaves the prior
    # answer's status/header flags behind — an errored exchange is
    # never an honest shed
    if (
        record.status in SHED_STATUSES
        and record.retry_after_quoted
        and not record.error
    ):
        record.shed = True
    if retry_wait_s > 0.0:
        # Retry-After parking is admission-imposed wait exactly like
        # gateway queue time — the client was told to stand off
        # because no dispatch capacity existed. Folding it into the
        # same stage keeps TTFT attribution honest: a request whose
        # SLO died in the shed-retry dance blames admission, not the
        # replica that eventually served it in milliseconds.
        record.stages["admission_queue_wait"] = (
            record.stages.get("admission_queue_wait", 0.0) + retry_wait_s
        )
    record.finished_s = time.monotonic() - clock_zero
    return record


async def _attempt(
    port: int,
    req: TraceRequest,
    clock_zero: float,
    record: RequestRecord,
    host: str,
    path: str,
) -> Dict[str, str]:
    """One wire exchange; mutates ``record`` and returns the response
    headers (empty on transport failure)."""
    # the record reflects the FINAL exchange: a retry that dies on
    # the wire must not inherit the prior attempt's status/header
    # flags (saw_5xx, set by the caller, is the cumulative memory)
    record.status = 0
    record.retry_after_quoted = False
    record.trace_id = ""
    record.stages = {}
    writer: Optional[asyncio.StreamWriter] = None
    headers: Dict[str, str] = {}
    try:
        record_body = json.dumps(req.payload()).encode()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), REQUEST_TIMEOUT_S
        )
        priority_header = (
            f"X-Priority: {req.priority}\r\n"
            if req.priority != "interactive" else ""
        )
        head = (
            f"POST {path} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(record_body)}\r\n"
            f"{priority_header}"
            f"Connection: close\r\n\r\n"
        )
        writer.write(head.encode() + record_body)
        await writer.drain()
        status, headers = await asyncio.wait_for(
            _read_head(reader), REQUEST_TIMEOUT_S
        )
        record.status = status
        record.retry_after_quoted = "retry-after" in headers
        # request identity + stage breakdown: every gateway answer —
        # sheds and 504s included — carries its trace id, and most
        # carry the span digest the triage ledger decomposes TTFT by
        record.trace_id = headers.get("x-cp-trace", "")
        record.stages = stage_totals(
            headers.get("x-cp-span-digest", "")
        )
        if "text/event-stream" in headers.get("content-type", ""):
            await _consume_stream(reader, req, record, clock_zero)
        else:
            length = int(headers.get("content-length", "0") or "0")
            body = await asyncio.wait_for(
                reader.readexactly(length) if length else reader.read(),
                REQUEST_TIMEOUT_S,
            )
            # buffered TTFT: the whole response IS the first token's
            # arrival (the replica decodes before writing anything)
            record.ttft_s = (
                time.monotonic() - clock_zero
            ) - record.started_s
            if status == 200:
                try:
                    record.tokens_out = _count_tokens(json.loads(body))
                except ValueError:
                    record.error = "unparseable 200 body"
    except (OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError, ValueError) as exc:
        record.error = f"{type(exc).__name__}: {exc}"
    finally:
        if writer is not None:
            writer.close()
    return headers


async def _consume_stream(
    reader: asyncio.StreamReader,
    req: TraceRequest,
    record: RequestRecord,
    clock_zero: float,
) -> None:
    """Read SSE events, marking TTFT at the first data event, hanging
    up after ``abandon_after_events`` when the trace says so, and
    flagging truncation when the stream ends without its terminal
    ``done`` event."""
    events = 0
    saw_done = False
    buffer = b""
    while True:
        chunk = await asyncio.wait_for(
            reader.read(65536), REQUEST_TIMEOUT_S
        )
        if not chunk:
            break
        buffer += chunk
        while b"\n\n" in buffer:
            raw, buffer = buffer.split(b"\n\n", 1)
            if not raw.startswith(b"data: "):
                continue
            try:
                event = json.loads(raw[len(b"data: "):])
            except ValueError:
                continue
            events += 1
            if record.ttft_s is None:
                record.ttft_s = (
                    time.monotonic() - clock_zero
                ) - record.started_s
            if event.get("done"):
                saw_done = True
                spans = event.get("spans")
                if isinstance(spans, str):
                    # the stream's digest channel: the replica ships
                    # its spans in the terminal frame (headers are
                    # long gone); merge them under the same prefix
                    # the gateway's stitcher uses
                    for stage, dur in stage_totals(spans).items():
                        key = "replica." + stage
                        record.stages[key] = (
                            record.stages.get(key, 0.0) + dur
                        )
            else:
                record.tokens_out += len(event.get("tokens") or [])
        if saw_done:
            return
        if (
            req.abandon_after_events is not None
            and events >= req.abandon_after_events
        ):
            record.abandoned = True
            return
    record.truncated = not saw_done
