"""Chaos scenarios: a real fleet, a real trace, scheduled faults, and
an SLO-goodput verdict.

The harness boots the ``make fleet-smoke`` topology for real — N
in-process ``InferenceServer`` replicas (slot engine enabled, so SSE
streaming and cancellation work), one ``FleetMember`` each
heartbeating a ``FileCatalogBackend``, and a ``FleetGateway`` polling
that catalog (through a ``FlakyBackend`` so catalog flaps can be
injected). The trace replays through the gateway exactly as an
external client fleet would: one connection per request, sessions
sticky, streams abandoned mid-flight when the trace says so.

A scenario is declarative: a trace config, a fault schedule, gateway
knobs, an SLO, and the invariant thresholds the run must clear
(``max_5xx`` is 0 for every scenario that models survivable faults —
the whole point of drains, retries, hedging, and hold-downs is that
members dying is not the client's problem). ``run_scenario`` returns a
JSON-able report with the goodput score, per-fault ledger, gateway
counters, and pass/fail per check; the CLI and the tier-1 tests both
consume it.

Determinism: the trace, the fault schedule, per-request seeds, and the
gateway's retry jitter all derive from the scenario seed. Wall-clock
measurements (TTFT/TPOT) naturally vary run to run; WHICH requests
arrive, WHAT they ask, and WHEN faults fire do not.
"""
from __future__ import annotations

import asyncio
import dataclasses
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.loopcheck import LoopLagProbe, TaskWatchdog
from ..telemetry import goodput as goodput_mod
from .client import issue_request
from .faults import ChaosProxy, Fault, FlakyBackend
from .slo import SLO, RequestRecord, ScenarioScore
from .trace import TraceConfig, TraceRequest, generate_trace, trace_summary

SERVICE = "inference"


def _counter_total(counter) -> float:
    """Sum a labeled prometheus counter across its label values."""
    total = 0.0
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                total += sample.value
    return total


def _counter_by_label(counter, label: str) -> Dict[str, float]:
    """Per-label-value totals of a labeled prometheus counter."""
    out: Dict[str, float] = {}
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                out[sample.labels.get(label, "")] = sample.value
    return out


class _HarnessLauncher:
    """The autoscaler's launcher, backed by the harness: launch =
    spawn an in-process replica + member (a production launcher
    submits a supervisor job instead — same duck type), retire =
    PR 3's drain path then stop. ``count``/``ids`` reflect what the
    harness believes alive AND active — standbys are parked capacity,
    not managed count — and catalog flaps can't shrink it, which is
    half the no-thrash story. The standby verbs (``launch_standby``/
    ``promote``) are the inner half of fleet/standby.StandbyLauncher;
    a production launcher would submit a ``--standby`` job and POST
    ``/v3/standby/promote`` at the replica instead."""

    def __init__(self, harness: "FleetHarness") -> None:
        self.harness = harness

    def ids(self) -> List[str]:
        h = self.harness
        return [
            f"replica-{i}"
            for i in range(len(h.servers))
            if i not in h.killed and i not in h.retired
            and h.roles.get(i, "active") == "active"
        ]

    def count(self) -> int:
        return len(self.ids())

    async def launch(self) -> str:
        return await self.harness.spawn_replica()

    async def launch_standby(self) -> str:
        return await self.harness.spawn_replica(role="standby")

    async def promote(self, replica_id: str) -> bool:
        return await self.harness.promote_replica(replica_id)

    async def retire(self, replica_id: str) -> None:
        await self.harness.retire_replica(replica_id)


class _PoolLauncher(_HarnessLauncher):
    """One phase pool's launcher for a disaggregated fleet: the same
    harness duck type scoped to replicas of ONE role, so a prefill
    autoscaler and a decode autoscaler can size their pools
    independently off ``gateway.pool_load(role)`` without either
    counting (or launching into) the other's capacity."""

    def __init__(self, harness: "FleetHarness", role: str) -> None:
        super().__init__(harness)
        self.role = role

    def ids(self) -> List[str]:
        h = self.harness
        return [
            f"replica-{i}"
            for i in range(len(h.servers))
            if i not in h.killed and i not in h.retired
            and h.roles.get(i) == self.role
        ]

    async def launch(self) -> str:
        return await self.harness.spawn_replica(role=self.role)


class FleetHarness:
    """A live multi-replica fleet the fault verbs operate on."""

    def __init__(
        self,
        catalog_dir: str,
        replicas: int = 2,
        *,
        ttl: int = 1,
        heartbeat_interval: float = 0.1,
        use_proxies: bool = False,
        gateway_kwargs: Optional[Dict[str, Any]] = None,
        autoscaler_kwargs: Optional[Dict[str, Any]] = None,
        server_kwargs: Optional[Dict[str, Any]] = None,
        standby_count: int = 0,
        roles: Tuple[str, ...] = (),
        pool_autoscaler_kwargs: Optional[
            Dict[str, Dict[str, Any]]
        ] = None,
    ) -> None:
        self.catalog_dir = catalog_dir
        self.n_replicas = replicas
        self.ttl = ttl
        self.heartbeat_interval = heartbeat_interval
        self.use_proxies = use_proxies
        self.gateway_kwargs = dict(gateway_kwargs or {})
        # extra InferenceServer knobs (e.g. prefix_cache_entries +
        # kv_spill_bytes for the KV-reuse scenarios)
        self.server_kwargs = dict(server_kwargs or {})
        self.autoscaler_kwargs = (
            dict(autoscaler_kwargs)
            if autoscaler_kwargs is not None else None
        )
        # warm-standby pool size (fleet/standby.py): boots after the
        # active fleet converges, promoted by the autoscaler's
        # launch path — requires autoscaler_kwargs
        self.standby_count = standby_count
        # disaggregated boot roles: replica i boots with
        # init_roles[i] ("prefill"/"decode"), or "active" (mixed)
        # past the tuple's end — the serve --role flag's in-process
        # twin, carried to the catalog by the same heartbeat note
        self.init_roles = tuple(roles)
        # role -> AutoscalerConfig kwargs: one INDEPENDENT autoscaler
        # per phase pool, signalled by gateway.pool_load(role) —
        # prefill sizes on admission-queue/TTFT pressure, decode on
        # slot occupancy (docs/60 § pool sizing)
        self.pool_autoscaler_kwargs = dict(pool_autoscaler_kwargs or {})
        self.pool_autoscalers: Dict[str, Any] = {}
        self.servers: List[Any] = []
        self.members: List[Any] = []
        self.proxies: List[Optional[ChaosProxy]] = []
        #: replica index -> role; promotion flips it active
        self.roles: Dict[int, str] = {}
        self.backend = None  # members' (real) catalog view
        self.flaky: Optional[FlakyBackend] = None  # the gateway's view
        self.gateway = None
        self.autoscaler = None
        self.standby_launcher = None
        self.killed: set = set()
        self.retired: set = set()
        #: slow_boot fault state: replicas spawned while this is > 0
        #: take the extra seconds in warmup (chaos_hook seam)
        self.slow_boot_s = 0.0
        self.fault_log: List[Dict[str, Any]] = []
        self._model = None  # (cfg, params), built once at start

    # -- lifecycle ---------------------------------------------------

    async def spawn_replica(self, role: str = "active") -> str:
        """Boot one replica (server + member, proxy when enabled) and
        register it; the autoscaler's launch verb, the standby
        refill, and the boot loop share this path. The in-process jit
        factories are lru-cached per config, so a mid-trace launch
        warms in milliseconds, not compile-seconds — UNLESS the
        ``slow_boot`` fault is armed, in which case warmup parks for
        the injected seconds (the production cold-start shape). A
        launch that dies mid-boot tears down what it started and
        re-raises, so the autoscaler's launch-failure path counts it
        instead of leaking a half-born replica."""
        from ..fleet import FleetMember
        from ..workload.serve import InferenceServer

        cfg, params = self._model
        # the index is claimed SYNCHRONOUSLY, before any await: a
        # background standby refill racing a cold launch must mint
        # two distinct replica ids, never two replica-N twins
        # heartbeating one catalog record
        index = len(self.servers)
        self.servers.append(None)
        self.members.append(None)
        self.proxies.append(None)
        self.roles[index] = role
        server = InferenceServer(
            cfg, params, "127.0.0.1", 0, max_len=64,
            slots=2, slot_chunk=4, role=role, **self.server_kwargs,
        )
        if self.slow_boot_s > 0:
            delay = self.slow_boot_s

            async def boot_hook(endpoint: str, _d=delay) -> None:
                if endpoint == "warmup":
                    await asyncio.sleep(_d)

            server.chaos_hook = boot_hook
        proxy: Optional[ChaosProxy] = None
        member = None
        try:
            await server.run()
            advertise = None
            if self.use_proxies:
                proxy = ChaosProxy("127.0.0.1", server.port)
                await proxy.start()
                advertise = proxy.port
            member = FleetMember(
                server, self.backend, SERVICE, ttl=self.ttl,
                heartbeat_interval=self.heartbeat_interval,
                instance_id=f"replica-{index}",
                advertise_port=advertise,
            )
            await member.start()
        except BaseException:
            # died during boot/warmup: release what was claimed so
            # the failure is a clean raise that frees its managed
            # slot (the autoscaler counts it as launch_failed), not
            # a leaked listener or a half-born catalog record
            self.killed.add(index)
            if member is not None:
                await member.stop(deregister=True)
            if proxy is not None:
                await proxy.stop()
            await server.stop()
            raise
        self.servers[index] = server
        self.members[index] = member
        self.proxies[index] = proxy
        return f"replica-{index}"

    async def promote_replica(self, replica_id: str) -> bool:
        """Flip one standby active (the StandbyLauncher's promote
        verb): False when the standby died or was already promoted —
        the caller drops it and tries the next. On success the
        member's heartbeat is forced NOW, so the role flip reaches
        the catalog (and the gateway's next poll) without waiting out
        a beat interval — promotion must be a milliseconds event."""
        index = int(replica_id.rsplit("-", 1)[1])
        if index in self.killed or index in self.retired:
            return False
        server = self.servers[index]
        if server is None or not server.promote():
            return False  # still booting, dead, or already promoted
        self.roles[index] = "active"
        try:
            self.members[index]._beat_once()  # noqa: SLF001
        except Exception as exc:
            # the regular beat loop (which already survives per-beat
            # exceptions) will carry the role flip on its next tick
            import logging

            logging.getLogger("containerpilot.chaos").warning(
                "promote %s: forced beat failed: %s", replica_id, exc
            )
        return True

    async def retire_replica(self, replica_id: str) -> None:
        """Scale-down: the PR 3 drain invariant — deregister, finish
        in-flight, stop — so retiring capacity is as invisible to
        clients as replica maintenance."""
        index = int(replica_id.rsplit("-", 1)[1])
        if index in self.killed or index in self.retired:
            return
        if self.members[index] is None:
            return  # still booting: nothing registered to drain yet
        self.retired.add(index)
        await self.members[index].drain(timeout=10.0)
        await self.members[index].stop(deregister=True)
        proxy = self.proxies[index]
        if proxy is not None:
            await proxy.stop()
        await self.servers[index].stop()

    def fleet_load(self):
        """The autoscaler's signal: admission queue depth + per-
        replica DISPATCHED load, straight from the gateway's own
        state. Dispatched only, deliberately: every queued request —
        sticky-pinned or not — is already in ``queue_depth``, and
        folding ``Replica.queued`` in as well would double-count
        pinned waiters and scale up on phantom load."""
        from ..fleet import FleetLoad

        gw = self.gateway
        return FleetLoad(
            queue_depth=gw.admission.depth,
            per_replica={
                r.id: float(r.outstanding)
                for r in gw._replicas.values()  # noqa: SLF001
            },
        )

    async def start(self) -> None:
        # JAX imports live here, not at module import: the trace/SLO
        # halves of the chaos package stay importable (and testable)
        # without an accelerator stack
        import jax
        import jax.numpy as jnp

        from ..discovery import FileCatalogBackend
        from ..fleet import Autoscaler, AutoscalerConfig, FleetGateway
        from ..models.transformer import TransformerConfig, init_params

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_seq_len=64, dtype=jnp.float32,
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        self._model = (cfg, params)
        self.backend = FileCatalogBackend(self.catalog_dir)
        for i in range(self.n_replicas):
            role = (
                self.init_roles[i]
                if i < len(self.init_roles) else "active"
            )
            await self.spawn_replica(role=role)
        self.flaky = FlakyBackend(self.backend)
        kwargs = dict(
            poll_interval=0.1, retries=3, retry_backoff=0.02,
            hedge=False,
        )
        kwargs.update(self.gateway_kwargs)
        self.gateway = FleetGateway(
            self.flaky, SERVICE, "127.0.0.1", 0, **kwargs
        )
        await self.gateway.run()
        for _ in range(200):
            if self.gateway.replica_count == self.n_replicas:
                break
            await asyncio.sleep(0.05)
        if self.gateway.replica_count != self.n_replicas:
            raise RuntimeError(
                f"fleet failed to converge: "
                f"{self.gateway.replica_count}/{self.n_replicas}"
            )
        if self.autoscaler_kwargs is not None:
            launcher: Any = _HarnessLauncher(self)
            if self.standby_count > 0:
                from ..fleet import StandbyLauncher

                launcher = StandbyLauncher(
                    launcher, self.standby_count,
                    jitter_seed=self.gateway_kwargs.get("jitter_seed"),
                )
                # the initial pool boots BEFORE traffic: warm
                # standbys are part of the fleet's steady state, and
                # their boot/compile badput belongs to the pre-trace
                # window exactly like the active replicas' warmup
                await launcher.prefill()
                self.standby_launcher = launcher
            # launch-retry jitter rides the run's seed like the
            # gateway's (seeded replays must replay backoff timing)
            scaler_kwargs = dict(self.autoscaler_kwargs)
            scaler_kwargs.setdefault(
                "jitter_seed", self.gateway_kwargs.get("jitter_seed")
            )
            self.autoscaler = Autoscaler(
                launcher,
                self.fleet_load,
                AutoscalerConfig(**scaler_kwargs),
                registry=self.gateway.registry,
            )
            self.gateway.attach_autoscaler(self.autoscaler)
        for role, kwargs in self.pool_autoscaler_kwargs.items():
            pool_kwargs = dict(kwargs)
            pool_kwargs.setdefault(
                "jitter_seed", self.gateway_kwargs.get("jitter_seed")
            )
            # registry=None: co-attached autoscalers would collide on
            # the per-pool metric names — the fleet-wide autoscaler
            # (when present) keeps the prometheus side; every
            # attached scaler's stats still reach /fleet and the
            # scenario report through scale_event_report
            scaler = Autoscaler(
                _PoolLauncher(self, role),
                lambda r=role: self.gateway.pool_load(r),
                AutoscalerConfig(**pool_kwargs),
                registry=None,
                pool=role,
            )
            self.gateway.attach_autoscaler(scaler)
            self.pool_autoscalers[role] = scaler

    def start_autoscalers(self) -> None:
        """Arm the scaler tick loops. Called AFTER warmup, not inside
        ``start()``: warm requests bypass the gateway, so a fleet
        booted above ``min_replicas`` would read as sustained-idle
        during the (minutes-long on a cold box) compile window and
        scale down replicas the warmup is still talking to. The
        autoscaler's clock starts with the traffic clock."""
        if self.autoscaler is not None:
            self.autoscaler.start()
        for scaler in self.pool_autoscalers.values():
            scaler.start()

    async def stop(self) -> None:
        for scaler in self.pool_autoscalers.values():
            await scaler.stop()
        if self.autoscaler is not None:
            await self.autoscaler.stop()
        if self.standby_launcher is not None:
            await self.standby_launcher.stop()
        if self.gateway is not None:
            await self.gateway.stop()
        for i, member in enumerate(self.members):
            if i in self.retired or member is None:
                continue  # retire_replica already stopped it / mid-boot
            await member.stop(deregister=i not in self.killed)
        for i, proxy in enumerate(self.proxies):
            if proxy is not None and i not in self.retired:
                await proxy.stop()
        for i, server in enumerate(self.servers):
            if (
                i not in self.killed and i not in self.retired
                and server is not None
            ):
                await server.stop()

    # -- fault verbs -------------------------------------------------

    def _log(self, fault: Fault) -> None:
        self.fault_log.append(
            {
                "at_s": fault.at_s, "kind": fault.kind,
                "replica": fault.replica, "value": fault.value,
            }
        )

    def kv_stats(self) -> Dict[str, int]:
        """Summed prefix-cache/spill counters across every replica
        ever booted (killed and retired included — their in-process
        stats remain readable): the fleet-wide reuse ledger."""
        totals: Dict[str, int] = {
            "hits": 0, "misses": 0, "tokens_reused": 0,
            "spilled": 0, "readmitted": 0,
        }
        for server in self.servers:
            pc = getattr(server, "prefix_cache", None)
            if pc is None:
                continue
            for key in totals:
                totals[key] += pc.stats.get(key, 0)
        return totals

    def goodput_stats(self) -> Dict[str, float]:
        """Summed device-time-ledger totals (+ dispatch/token
        counters) across every replica ever booted — killed and
        retired included (their ledgers froze at death, exactly as a
        dead process's heartbeat note stops updating). Snapshotted
        before/after the driven window, the delta is the scenario's
        goodput ledger: scale-up replicas launched mid-trace
        contribute their whole boot/compile life, which is precisely
        the badput the cold-start ROADMAP item must collapse."""
        per = []
        for server in self.servers:
            ledger = getattr(server, "ledger", None)
            if ledger is None:
                continue
            totals = ledger.totals()
            engine = getattr(server, "slot_engine", None)
            totals["dispatches"] = float(
                getattr(engine, "dispatches", 0)
            )
            totals["tokens_out"] = float(
                getattr(engine, "tokens_out", 0)
            )
            per.append(totals)
        return goodput_mod.sum_stage_totals(per)

    def goodput_breakdown(self) -> Dict[str, Any]:
        """Per-replica ledger snapshots (cumulative, whole life) for
        the report — the departed-fold-in view: killed/retired
        replicas stay listed with their frozen totals."""
        out: Dict[str, Any] = {}
        for index, server in enumerate(self.servers):
            ledger = getattr(server, "ledger", None)
            if ledger is None:
                continue
            totals = ledger.totals()
            out[f"replica-{index}"] = {
                "departed": (
                    index in self.killed or index in self.retired
                ),
                "productive_fraction": (
                    goodput_mod.productive_fraction(totals)
                ),
                "stages_s": {
                    s: round(totals[s], 3) for s in goodput_mod.STAGES
                },
            }
        return out

    def goodput_stats_by_role(self) -> Dict[str, Dict[str, float]]:
        """Per-ROLE summed stage totals (cumulative; snapshot twice
        and difference for the driven window) — the disaggregation
        ledger: a decode pool whose productive fraction beats the
        mixed arm's is the whole point of the split, and only a
        per-role cut of the PR 12 ledger can say so. Roles reflect
        end state (a promoted standby's life lands under "active"),
        and departed replicas' frozen ledgers fold in as ever."""
        per: Dict[str, List[Dict[str, float]]] = {}
        for index, server in enumerate(self.servers):
            ledger = getattr(server, "ledger", None)
            if ledger is None:
                continue
            role = self.roles.get(index, "active")
            per.setdefault(role, []).append(ledger.totals())
        return {
            role: goodput_mod.sum_stage_totals(totals)
            for role, totals in per.items()
        }

    async def apply(self, fault: Fault) -> None:
        self._log(fault)
        if fault.kind == "kill":
            await self.kill(fault.replica)
        elif fault.kind == "drain":
            # graceful scale-away mid-conversation: the PR 3 drain
            # invariant (deregister, finish in-flight, stop) — the
            # rebalance event cache-aware routing must absorb warmly
            await self.retire_replica(f"replica-{fault.replica}")
        elif fault.kind == "wedge":
            self.servers[fault.replica].ready = False
        elif fault.kind == "unwedge":
            self.servers[fault.replica].ready = True
        elif fault.kind == "slow":
            self.set_delay(fault.replica, fault.value)
        elif fault.kind == "slow_boot":
            # arms for every replica launched from now on: their
            # warmup parks fault.value seconds (0 disarms) — the
            # cold-start tax the standby pool must mask
            self.slow_boot_s = fault.value
        elif fault.kind == "lossy":
            proxy = self.proxies[fault.replica]
            if proxy is None:
                raise RuntimeError("lossy fault needs use_proxies=True")
            proxy.reset_after_bytes = (
                int(fault.value) if fault.value > 0 else None
            )
        elif fault.kind == "flap":
            assert self.flaky is not None
            self.flaky.flap(int(fault.value))
        else:
            raise ValueError(f"unknown fault kind {fault.kind!r}")

    async def kill(self, i: int) -> None:
        """SIGKILL semantics: heartbeats stop WITHOUT deregistering
        (the record decays critical by TTL), then the server aborts —
        listener and live connections drop with no drain."""
        self.killed.add(i)
        await self.members[i].stop(deregister=False)
        proxy = self.proxies[i]
        if proxy is not None:
            await proxy.stop()
        await self.servers[i].abort()

    def set_delay(self, i: int, delay_s: float) -> None:
        server = self.servers[i]
        if delay_s <= 0:
            server.chaos_hook = None
            return

        async def hook(endpoint: str) -> None:
            if endpoint in ("generate", "completions"):
                await asyncio.sleep(delay_s)

        server.chaos_hook = hook

    async def run_schedule(
        self, faults: Tuple[Fault, ...], clock_zero: float
    ) -> None:
        for fault in sorted(faults, key=lambda f: f.at_s):
            delay = clock_zero + fault.at_s - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            await self.apply(fault)


@dataclass(frozen=True)
class ScenarioSpec:
    """One scenario: the workload, the faults, and the bar to clear."""

    name: str
    description: str
    trace: TraceConfig
    faults: Tuple[Fault, ...] = ()
    replicas: int = 2
    ttl: int = 1
    use_proxies: bool = False
    gateway: Dict[str, Any] = field(default_factory=dict)
    #: extra InferenceServer knobs per replica (e.g.
    #: prefix_cache_entries + kv_spill_bytes for KV-reuse scenarios)
    server: Dict[str, Any] = field(default_factory=dict)
    #: disaggregated boot roles: replica i boots with roles[i]
    #: ("prefill"/"decode"); replicas past the tuple's end (and the
    #: whole fleet when empty) boot mixed. The role rides the same
    #: heartbeat note role=standby does, and the gateway's
    #: phase-aware _pick degrades to mixed routing the moment a pool
    #: empties — which is exactly what prefill_pool_killed proves
    roles: Tuple[str, ...] = ()
    #: AutoscalerConfig kwargs; None runs without an autoscaler
    autoscaler: Optional[Dict[str, Any]] = None
    #: role -> AutoscalerConfig kwargs: one independent autoscaler
    #: per phase pool (prefill sizes on admission-queue pressure,
    #: decode on slot occupancy — gateway.pool_load(role) is the
    #: signal); None runs without pool autoscalers
    pool_autoscaler: Optional[Dict[str, Dict[str, Any]]] = None
    #: warm-standby pool size (fleet/standby.py; needs autoscaler):
    #: booted before traffic, promoted instead of launched on scale
    #: events, refilled in the background
    standby: int = 0
    slo: SLO = field(default_factory=SLO)
    #: seconds after the last request for TTL expiries / polls to
    #: converge before end-state checks run (and, for autoscaled
    #: scenarios, the sustained-idle window scale-down needs)
    settle_s: float = 0.5
    quick: bool = True
    # -- invariant thresholds ----------------------------------------
    max_5xx: int = 0
    max_transport_errors: int = 0
    min_goodput_fraction: float = 0.9
    expect_hedged_min: int = 0
    expect_flaps_damped_min: int = 0
    #: replica indices that must have left catalog AND routing table
    expect_absent: Tuple[int, ...] = ()
    max_ttft_p99_ms: Optional[float] = None
    max_truncated_streams: Optional[int] = None
    # -- overload / autoscaling invariants ---------------------------
    #: the burst must actually shed (proves admission bit, and that
    #: every shed was honest 429/504, since max_5xx still holds)
    expect_sheds_min: int = 0
    #: goodput floor over the requests the fleet ADMITTED
    min_admitted_goodput_fraction: Optional[float] = None
    expect_scale_up_min: int = 0
    expect_scale_down_min: int = 0
    #: thrash bound: scale_ups + scale_downs must stay under this
    max_scale_events: Optional[int] = None
    # -- mux transport invariants ------------------------------------
    #: abandoned/cancelled streams must become CANCEL frames (stream
    #: id freed, shared connection kept), not connection teardowns
    expect_mux_cancels_min: int = 0
    #: closes where the HTTP/1.1 path would have burned a connection
    expect_conns_saved_min: int = 0
    #: a replica launched mid-run (index >= the boot count) must have
    #: been registered and routed to
    expect_scaled_replica_routed: bool = False
    #: replicas the autoscaler manages at the end (back to min)
    expect_managed_at_end: Optional[int] = None
    # -- KV reuse invariants -------------------------------------------
    #: routing picks that must land on a digest-warm replica (a
    #: drained session re-pinning onto the replica that absorbed its
    #: retried turns is the canonical hit)
    expect_cache_hint_hits_min: int = 0
    #: fleet-wide prefix tokens reused during the trace (warmup
    #: excluded) — proves the reuse machinery ran, not just routed
    expect_tokens_reused_min: int = 0
    #: spill-tier readmissions (device LRU eviction -> host RAM ->
    #: device again) that must have happened
    expect_readmitted_min: int = 0
    # -- disaggregation invariants -------------------------------------
    #: completed prefill->decode KV handoffs (gateway-orchestrated
    #: /v1/prefill seed + /v1/kv/pull, the cp-mux/1 stream) the run
    #: must have performed — proves the split fleet actually moved
    #: KV replica-to-replica instead of silently falling back to
    #: decode-side prefill on every request
    expect_handoffs_min: int = 0
    # -- drain-migration invariants ------------------------------------
    #: sessions a draining replica must have pushed (KV prefix over
    #: the handoff wire, or digest-warm landing) onto survivors —
    #: proves drain ran as a migration, not an eviction
    expect_migrations_min: int = 0
    #: ceiling on migration window timeouts (0 gates "nothing fell
    #: back to the eviction path"; None skips)
    expect_migration_timeouts_max: Optional[int] = None
    #: sessions_migrated must cover pins_repointed: every sticky pin
    #: the gateway moved off an mg= landing corresponds to a prefix
    #: that actually landed on the survivor first
    expect_migrations_cover_moves: bool = False
    #: violation class -> a stage that must NOT dominate it (e.g.
    #: {"ttft": "replica.prefill"}: migrated sessions' TTFT misses
    #: must not be re-prefill — the KV landed, so blame belongs to
    #: queueing/transport, never recompute). Vacuously true when the
    #: class has no violations.
    forbid_dominant_stage: Dict[str, str] = field(default_factory=dict)
    # -- latency-attribution invariants --------------------------------
    #: violation class -> the stage that must dominate it in the
    #: report's stage_attribution (e.g. {"ttft":
    #: "admission_queue_wait"}: a burst's TTFT misses must be queue
    #: wait, not replica compute). Vacuously true when the class has
    #: no violations — the invariant constrains the blame, not the
    #: failure count (goodput floors do that).
    expect_dominant_stage: Dict[str, str] = field(default_factory=dict)
    # -- device-time-ledger invariants ----------------------------------
    #: floor on the driven window's fleet productive fraction —
    #: (prefill + decode) device-seconds over ALL device-seconds the
    #: fleet accrued between traffic start and the end-state reads
    #: (settle included; mid-run scale-ups contribute their whole
    #: boot/compile cold start). Lab-box bars are necessarily low —
    #: the tiny model decodes in ms while injected slow-hooks and
    #: admission waits burn idle wall time — but a floor still
    #: catches the regression class where serving stops progressing
    #: while the fleet stays "up" (None skips the check)
    min_productive_fraction: Optional[float] = None
    #: a scale-up event must carry a finite time-to-first-routed-
    #: token (launch decision -> first 200 served by the new
    #: replica) — the cold-start collapse item's yardstick
    expect_scale_up_ttfrt: bool = False
    #: the PROMOTED-path TTFRT bound: at least one ``mode ==
    #: "promoted"`` scale-up must carry a finite TTFRT, and every
    #: finite one must sit at or under this many seconds — the
    #: tightened cold-start yardstick (cold launches measured
    #: 0.4-5.4s on the lab box; a promotion skips boot AND compile,
    #: so the bound is stated, not aspirational). A promoted event
    #: with ttfrt None is one the trace never routed to (e.g. a
    #: repair promotion in the idle tail) — not serving when nothing
    #: asks is not a violation, which is why the bound applies to
    #: the finite set and the existence check covers the rest.
    #: None skips.
    max_scale_up_ttfrt_s: Optional[float] = None
    #: standby promotions the autoscaler's launcher must have
    #: performed (proves scale-up rode the promote path, not a lucky
    #: cold launch)
    expect_promotions_min: int = 0
    # -- event-loop health invariant ------------------------------------
    #: loopcheck bound: the harness loop (which carries the gateway,
    #: every replica, the members, AND the chaos client) must never
    #: stall longer than this during the driven window. The stated
    #: default leaves generous room for GIL contention with the
    #: decode/compile executor threads on a loaded CPU box while
    #: still catching the CP-ASYNCBLOCK failure shape (a sync sleep,
    #: file read, or device fetch on the loop shows up as its own
    #: duration). Scenarios with harsher compute may raise it —
    #: stating the bound is the point.
    max_loop_lag_ms: float = 1500.0


async def _warm_fleet(
    harness: FleetHarness, requests: List[TraceRequest]
) -> None:
    """Compile every prompt-length bucket the trace will use BEFORE
    the clock starts: static-shape serving compiles one prefill
    program per distinct prompt length, and the jit factories are
    process-wide (lru-cached per config), so one warm request per
    bucket against one replica warms the whole in-process fleet.
    Mid-trace cold compiles would otherwise dominate TTFT on a lab
    box and score the run on XLA, not on the fleet."""
    port = harness.servers[0].port
    index = 0

    async def warm_one(tokens: List[int]) -> None:
        nonlocal index
        index += 1
        warm = TraceRequest(
            index=-index, at_s=0.0, session_id="warm", tenant=0,
            tokens=tokens, max_new_tokens=2, seed=0,
        )
        record = await issue_request(port, warm, time.monotonic())
        if record.status != 200:
            raise RuntimeError(
                f"warm request (prompt len {len(tokens)}) failed: "
                f"status={record.status} error={record.error!r}"
            )

    lengths = sorted({len(r.tokens) for r in requests})
    for length in lengths:
        await warm_one([1] * length)
    if getattr(harness.servers[0], "prefix_cache", None) is None:
        return
    # with a prefix cache on, the REUSE path has its own compile set:
    # one (1, bucket)-shaped extend program per suffix bucket a hit
    # can rewind+extend. The chained [1]*L warms above only ever
    # produce the smallest bucket (each length extends its
    # predecessor), so larger jumps — turn k of one session matching
    # only a short prefix of a longer prompt — would compile mid-trace
    # and bill XLA to that request's TTFT. Warm each bucket with a
    # fresh token family: store a MIN_REUSE base, then jump by the
    # bucket so the hit extends exactly that shape.
    from ..workload.serve_prefix import BUCKET, MIN_REUSE

    max_len = lengths[-1] if lengths else 0
    targets = set()
    jump = MIN_REUSE + BUCKET
    while jump <= max_len:
        targets.add(jump)
        jump += BUCKET
    if max_len > MIN_REUSE:
        # the ragged largest jump (max_len - MIN_REUSE may not be a
        # bucket multiple, and its rounded-up bucket is a shape no
        # aligned jump produces)
        targets.add(max_len)
    for i, length in enumerate(sorted(targets)):
        family = 2 + i  # distinct ids, never the [1]* family above
        await warm_one([family] * MIN_REUSE)
        await warm_one([family] * length)


async def _drive(
    requests: List[TraceRequest], port: int, clock_zero: float
) -> List[RequestRecord]:
    tasks = []
    for req in requests:
        delay = clock_zero + req.at_s - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(
            asyncio.ensure_future(issue_request(port, req, clock_zero))
        )
    return list(await asyncio.gather(*tasks))


async def run_scenario_async(
    spec: ScenarioSpec, catalog_dir: str, seed: int = 0
) -> Dict[str, Any]:
    """Boot the fleet, replay the trace while the fault schedule
    fires, and score the run. Returns the JSON-able report."""
    trace_cfg = dataclasses.replace(spec.trace, seed=seed)
    requests = generate_trace(trace_cfg)
    # event-loop sentinel (analysis/loopcheck.py): the watchdog wraps
    # the task factory BEFORE the fleet boots so every task the run
    # creates is covered; the lag probe starts with the traffic clock
    # (boot/warmup deliberately compile XLA programs — that stall is
    # paid before the SLO window opens and must not pollute the bound)
    probe = LoopLagProbe()
    watchdog = TaskWatchdog().install()
    harness = FleetHarness(
        catalog_dir,
        spec.replicas,
        ttl=spec.ttl,
        use_proxies=spec.use_proxies,
        gateway_kwargs=dict(spec.gateway, jitter_seed=seed),
        autoscaler_kwargs=spec.autoscaler,
        server_kwargs=spec.server,
        standby_count=spec.standby,
        roles=spec.roles,
        pool_autoscaler_kwargs=spec.pool_autoscaler,
    )
    try:
        # start() inside the try: a boot that fails half-way (e.g.
        # convergence timeout on a loaded box) must still tear down
        # the members/servers it already launched — stop() tolerates
        # partial state
        await harness.start()
        gw = harness.gateway
        await _warm_fleet(harness, requests)
        harness.start_autoscalers()
        # reuse accounting starts AFTER warmup: the warm requests
        # seed replica-0's prefix cache with [1]*L prompts whose
        # chained matches must not inflate the trace's reuse numbers
        kv_before = harness.kv_stats()
        # device-time accounting starts here too: boot + warmup
        # compile happened before the clock, so the scenario's
        # goodput ledger scores the DRIVEN window (a mid-run
        # scale-up's cold start still lands inside it, deliberately)
        gp_before = harness.goodput_stats()
        gp_role_before = harness.goodput_stats_by_role()
        probe.start()
        clock_zero = time.monotonic()
        schedule = asyncio.ensure_future(
            harness.run_schedule(spec.faults, clock_zero)
        )
        records = await _drive(requests, gw.port, clock_zero)
        await schedule
        # wall clock for goodput stops when the WORKLOAD ends: the
        # settle window below is a convergence knob for the end-state
        # checks, and folding it in would deflate goodput_rps by a
        # constant idle tax that varies per scenario
        wall_s = time.monotonic() - clock_zero
        await asyncio.sleep(spec.settle_s)
        # the settle window stays inside the measured span: autoscaler
        # drain/retire and late TTL expiries run on the same loop and
        # a stall there is just as real to the next request
        probe.stop()
        loop_stats = probe.snapshot()
        score = ScenarioScore(records, wall_s, spec.slo).as_dict()
        catalog_ids = {
            inst.id for inst in harness.backend.instances(SERVICE)
        }
        routing_ids = set(gw._replicas)  # noqa: SLF001
        gateway_stats = {
            "replicas_at_end": gw.replica_count,
            "retried": _counter_total(gw._m_retried),  # noqa: SLF001
            "hedged": _counter_total(gw._m_hedged),  # noqa: SLF001
            "drained_away": _counter_total(gw._m_drained),  # noqa: SLF001
            "catalog_flaps_damped": gw.flaps_damped,
            "admission": gw.admission.stats(),
            "routed": _counter_by_label(
                gw._m_routed, "replica"  # noqa: SLF001
            ),
            "mux_streams": _counter_total(gw._m_mux_streams),  # noqa: SLF001
            "mux_cancels": _counter_total(gw._m_mux_cancels),  # noqa: SLF001
            "conns_saved_by_mux": _counter_total(
                gw._m_conns_saved  # noqa: SLF001
            ),
            "proxy_resets": sum(
                p.resets_injected
                for p in harness.proxies if p is not None
            ),
            "sticky": {
                "size": len(gw._sticky),  # noqa: SLF001
                "capacity": gw.sticky_capacity,
                "evicted": gw.sticky_evicted,
            },
            # disaggregation ledger: completed KV handoffs, bytes
            # moved, failures (fell back to local prefill),
            # digest-warm skips, and summed transfer wall ms
            "handoff": dict(gw.handoffs),
            # drain-migration ledger: sessions landed on survivors,
            # counted fallbacks (failed pushes / window timeouts),
            # sticky pins repointed off mg= landings, and 503 drain
            # answers that carried X-CP-Migrated-To
            "migration": dict(gw.migrations),
        }
        kv_after = harness.kv_stats()
        prompt_tokens = sum(len(r.tokens) for r in requests)
        kv_stats = {
            key: kv_after[key] - kv_before[key] for key in kv_after
        }
        kv_stats.update(
            cache_hint_hits=gw.hint_hits,
            cache_hint_misses=gw.hint_misses,
            prompt_tokens=prompt_tokens,
            # the ML-goodput yardstick: prefix tokens the fleet did
            # NOT recompute, per prompt token it was sent
            tokens_reused_per_prompt_token=round(
                kv_stats["tokens_reused"] / max(1, prompt_tokens), 4
            ),
        )
        autoscaler_stats = (
            dict(harness.autoscaler.stats)
            if harness.autoscaler is not None else None
        )
        # the scenario's device-time ledger: per-stage fleet seconds
        # over the driven window (delta against the pre-traffic
        # snapshot), the productive fraction the specs gate on, the
        # per-replica breakdown (departed replicas' frozen ledgers
        # folded in), and per-scale-event time-to-first-routed-token
        gp_after = harness.goodput_stats()
        gp_delta = {
            key: max(gp_after[key] - gp_before.get(key, 0.0), 0.0)
            for key in gp_after
        }
        gp_tokens = gp_delta["tokens_out"]
        goodput_ledger = {
            "stages_s": {
                s: round(gp_delta[s], 3) for s in goodput_mod.STAGES
            },
            "device_seconds": round(
                sum(gp_delta[s] for s in goodput_mod.STAGES), 3
            ),
            "productive_fraction": goodput_mod.productive_fraction(
                gp_delta
            ),
            "dispatches": int(gp_delta["dispatches"]),
            "tokens_out": int(gp_tokens),
            "dispatches_per_token": (
                round(gp_delta["dispatches"] / gp_tokens, 4)
                if gp_tokens else None
            ),
            "per_replica": harness.goodput_breakdown(),
            "scale_events": gw.scale_event_report(),
        }
        # the per-ROLE cut of the same driven-window delta: the
        # disagg_bench compares the decode pool's productive
        # fraction against the mixed arm's fleet-wide number, and
        # prefill_pool_killed reads it to show where the TTFT hit
        # migrated when the pool died
        gp_role_after = harness.goodput_stats_by_role()
        per_role: Dict[str, Any] = {}
        for role, totals in gp_role_after.items():
            before = gp_role_before.get(role, {})
            role_delta = {
                key: max(totals[key] - before.get(key, 0.0), 0.0)
                for key in totals
            }
            per_role[role] = {
                "replicas": sum(
                    1 for i in harness.roles
                    if harness.roles[i] == role
                ),
                "productive_fraction": (
                    goodput_mod.productive_fraction(role_delta)
                ),
                "device_seconds": round(
                    sum(
                        role_delta.get(s, 0.0)
                        for s in goodput_mod.STAGES
                    ), 3
                ),
            }
        goodput_ledger["per_role"] = per_role
    finally:
        probe.stop()
        await harness.stop()
        watchdog.uninstall()
        # the leak ledger is read AFTER teardown and one drained grace
        # window: a task that died during harness.stop() (or in the
        # final grace_s of the run) must not slip past the
        # task_exceptions == [] gate because its deferred _check
        # hadn't fired when the snapshot was taken
        await asyncio.sleep(watchdog.grace_s * 2)

    loop_stats["task_exceptions"] = watchdog.snapshot()
    loop_stats["tasks_created"] = watchdog.tasks_created

    checks: List[Dict[str, Any]] = []

    def check(name: str, ok: bool, detail: str) -> None:
        checks.append({"name": name, "ok": bool(ok), "detail": detail})

    check(
        "5xx", score["count_5xx"] <= spec.max_5xx,
        f"{score['count_5xx']} client-visible 5xx "
        f"(allowed {spec.max_5xx})",
    )
    check(
        "loop_lag",
        loop_stats["lag_max_ms"] <= spec.max_loop_lag_ms,
        f"event-loop lag max {loop_stats['lag_max_ms']}ms over "
        f"{loop_stats['heartbeats']} heartbeats "
        f"(bound {spec.max_loop_lag_ms}ms; p99 "
        f"{loop_stats['lag_p99_ms']}ms — a blocking call on the loop "
        f"shows up here as its own duration)",
    )
    check(
        "transport_errors",
        score["transport_errors"] <= spec.max_transport_errors,
        f"{score['transport_errors']} client transport errors "
        f"(allowed {spec.max_transport_errors})",
    )
    check(
        "goodput",
        score["goodput_fraction"] is not None
        and score["goodput_fraction"] >= spec.min_goodput_fraction,
        f"goodput fraction {score['goodput_fraction']} "
        f"(floor {spec.min_goodput_fraction})",
    )
    if spec.expect_hedged_min > 0:
        check(
            "hedged",
            gateway_stats["hedged"] >= spec.expect_hedged_min,
            f"{gateway_stats['hedged']:.0f} hedge dispatches "
            f"(expected >= {spec.expect_hedged_min})",
        )
    if spec.expect_flaps_damped_min > 0:
        check(
            "flaps_damped",
            gateway_stats["catalog_flaps_damped"]
            >= spec.expect_flaps_damped_min,
            f"{gateway_stats['catalog_flaps_damped']} empty polls "
            f"damped (expected >= {spec.expect_flaps_damped_min})",
        )
    for idx in spec.expect_absent:
        rid = f"replica-{idx}"
        check(
            f"{rid}_absent",
            rid not in catalog_ids and rid not in routing_ids,
            f"{rid} at end: in catalog={rid in catalog_ids}, in "
            f"routing table={rid in routing_ids} "
            f"(catalog={sorted(catalog_ids)}, "
            f"routing={sorted(routing_ids)})",
        )
    if spec.max_ttft_p99_ms is not None:
        p99 = score["ttft_ms"]["p99"]
        check(
            "ttft_p99",
            p99 is not None and p99 <= spec.max_ttft_p99_ms,
            f"TTFT p99 {p99}ms (cap {spec.max_ttft_p99_ms}ms)",
        )
    if spec.max_truncated_streams is not None:
        check(
            "truncated_streams",
            score["truncated_streams"] <= spec.max_truncated_streams,
            f"{score['truncated_streams']} truncated streams "
            f"(allowed {spec.max_truncated_streams})",
        )
    if spec.expect_sheds_min > 0:
        check(
            "sheds",
            score["sheds"] >= spec.expect_sheds_min,
            f"{score['sheds']} sheds (429={score['shed_429']}, "
            f"504={score['shed_504']}; expected >= "
            f"{spec.expect_sheds_min})",
        )
    if spec.min_admitted_goodput_fraction is not None:
        check(
            "admitted_goodput",
            score["goodput_fraction_admitted"] is not None
            and score["goodput_fraction_admitted"]
            >= spec.min_admitted_goodput_fraction,
            f"goodput over admitted requests "
            f"{score['goodput_fraction_admitted']} "
            f"(floor {spec.min_admitted_goodput_fraction})",
        )
    if spec.expect_scale_up_min > 0:
        ups = (autoscaler_stats or {}).get("scale_ups", 0)
        check(
            "scale_up",
            ups >= spec.expect_scale_up_min,
            f"{ups} scale-ups (expected >= {spec.expect_scale_up_min})",
        )
    if spec.expect_scale_down_min > 0:
        downs = (autoscaler_stats or {}).get("scale_downs", 0)
        check(
            "scale_down",
            downs >= spec.expect_scale_down_min,
            f"{downs} scale-downs "
            f"(expected >= {spec.expect_scale_down_min})",
        )
    if spec.max_scale_events is not None:
        events = (autoscaler_stats or {}).get("scale_ups", 0) + (
            autoscaler_stats or {}
        ).get("scale_downs", 0)
        check(
            "scale_thrash",
            events <= spec.max_scale_events,
            f"{events} scale events (thrash bound "
            f"{spec.max_scale_events})",
        )
    if spec.expect_mux_cancels_min > 0:
        check(
            "mux_cancels",
            gateway_stats["mux_cancels"] >= spec.expect_mux_cancels_min,
            f"{gateway_stats['mux_cancels']:.0f} CANCEL frames "
            f"(expected >= {spec.expect_mux_cancels_min}; an abandoned "
            f"stream must free its stream id, not its connection)",
        )
    if spec.expect_conns_saved_min > 0:
        check(
            "conns_saved_by_mux",
            gateway_stats["conns_saved_by_mux"]
            >= spec.expect_conns_saved_min,
            f"{gateway_stats['conns_saved_by_mux']:.0f} connection "
            f"teardowns avoided (expected >= "
            f"{spec.expect_conns_saved_min})",
        )
    if spec.expect_scaled_replica_routed:
        launched = {
            f"replica-{i}"
            for i in range(spec.replicas, len(harness.servers))
        }
        routed_launched = {
            rid for rid, n in gateway_stats["routed"].items()
            if rid in launched and n > 0
        }
        check(
            "scaled_replica_routed",
            bool(routed_launched),
            f"launched={sorted(launched)}, routed-to="
            f"{sorted(routed_launched)} (a scale-up must register "
            f"AND take traffic)",
        )
    if spec.expect_managed_at_end is not None:
        managed = (autoscaler_stats or {}).get("replicas", -1)
        check(
            "managed_at_end",
            managed == spec.expect_managed_at_end,
            f"{managed} managed replicas at end "
            f"(expected {spec.expect_managed_at_end})",
        )
    if spec.expect_cache_hint_hits_min > 0:
        check(
            "cache_hint_hits",
            kv_stats["cache_hint_hits"]
            >= spec.expect_cache_hint_hits_min,
            f"{kv_stats['cache_hint_hits']} cache-hint routing hits "
            f"(expected >= {spec.expect_cache_hint_hits_min}; a "
            f"re-pinned session must land on the warmest survivor)",
        )
    if spec.expect_tokens_reused_min > 0:
        check(
            "tokens_reused",
            kv_stats["tokens_reused"] >= spec.expect_tokens_reused_min,
            f"{kv_stats['tokens_reused']} prefix tokens reused "
            f"fleet-wide ({kv_stats['tokens_reused_per_prompt_token']}"
            f"/prompt token; expected >= "
            f"{spec.expect_tokens_reused_min})",
        )
    if spec.expect_readmitted_min > 0:
        check(
            "spill_readmitted",
            kv_stats["readmitted"] >= spec.expect_readmitted_min,
            f"{kv_stats['readmitted']} spill-tier readmissions "
            f"(expected >= {spec.expect_readmitted_min}; evicted KV "
            f"must come back from host RAM, not re-prefill)",
        )
    if spec.expect_handoffs_min > 0:
        done = gateway_stats["handoff"]["total"]
        check(
            "kv_handoffs",
            done >= spec.expect_handoffs_min,
            f"{done:.0f} completed prefill->decode KV handoffs, "
            f"{gateway_stats['handoff']['bytes']:.0f} bytes in "
            f"{gateway_stats['handoff']['ms_sum']:.0f}ms total "
            f"(failed={gateway_stats['handoff']['failed']:.0f}, "
            f"digest-warm skips="
            f"{gateway_stats['handoff']['skipped_warm']:.0f}; "
            f"expected >= {spec.expect_handoffs_min})",
        )
    migration_stats = gateway_stats.get("migration", {})
    if spec.expect_migrations_min > 0:
        moved = migration_stats.get("sessions_migrated", 0)
        check(
            "sessions_migrated",
            moved >= spec.expect_migrations_min,
            f"{moved} sessions migrated off draining replicas "
            f"(failed={migration_stats.get('failed', 0)}, "
            f"timeout={migration_stats.get('timeout', 0)}; expected "
            f">= {spec.expect_migrations_min}; a drain must push its "
            f"live KV to survivors, not evict it)",
        )
    if spec.expect_migration_timeouts_max is not None:
        timed_out = migration_stats.get("timeout", 0)
        check(
            "migration_timeouts",
            timed_out <= spec.expect_migration_timeouts_max,
            f"{timed_out} migration window timeouts (bound "
            f"{spec.expect_migration_timeouts_max}; a timeout is the "
            f"counted eviction fallback — this run must not need it)",
        )
    if spec.expect_migrations_cover_moves:
        moved = migration_stats.get("sessions_migrated", 0)
        repointed = migration_stats.get("pins_repointed", 0)
        check(
            "migrations_cover_moves",
            moved >= repointed,
            f"{moved} sessions migrated vs {repointed} sticky pins "
            f"repointed (every repoint must ride an mg= landing — a "
            f"pin moved without its KV is a silent re-prefill)",
        )
    if spec.min_productive_fraction is not None:
        fraction = goodput_ledger["productive_fraction"]
        check(
            "productive_fraction",
            fraction is not None
            and fraction >= spec.min_productive_fraction,
            f"fleet productive fraction {fraction} over the driven "
            f"window (floor {spec.min_productive_fraction}; stages "
            f"{goodput_ledger['stages_s']})",
        )
    if spec.expect_scale_up_ttfrt:
        ups = [
            e for e in goodput_ledger["scale_events"]
            if e["direction"] == "up"
        ]
        finite = [
            e["ttfrt_s"] for e in ups
            if e.get("ttfrt_s") is not None
        ]
        check(
            "scale_up_ttfrt",
            bool(finite),
            f"scale-up time-to-first-routed-token: "
            f"{finite or 'none finite'} over {len(ups)} launch(es) "
            f"(a scale-up must serve its first 200, and the ledger "
            f"must say how long the cold start took)",
        )
    if spec.max_scale_up_ttfrt_s is not None:
        promoted = [
            e for e in goodput_ledger["scale_events"]
            if e["direction"] == "up" and e.get("mode") == "promoted"
        ]
        finite = [
            e["ttfrt_s"] for e in promoted
            if e.get("ttfrt_s") is not None
        ]
        check(
            "promoted_ttfrt_bound",
            bool(finite)
            and max(finite) <= spec.max_scale_up_ttfrt_s,
            f"promoted-path TTFRT {finite or 'none finite'} over "
            f"{len(promoted)} promotion(s) (bound "
            f"{spec.max_scale_up_ttfrt_s}s — a promotion skips boot "
            f"and compile, so this is the fast path's contract)",
        )
    if spec.expect_promotions_min > 0:
        promotions = (
            (autoscaler_stats or {}).get("standby", {})
        ).get("promotions", 0)
        check(
            "standby_promotions",
            promotions >= spec.expect_promotions_min,
            f"{promotions} standby promotions (expected >= "
            f"{spec.expect_promotions_min}; scale-up must ride the "
            f"warm pool, not a cold launch)",
        )
    for cls, want in sorted(spec.expect_dominant_stage.items()):
        attributed = score["stage_attribution"].get(cls)
        if attributed is None:
            check(
                f"dominant_{cls}", True,
                f"no {cls} violations to attribute (vacuous pass)",
            )
        elif attributed["with_stage_data"] == 0:
            # violations happened but NONE carried a stage breakdown:
            # that is a tracing regression (digest dropped or parse
            # broken), not a vacuous pass — failing here keeps the
            # attribution invariant honest
            check(
                f"dominant_{cls}", False,
                f"{attributed['count']} {cls} violations but none "
                f"carried stage data — trace propagation broken?",
            )
        else:
            check(
                f"dominant_{cls}",
                attributed["dominant"] == want,
                f"{attributed['count']} {cls} violations dominated by "
                f"{attributed['dominant']!r} (expected {want!r}; "
                f"stage totals {attributed['stages_ms']})",
            )
    for cls, banned in sorted(spec.forbid_dominant_stage.items()):
        attributed = score["stage_attribution"].get(cls)
        if attributed is None:
            check(
                f"not_dominant_{cls}", True,
                f"no {cls} violations to attribute (vacuous pass)",
            )
        elif attributed["with_stage_data"] == 0:
            check(
                f"not_dominant_{cls}", False,
                f"{attributed['count']} {cls} violations but none "
                f"carried stage data — trace propagation broken?",
            )
        else:
            check(
                f"not_dominant_{cls}",
                attributed["dominant"] != banned,
                f"{attributed['count']} {cls} violations dominated by "
                f"{attributed['dominant']!r} (must NOT be {banned!r}; "
                f"stage totals {attributed['stages_ms']})",
            )

    fault_counts: Dict[str, int] = {}
    for entry in harness.fault_log:
        fault_counts[entry["kind"]] = (
            fault_counts.get(entry["kind"], 0) + 1
        )
    return {
        "scenario": spec.name,
        "description": spec.description,
        "seed": seed,
        "passed": all(c["ok"] for c in checks),
        "checks": checks,
        "trace": trace_summary(requests),
        "score": score,
        # event-loop health (analysis/loopcheck.py): the gated max is
        # also surfaced top-level as the report's schema-stable name
        "loop_lag_max_ms": loop_stats["lag_max_ms"],
        "loop": loop_stats,
        "gateway": gateway_stats,
        "goodput_ledger": goodput_ledger,
        "kv": kv_stats,
        "autoscaler": autoscaler_stats,
        "faults": harness.fault_log,
        "fault_counts": fault_counts,
    }


def run_scenario(
    spec_or_name, catalog_dir: str, seed: int = 0
) -> Dict[str, Any]:
    """Synchronous entry point (CLI, bench): fresh event loop."""
    spec = (
        SCENARIOS[spec_or_name]
        if isinstance(spec_or_name, str) else spec_or_name
    )
    return asyncio.run(run_scenario_async(spec, catalog_dir, seed))


# -- the registry ----------------------------------------------------

def _trace(**overrides: Any) -> TraceConfig:
    base = dict(
        duration_s=2.5, mean_rps=10.0, burst_factor=3.0,
        tenants=3, sessions_per_tenant=3,
        stream_fraction=0.25, abandon_fraction=0.3,
    )
    base.update(overrides)
    return TraceConfig(**base)


SCENARIOS: Dict[str, ScenarioSpec] = {}


def _register(spec: ScenarioSpec) -> None:
    SCENARIOS[spec.name] = spec


_register(ScenarioSpec(
    name="kill_spare",
    description=(
        "SIGKILL one of three replicas mid-trace with spare capacity: "
        "retries absorb the resets, the record TTL-expires, zero "
        "client-visible 5xx"
    ),
    trace=_trace(),
    faults=(Fault(at_s=0.8, kind="kill", replica=2),),
    replicas=3,
    settle_s=1.5,  # ttl=1 expiry + a poll must land before end checks
    expect_absent=(2,),
    min_goodput_fraction=0.85,
))

_register(ScenarioSpec(
    name="wedged_health",
    description=(
        "a replica's health wedges (heartbeats stop, process still "
        "answers): the record goes catalog-critical by TTL and "
        "traffic routes around it with zero 5xx — the reference "
        "ContainerPilot's core failure mode"
    ),
    trace=_trace(duration_s=3.0),
    faults=(Fault(at_s=0.6, kind="wedge", replica=1),),
    replicas=2,
    settle_s=1.5,
    expect_absent=(1,),
    min_goodput_fraction=0.9,
))

_register(ScenarioSpec(
    name="catalog_flap",
    description=(
        "the catalog transiently answers empty (torn read): the "
        "gateway's hold-down keeps the routing table, zero 5xx, "
        "catalog_flaps_damped > 0"
    ),
    trace=_trace(),
    faults=(
        Fault(at_s=0.5, kind="flap", value=2),
        Fault(at_s=1.5, kind="flap", value=2),
    ),
    replicas=2,
    expect_flaps_damped_min=2,
    min_goodput_fraction=0.95,
))

_register(ScenarioSpec(
    name="slow_replica",
    description=(
        "one replica browns out (injected per-request latency): tail "
        "hedging races the slow legs to the healthy replica, keeping "
        "scenario p99 bounded with zero 5xx"
    ),
    trace=_trace(stream_fraction=0.0),  # hedging covers buffered legs
    faults=(Fault(at_s=0.4, kind="slow", replica=0, value=0.5),),
    replicas=2,
    gateway={"hedge": True, "hedge_after_ms": 100.0},
    expect_hedged_min=1,
    min_goodput_fraction=0.85,
    max_ttft_p99_ms=1800.0,
))

_register(ScenarioSpec(
    name="abandoned_streams_mux",
    description=(
        "SSE-heavy trace where most clients hang up mid-stream, all "
        "over the mux transport: every abandon becomes a CANCEL "
        "frame that frees its stream id while the replicas' shared "
        "connections keep serving the co-resident streams — zero "
        "client-visible 5xx, no connection teardowns"
    ),
    trace=_trace(
        duration_s=2.5, mean_rps=12.0,
        stream_fraction=0.7, abandon_fraction=0.6,
        # long outputs so streams span many decode rounds: an abandon
        # after 1-2 SSE events must land MID-stream (a stream that
        # already ended has nothing to CANCEL), warm caches included
        output_median=24, output_sigma=0.3, max_output=32,
    ),
    replicas=2,
    min_goodput_fraction=0.85,
    expect_mux_cancels_min=1,
    expect_conns_saved_min=3,
))

_register(ScenarioSpec(
    name="lossy_transport",
    description=(
        "the gateway->replica transport turns lossy (RST after a "
        "byte budget, mid-response): buffered requests retry to "
        "clean replicas with zero 5xx; stream truncations stay "
        "bounded"
    ),
    trace=_trace(duration_s=3.0, stream_fraction=0.15),
    faults=(
        Fault(at_s=0.5, kind="lossy", replica=0, value=512),
        Fault(at_s=2.0, kind="lossy", replica=0, value=0),  # heal
    ),
    replicas=2,
    use_proxies=True,
    quick=False,
    min_goodput_fraction=0.75,
    max_truncated_streams=4,
))

_register(ScenarioSpec(
    name="kill_under_burst",
    description=(
        "a replica dies at the height of a 5x burst while the "
        "catalog also flaps: jittered retries + hold-down keep the "
        "run at zero 5xx"
    ),
    trace=_trace(
        duration_s=5.0, mean_rps=16.0, burst_factor=5.0,
        burst_dwell_s=0.6,
    ),
    faults=(
        Fault(at_s=1.0, kind="kill", replica=2),
        Fault(at_s=2.0, kind="flap", value=2),
    ),
    replicas=3,
    settle_s=1.5,
    quick=False,
    expect_absent=(2,),
    expect_flaps_damped_min=1,
    min_goodput_fraction=0.8,
))

_register(ScenarioSpec(
    name="burst_10x",
    description=(
        "a 10x arrival-rate burst slams a browned-out two-replica "
        "fleet: admission control sheds the overflow honestly (429 "
        "for batch past high-water, 504 at the TTFT deadline, both "
        "with drain-rate-derived Retry-After the clients honor with "
        "jitter) — zero client-visible 5xx, and the work the fleet "
        "DID admit still meets its SLOs — and since PR 8 the whole "
        "burst rides the mux transport (interleaved streams on one "
        "warm connection per replica). burst_10x_standby is the SAME "
        "burst with a warm-standby pool: its shed count against this "
        "one's is the cold-start-collapse yardstick"
    ),
    # the injected per-request service floor stands in for a
    # production-sized model's decode time: the lab model answers in
    # ms, which no burst the 1-core box can generate would saturate
    trace=_trace(
        # dwell means favor the burst state so EVERY seed spends
        # real time at 10x — a seed that never bursts can't prove
        # shedding
        duration_s=5.0, mean_rps=6.0, burst_factor=10.0,
        quiet_dwell_s=0.6, burst_dwell_s=1.2,
        stream_fraction=0.1, abandon_fraction=0.2,
        batch_fraction=0.35,
    ),
    faults=(
        Fault(at_s=0.0, kind="slow", replica=0, value=0.15),
        Fault(at_s=0.0, kind="slow", replica=1, value=0.15),
    ),
    replicas=2,
    gateway={
        "admission": {
            "per_replica_inflight": 2,
            "max_queue_depth": 16,
            "high_water": 8,
            "deadline_s": 1.2,
            "session_rate": 8.0,
        },
    },
    settle_s=1.0,
    # TTFT is honest — measured from the FIRST attempt, so a shed
    # that retried after Retry-After (~1-2s) and then served carries
    # the whole dance. The scenario SLO allows one polite retry
    # (3s); the fleet-side bar stays sharp via the 1.2s admission
    # deadline. Floors leave headroom for 1-core-box scheduling
    # noise: observed run-to-run spread is wide under overload.
    slo=SLO(ttft_s=3.0, tpot_s=0.5),
    min_goodput_fraction=0.2,
    min_admitted_goodput_fraction=0.8,
    expect_sheds_min=1,
    # the PR 9 attribution invariant: a burst's TTFT misses are QUEUE
    # time (gateway admission wait + Retry-After parking, which the
    # client folds into the same stage), never replica compute — an
    # overloaded-but-honest fleet pages the operator at admission,
    # not at the replicas
    expect_dominant_stage={"ttft": "admission_queue_wait"},
    # device-time floor: even shedding honestly under 10x, the fleet
    # must keep ADVANCING admitted work. Measured 0.12-0.28 on the
    # CPU lab box depending on whether the process's jit caches were
    # warm (cold runs bill mid-trace compiles to prefill); the floor
    # sits 3x under the warm minimum and still catches the
    # wedged-but-up regression shape (pf ~ 0: fleet up, nothing
    # advancing)
    min_productive_fraction=0.04,
))

_register(ScenarioSpec(
    name="burst_10x_standby",
    description=(
        "the SAME 10x burst, trace and admission knobs as burst_10x, "
        "with a warm-standby pool: the autoscaler PROMOTES the "
        "standby into the sustained pressure (admission capacity "
        "grows the moment its role flips — ~a poll interval instead "
        "of a full boot), so the fleet OUTRUNS part of the burst "
        "instead of only shedding it. Shed counts against burst_10x "
        "in the same report are the cold-start-collapse yardstick "
        "(105 -> 53 at the suite seed; a lightly-loaded seed can "
        "reach zero sheds, which is the point — so no shed minimum "
        "here; burst_10x keeps the shed-honesty proof)"
    ),
    trace=_trace(
        duration_s=5.0, mean_rps=6.0, burst_factor=10.0,
        quiet_dwell_s=0.6, burst_dwell_s=1.2,
        stream_fraction=0.1, abandon_fraction=0.2,
        batch_fraction=0.35,
    ),
    faults=(
        Fault(at_s=0.0, kind="slow", replica=0, value=0.15),
        Fault(at_s=0.0, kind="slow", replica=1, value=0.15),
    ),
    replicas=2,
    # ttl 2 (not the default 1): the standby pool adds a third (and,
    # refilled, fourth) in-process replica to the one-core box, and
    # a contention spike in a hot suite process can starve a
    # heartbeat thread past a 1s TTL — flapping a HEALTHY replica
    # out of routing mid-burst into no-healthy-replica 503s (the
    # multiturn scenarios carry the same stated mitigation)
    ttl=2,
    gateway={
        "admission": {
            "per_replica_inflight": 2,
            "max_queue_depth": 16,
            "high_water": 8,
            "deadline_s": 1.2,
            "session_rate": 8.0,
        },
    },
    autoscaler={
        "min_replicas": 2,
        "max_replicas": 3,
        "slots_per_replica": 2,
        "high_water": 0.75,
        "low_water": 0.1,
        "up_sustain_s": 0.3,
        "down_sustain_s": 2.0,
        "cooldown_s": 0.7,
        "tick_interval": 0.15,
    },
    standby=1,
    max_scale_events=6,
    settle_s=1.0,
    # mid-run standby refills run a replica warmup on an executor
    # thread; even jit-cache-warm, the GIL bursts bleed into loop
    # scheduling on the 1-core box — same raised, stated bound as
    # the other autoscaled scenarios
    max_loop_lag_ms=3000.0,
    slo=SLO(ttft_s=3.0, tpot_s=0.5),
    min_goodput_fraction=0.2,
    min_admitted_goodput_fraction=0.8,
    expect_promotions_min=1,
    expect_dominant_stage={"ttft": "admission_queue_wait"},
    min_productive_fraction=0.04,
))

_register(ScenarioSpec(
    name="kill_under_burst_autoscaled",
    description=(
        "a replica is SIGKILLed inside an 8x burst while the catalog "
        "flaps: the autoscaler relaunches to hold the min, scales "
        "into the pressure (launched replica registers and takes "
        "traffic), then drains back to min in the idle tail — no "
        "scale thrash, zero client-visible 5xx"
    ),
    trace=_trace(
        # burst-favored dwells, like burst_10x: every seed must
        # spend real time over capacity or the scale-up/-down
        # choreography has nothing to react to
        duration_s=6.5, mean_rps=6.0, burst_factor=8.0,
        quiet_dwell_s=0.6, burst_dwell_s=1.4,
        stream_fraction=0.1, abandon_fraction=0.2,
        batch_fraction=0.25,
    ),
    faults=(
        Fault(at_s=0.0, kind="slow", replica=0, value=0.12),
        Fault(at_s=0.0, kind="slow", replica=1, value=0.12),
        Fault(at_s=1.2, kind="kill", replica=1),
        Fault(at_s=2.5, kind="flap", value=2),
        Fault(at_s=4.0, kind="flap", value=2),
    ),
    replicas=2,
    gateway={
        "admission": {
            "per_replica_inflight": 2,
            "max_queue_depth": 24,
            "high_water": 12,
            "deadline_s": 1.5,
        },
    },
    autoscaler={
        "min_replicas": 2,
        "max_replicas": 4,
        "slots_per_replica": 2,
        "high_water": 0.75,
        "low_water": 0.2,
        "up_sustain_s": 0.3,
        "down_sustain_s": 1.0,
        "cooldown_s": 0.7,
        "tick_interval": 0.15,
    },
    # scale-down needs sustained idle AFTER the trace: the settle
    # window is where the fleet shrinks back to min
    settle_s=5.0,
    # mid-run scale-ups compile a fresh replica's XLA warmup on an
    # executor thread; the GIL bursts bleed into loop scheduling
    # (~0.9s observed on the CPU lab box) — a raised, stated bound,
    # not an exemption
    max_loop_lag_ms=3000.0,
    min_goodput_fraction=0.2,
    min_admitted_goodput_fraction=0.8,
    expect_flaps_damped_min=1,
    expect_absent=(1,),
    expect_scale_up_min=1,
    expect_scale_down_min=1,
    max_scale_events=8,
    expect_scaled_replica_routed=True,
    expect_managed_at_end=2,
    # the cold-start yardstick: every launch is stamped into the
    # ledger, and at least one scale-up must carry a finite
    # time-to-first-routed-token (launch decision -> first 200 from
    # the new replica) — the number the ROADMAP's warm-standby work
    # must drive down release-over-release
    expect_scale_up_ttfrt=True,
    slo=SLO(ttft_s=2.5, tpot_s=0.5),
))

_register(ScenarioSpec(
    name="kill_under_burst_promoted",
    description=(
        "the promoted-path variant of kill_under_burst_autoscaled, "
        "with the slow_boot fault armed (every NEW launch pays +2s "
        "of warmup — the production cold-start tax): a replica is "
        "SIGKILLed inside an 8x burst, and repair PROMOTES the warm "
        "standby instead of paying boot+compile — the promoted "
        "scale-up's time-to-first-routed-token stays under a stated "
        "bound that a slow-booted cold launch could not meet, while "
        "the background refill absorbs the slow boot off the "
        "critical path. Zero client-visible 5xx throughout"
    ),
    trace=_trace(
        duration_s=6.5, mean_rps=6.0, burst_factor=8.0,
        quiet_dwell_s=0.6, burst_dwell_s=1.4,
        stream_fraction=0.1, abandon_fraction=0.2,
        batch_fraction=0.25,
    ),
    faults=(
        # slow_boot armed from t=0: anything launched after this —
        # including the standby refill — pays +2s of warmup; only
        # promotion dodges it, which is the point
        Fault(at_s=0.0, kind="slow_boot", value=2.0),
        Fault(at_s=0.0, kind="slow", replica=0, value=0.12),
        Fault(at_s=0.0, kind="slow", replica=1, value=0.12),
        Fault(at_s=1.2, kind="kill", replica=1),
        Fault(at_s=2.5, kind="flap", value=2),
    ),
    replicas=2,
    # ttl 2, like burst_10x: the pool's extra in-process replicas
    # make 1s-TTL heartbeat starvation a real flake shape on the
    # one-core box; the killed corpse still expires well inside the
    # 5s settle window
    ttl=2,
    gateway={
        "admission": {
            "per_replica_inflight": 2,
            "max_queue_depth": 24,
            "high_water": 12,
            "deadline_s": 1.5,
        },
    },
    autoscaler={
        "min_replicas": 2,
        "max_replicas": 4,
        "slots_per_replica": 2,
        "high_water": 0.75,
        "low_water": 0.2,
        "up_sustain_s": 0.3,
        "down_sustain_s": 1.0,
        "cooldown_s": 0.7,
        "tick_interval": 0.15,
    },
    standby=1,
    # scale-down needs sustained idle AFTER the trace; the refilled
    # standby's +2s slow boot also completes inside this window
    settle_s=5.0,
    # same stated GIL-burst allowance as the autoscaled sibling
    max_loop_lag_ms=3000.0,
    min_goodput_fraction=0.2,
    min_admitted_goodput_fraction=0.8,
    expect_flaps_damped_min=1,
    expect_absent=(1,),
    expect_scale_up_min=1,
    max_scale_events=8,
    expect_scaled_replica_routed=True,
    expect_managed_at_end=2,
    expect_promotions_min=1,
    expect_scale_up_ttfrt=True,
    # THE tightened cold-start yardstick: PR 12 measured cold-launch
    # TTFRT at 0.4-5.4s on the lab box, and the armed slow_boot adds
    # +2s to any cold path — a promotion (role flip + forced beat +
    # one poll + first routed token) must land in 2.0s even on a
    # contended 1-core box
    max_scale_up_ttfrt_s=2.0,
    slo=SLO(ttft_s=2.5, tpot_s=0.5),
))

#: the KV-reuse fleet: a TINY device LRU (2 entries) so a session's
#: newest key is routinely evicted between its turns — forcing the
#: host-RAM spill tier to earn its readmissions — with a budget
#: comfortably holding the lab model's ~16KB entries
_REUSE_SERVER = {
    "prefix_cache_entries": 2,
    "kv_spill_bytes": 512 * 1024,
}

#: sticky pins bounded WELL below the session count: pins churn out
#: of the LRU between most turns (the satellite bound doing its job),
#: and each re-pin is exactly the decision cache-aware routing
#: upgrades — digest-warm replica vs. wherever least-loaded points.
#: cache_slack 2 = one 2-slot replica's worth of queue: warmth may
#: absorb that much extra load (a readmit is far cheaper than a
#: re-prefill) but never out-shouts a real hotspot; retries carry one
#: extra attempt because a drain racing a contention spike can bounce
#: a request off more than one replica
_REUSE_GATEWAY = {"sticky_capacity": 2, "cache_slack": 2, "retries": 3}

#: the multi-turn conversation workload both reuse scenarios replay:
#: growing chat histories whose successive turns share an
#: ever-longer prefix (prompts stop at 48 so prompt + max_new fits
#: the lab model's max_len=64)
#: enough CONCURRENT sessions that one replica cannot hold the whole
#: working set: with sparse arrivals the blind tie-break concentrates
#: every no-pin pick on the lowest-id replica, which is accidentally
#: cache-optimal — routing policies only separate under overlap, the
#: regime a fleet exists for. The think floor keeps turn k+1 from
#: arriving before turn k even completes (real users read the answer)
_REUSE_TRACE = _trace(
    multiturn=True, duration_s=1.2,
    think_time_s=0.5, think_floor_s=0.4,
    tenants=3, sessions_per_tenant=4, turns_per_session=5,
    max_prompt=56, max_output=6, output_median=4,
    stream_fraction=0.15, abandon_fraction=0.3,
)

_REUSE_FAULTS = (Fault(at_s=0.9, kind="drain", replica=0),)

_register(ScenarioSpec(
    name="multiturn_rebalance",
    description=(
        "multi-turn chat sessions (growing shared-prefix histories) "
        "against a bounded sticky table while a replica DRAINS "
        "mid-conversation: evicted/drained pins re-route, and "
        "cache-aware routing lands each re-pinned session on the "
        "replica that actually holds its KV (cache_hint_hits > 0) "
        "with zero client-visible 5xx — the host-RAM spill tier "
        "readmitting what the tiny device LRU evicted between turns "
        "instead of re-prefilling it"
    ),
    trace=_REUSE_TRACE,
    faults=_REUSE_FAULTS,
    replicas=4,
    # ttl 2 (not the default 1): four replicas + gateway + client in
    # ONE lab-box process means a contention spike can starve a
    # heartbeat thread past a 1s TTL and flap a healthy replica out
    # of the routing table mid-drain
    ttl=2,
    server=dict(_REUSE_SERVER),
    gateway=dict(_REUSE_GATEWAY),
    settle_s=1.0,
    # spill readmits (device_put) and mid-trace extend-bucket jit
    # compiles burst the GIL from the executor threads (~0.35-0.65s
    # lag observed on the CPU lab box) — a raised, stated bound
    max_loop_lag_ms=2500.0,
    # 2 slots/replica on the 1-core lab box: bursts of co-resident
    # turns queue on slots, so the TTFT bar carries headroom the way
    # burst_10x's does — the floor still bites on real regressions
    slo=SLO(ttft_s=4.0, tpot_s=0.5),
    expect_absent=(0,),
    min_goodput_fraction=0.8,
    expect_cache_hint_hits_min=1,
    expect_tokens_reused_min=100,
    expect_readmitted_min=1,
    # the drain is now a MIGRATION: at least one session's KV must
    # land on a survivor over the handoff wire, and every sticky pin
    # the gateway repoints must ride one of those landings (reuse
    # holding through the drain is the expect_tokens_reused_min gate
    # above — migration is HOW it holds)
    expect_migrations_min=1,
    expect_migrations_cover_moves=True,
    # device-time floor: measured ~0.044 warm-process (tier-1 module
    # runs — the tiny model's reuse-accelerated turns cost ms) up to
    # ~0.59 cold (mid-trace extend-bucket compiles billed to
    # prefill); the floor sits 4x under the warm minimum and catches
    # the regression that turns serving into pure idle waiting
    min_productive_fraction=0.01,
))

_register(ScenarioSpec(
    name="multiturn_sticky_baseline",
    description=(
        "the SAME multi-turn drain workload with cache-aware routing "
        "OFF (pure session-sticky + least-outstanding): the baseline "
        "prefix_reuse_bench compares fleet tokens_reused/token "
        "against — re-pins after an eviction or the drain land by "
        "load, blind to where the KV lives"
    ),
    trace=_REUSE_TRACE,
    faults=_REUSE_FAULTS,
    replicas=4,
    # ttl 2 (not the default 1): four replicas + gateway + client in
    # ONE lab-box process means a contention spike can starve a
    # heartbeat thread past a 1s TTL and flap a healthy replica out
    # of the routing table mid-drain
    ttl=2,
    server=dict(_REUSE_SERVER),
    gateway=dict(_REUSE_GATEWAY, cache_routing=False),
    settle_s=1.0,
    quick=False,  # the bench drives it explicitly, by name
    slo=SLO(ttft_s=4.0, tpot_s=0.5),
    expect_absent=(0,),
    # this arm is the COMPARISON BASELINE, not a robustness gate: its
    # blind tie-break concentrates no-pin picks onto one replica, and
    # on the shared-core lab box that hot spot can starve heartbeats
    # into transient no-healthy-replica 503s and TTFT spikes — the
    # degradation prefix_reuse_bench exists to measure, not a reason
    # to fail the measurement. The aware arm keeps the strict bars.
    max_5xx=30,
    min_goodput_fraction=0.0,
    expect_tokens_reused_min=1,
))

_register(ScenarioSpec(
    name="scale_down_migrated",
    description=(
        "the AUTOSCALER retires a replica out of a live multi-turn "
        "fleet (3 -> min 2) and the retire path runs the migrate "
        "window: every live session's KV pushes to a digest-chosen "
        "survivor over the handoff wire before the record "
        "deregisters, sticky pins repoint off mg= landings, and the "
        "next turns land warm — zero client-visible 5xx, zero "
        "migration-window timeouts, and any TTFT violations must "
        "NOT be re-prefill (the KV moved, so recompute is the one "
        "cause this scenario forbids)"
    ),
    trace=_REUSE_TRACE,
    # no injected fault: the scale-down IS the event, decided by the
    # autoscaler when the trace's load falls away
    faults=(),
    replicas=3,
    # ttl 2 for the same reason as multiturn_rebalance: one lab-box
    # process carries the whole fleet, and a contention spike must
    # not flap a healthy replica mid-retire
    ttl=2,
    server=dict(_REUSE_SERVER),
    # sticky capacity raised well above the session count: this
    # scenario gates on pins REPOINTING (mg= landings / drain
    # answers), so pins must still exist when the retire fires —
    # LRU churn is multiturn_rebalance's subject, not this one's
    gateway=dict(_REUSE_GATEWAY, sticky_capacity=12),
    autoscaler={
        "min_replicas": 2,
        "max_replicas": 3,
        "slots_per_replica": 2,
        # high_water parked out of reach: the scenario is about the
        # way DOWN — a surprise scale-up would hide the migration
        # under fresh capacity
        "high_water": 0.95,
        # low_water UNDER one outstanding request's occupancy (1/6):
        # only a totally idle fleet reads as under, so the down can
        # only fire once the conversations have stopped arriving —
        # when the victim's prefix cache is at its fullest
        "low_water": 0.1,
        "up_sustain_s": 10.0,
        "down_sustain_s": 2.0,
        "cooldown_s": 0.5,
        "tick_interval": 0.15,
    },
    # the idle tail is where down_sustain elapses, the retire's
    # migrate window runs, and the survivors serve the repointed
    # sessions' final turns
    settle_s=5.0,
    # spill readmits + extend-bucket compiles burst the GIL exactly
    # as in multiturn_rebalance — same raised, stated bound
    max_loop_lag_ms=2500.0,
    slo=SLO(ttft_s=4.0, tpot_s=0.5),
    min_goodput_fraction=0.8,
    expect_scale_down_min=1,
    expect_managed_at_end=2,
    expect_migrations_min=1,
    # the counted eviction fallback must stay unused: a localhost
    # push inside a 5s window has no business timing out
    expect_migration_timeouts_max=0,
    expect_migrations_cover_moves=True,
    forbid_dominant_stage={"ttft": "replica.prefill"},
    min_productive_fraction=0.01,
))

#: the disaggregation fleet's server knobs: the KV-reuse tiering
#: (tiny device LRU + host spill, so handoffs adopt into the spill
#: tier and readmit on demand) PLUS the synthetic cold-admission
#: floor. The lab model prefills in ~ms, so phase specialization
#: would have nothing to relieve; prefill_floor_s stands in for a
#: production-sized prompt occupying the slot worker between decode
#: windows, and serve_slots carves the floor's seconds to IDLE in
#: the device-time ledger so the mixed arm's productive fraction is
#: not inflated by the very interference the split removes
_DISAGG_SERVER = dict(_REUSE_SERVER, prefill_floor_s=0.25)

#: default-capacity sticky pins (the decode pin made by the handoff
#: orchestration must survive until the generation routes) with the
#: reuse scenarios' cache_slack and retry depth
_DISAGG_GATEWAY = {"cache_slack": 2, "retries": 3}

#: the disaggregation workload: multiturn conversations whose first
#: turns all clear the fingerprint floor (handoff-eligible by
#: construction: first_turn_min=16), streaming-heavy with NO
#: abandons so nearly every request carries a measurable TPOT — the
#: headline disagg_bench metric is the decode pool's TPOT p99 under
#: concurrent cold-prefill pressure
_DISAGG_TRACE = _trace(
    multiturn=True, duration_s=1.6,
    think_time_s=0.5, think_floor_s=0.4,
    tenants=3, sessions_per_tenant=3, turns_per_session=4,
    max_prompt=56, max_output=10, output_median=8,
    stream_fraction=0.85, abandon_fraction=0.0,
)

#: one prefill replica, two decode replicas — the SAME fleet size as
#: the mixed baseline, split into phase pools
_DISAGG_ROLES = ("prefill", "decode", "decode")

_register(ScenarioSpec(
    name="disagg_mixed_baseline",
    description=(
        "the disaggregation comparison arm: three MIXED replicas "
        "serve the multiturn streaming trace while every cold "
        "prefill occupies its replica's slot worker for the "
        "injected admission floor — the interference that inflates "
        "co-resident streams' TPOT and that disagg_split removes. "
        "disagg_bench replays this arm and the split arm on the "
        "same seed and compares decode TPOT p99, handoff cost, and "
        "per-role productive fraction"
    ),
    trace=_DISAGG_TRACE,
    replicas=3,
    # ttl 2: three replicas + gateway + client in one lab-box
    # process, same heartbeat-starvation mitigation as the other
    # multiturn scenarios
    ttl=2,
    server=dict(_DISAGG_SERVER),
    gateway=dict(_DISAGG_GATEWAY),
    settle_s=1.0,
    quick=False,  # the bench drives it explicitly, by name
    # spill readmits + the deliberate admission floors burst the GIL
    # from executor threads — the multiturn scenarios' stated bound
    max_loop_lag_ms=2500.0,
    # loose bars: this arm is the MEASUREMENT BASELINE — the floors
    # are supposed to hurt its tail, and the bench reads the p99s
    # from both arms' reports rather than this spec failing the run
    slo=SLO(ttft_s=4.0, tpot_s=0.5),
    min_goodput_fraction=0.5,
    min_productive_fraction=0.01,
))

_register(ScenarioSpec(
    name="disagg_split",
    description=(
        "the SAME trace, fleet size, and admission floor as "
        "disagg_mixed_baseline, with the fleet split into phase "
        "pools (1 prefill + 2 decode): fresh prompts prefill on the "
        "prefill pool, the KV prefix ships replica-to-replica over "
        "the cp-mux/1 handoff stream, and the decode pool readmits "
        "it through the same reuse_admission path a local spill "
        "takes — so decode slot workers never stall on a cold "
        "prefill floor, and each pool's independent autoscaler "
        "(admission-pressure for prefill, slot occupancy for "
        "decode) holds its own size"
    ),
    trace=_DISAGG_TRACE,
    replicas=3,
    roles=_DISAGG_ROLES,
    ttl=2,
    server=dict(_DISAGG_SERVER),
    gateway=dict(_DISAGG_GATEWAY),
    # one independent autoscaler per pool, signalled by
    # gateway.pool_load(role); min==max holds the 1+2 split so the
    # bench compares a FIXED fleet, but the wiring (pool-stamped
    # scale log + stats, per-pool load signal) runs for real
    pool_autoscaler={
        "prefill": {
            "min_replicas": 1, "max_replicas": 1,
            "slots_per_replica": 2, "tick_interval": 0.2,
        },
        "decode": {
            "min_replicas": 2, "max_replicas": 2,
            "slots_per_replica": 2, "tick_interval": 0.2,
        },
    },
    settle_s=1.0,
    quick=False,  # the bench drives it explicitly, by name
    max_loop_lag_ms=2500.0,
    slo=SLO(ttft_s=4.0, tpot_s=0.5),
    min_goodput_fraction=0.5,
    # the split must actually MOVE KV: fresh first turns (>= 3 per
    # seed with 9 sessions) each complete a prefill->decode handoff,
    # and the decode pool readmits what it adopted
    expect_handoffs_min=3,
    expect_tokens_reused_min=50,
    expect_readmitted_min=1,
    min_productive_fraction=0.01,
))

_register(ScenarioSpec(
    name="prefill_pool_killed",
    description=(
        "the ENTIRE prefill pool is SIGKILLed early in a multiturn "
        "streaming run: in-flight handoff legs fail onto the "
        "degradation ladder (dead leg excluded + sticky pin "
        "invalidated in the same cycle), fresh prompts fall back to "
        "decode-side local prefill — paying the admission floor "
        "there, which is exactly where the TTFT attribution must "
        "land (replica.prefill, not a mystery smear) — and every "
        "conversation completes with zero client-visible 5xx"
    ),
    trace=_DISAGG_TRACE,
    # kill at 0.25s: early enough that most sessions' first turns
    # arrive AFTER the pool is gone (the local-prefill cohort must
    # dominate the TTFT attribution), late enough that in-flight
    # handoffs are routinely caught mid-leg
    faults=(Fault(at_s=0.25, kind="kill", replica=0),),
    replicas=3,
    roles=_DISAGG_ROLES,
    ttl=2,
    server=dict(_DISAGG_SERVER),
    gateway=dict(_DISAGG_GATEWAY),
    # killed-corpse TTL expiry (2s) + a poll must land before the
    # end-state absence checks
    settle_s=2.5,
    max_loop_lag_ms=2500.0,
    # the TTFT bar sits BELOW the admission floor on purpose, the
    # burst_10x discipline: every floor-paying cold prefill is a
    # violation, and the invariant pins WHERE the time went — the
    # decode replicas' own prefill windows — while the goodput floor
    # (warm turns reuse and stay fast) keeps the run honest
    slo=SLO(ttft_s=0.2, tpot_s=0.5),
    min_goodput_fraction=0.5,
    expect_absent=(0,),
    expect_dominant_stage={"ttft": "replica.prefill"},
    min_productive_fraction=0.01,
))

_register(ScenarioSpec(
    name="rolling_chaos",
    description=(
        "the marathon: brownout, catalog flap, wedged health, "
        "recovery, and a kill across one long bursty trace — the "
        "compound-fault bar every future routing change must clear"
    ),
    trace=_trace(duration_s=8.0, mean_rps=12.0),
    faults=(
        Fault(at_s=0.8, kind="slow", replica=0, value=0.3),
        Fault(at_s=1.6, kind="flap", value=2),
        Fault(at_s=2.5, kind="wedge", replica=1),
        Fault(at_s=4.0, kind="slow", replica=0, value=0.0),  # heal
        Fault(at_s=4.5, kind="unwedge", replica=1),
        Fault(at_s=6.0, kind="kill", replica=2),
    ),
    replicas=3,
    ttl=2,
    settle_s=2.5,
    gateway={"hedge": True, "hedge_after_ms": 150.0},
    quick=False,
    expect_absent=(2,),
    expect_flaps_damped_min=1,
    min_goodput_fraction=0.75,
))


def quick_scenarios() -> List[str]:
    return [s.name for s in SCENARIOS.values() if s.quick]


def full_scenarios() -> List[str]:
    return list(SCENARIOS)
