"""Fault injection for the chaos harness.

Five fault families, each reproducing a real production failure the
reference ContainerPilot's design exists to absorb:

- **Replica kill** (SIGKILL semantics): the replica's listener and
  every live connection drop abruptly and its heartbeats stop WITHOUT
  deregistering — the catalog record decays to critical by TTL expiry,
  exactly like a host that lost power. In-flight requests see resets;
  the gateway must retry them away and route around the corpse.
- **Wedged health check**: the replica process is alive but stops
  being serveable (``ready`` regresses — a wedged device tunnel, a
  deadlocked worker). Heartbeats stop, the record TTL-expires, traffic
  routes around it; recovery resumes beats and the record revives.
- **Slow replica**: injected per-request latency via the serve-side
  test hook (``InferenceServer.chaos_hook``) — the brownout case tail
  hedging exists for.
- **Lossy transport**: a TCP proxy in front of the replica aborts
  connections after N response bytes (RST mid-response), modeling a
  flaky NIC/conntrack path between gateway and replica.
- **Catalog flap**: the discovery backend transiently answers with an
  empty healthy set (torn NFS read, catalog restart). The gateway's
  hold-down must damp it instead of wiping its routing table.
- **Slow boot**: every replica launched AFTER the fault arms takes an
  extra N seconds in warmup (injected through the serve-side
  ``chaos_hook`` seam, attributed as ``compile_warmup`` in the
  device-time ledger) — the production shape of a cold scale-up
  paying image pull + weight load + XLA compile mid-burst, and the
  fault the warm-standby pool (fleet/standby.py) exists to mask:
  promotion skips the slow boot entirely while the background refill
  pays it off the critical path.

Faults are declarative ``(at_s, kind, target)`` records; the scenario
runner applies each when the trace clock passes ``at_s`` and logs it
into the report's fault ledger.
"""
from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..discovery import Backend, ServiceInstance, ServiceRegistration

log = logging.getLogger("containerpilot.chaos")


class FlakyBackend(Backend):
    """Delegating discovery backend that can serve a bounded run of
    empty reads — the gateway-visible shape of a torn catalog read or
    a catalog server restart. Registration/TTL verbs pass through
    untouched (members keep heartbeating the real catalog; only the
    reader flaps, which is how NFS tears actually present)."""

    def __init__(self, inner: Backend) -> None:
        self.inner = inner
        self._empty_reads_left = 0
        self.flaps_served = 0

    def flap(self, polls: int) -> None:
        """Serve the next ``polls`` poll cycles an empty healthy set."""
        self._empty_reads_left = polls

    # -- reader surface (flappable) ---------------------------------

    def check_for_upstream_changes(
        self, service_name: str, tag: str = "", dc: str = ""
    ) -> Tuple[bool, bool]:
        if self._empty_reads_left > 0:
            # a torn read looks like "everything vanished": report a
            # change to an empty healthy set. The budget is consumed
            # by instances() — one poll cycle is check + re-list, and
            # reporting a change guarantees the gateway re-lists.
            return True, False
        return self.inner.check_for_upstream_changes(
            service_name, tag, dc
        )

    def instances(
        self, service_name: str, tag: str = ""
    ) -> List[ServiceInstance]:
        if self._empty_reads_left > 0:
            self._empty_reads_left -= 1
            self.flaps_served += 1
            return []
        return self.inner.instances(service_name, tag)

    # -- writer surface (pass-through) -------------------------------

    def service_register(
        self, registration: ServiceRegistration, status: str = ""
    ) -> None:
        self.inner.service_register(registration, status)

    def service_deregister(self, service_id: str) -> None:
        self.inner.service_deregister(service_id)

    def update_ttl(self, check_id: str, output: str, status: str) -> None:
        self.inner.update_ttl(check_id, output, status)


class ChaosProxy:
    """TCP forwarder between the gateway and one replica that can
    inject transport loss: when armed, each connection's server->client
    relay aborts (RST, not FIN) after forwarding ``reset_after_bytes``
    response bytes. Registered in the catalog in the replica's place,
    so the gateway dials through it without knowing."""

    def __init__(
        self, target_host: str, target_port: int, host: str = "127.0.0.1"
    ) -> None:
        self.target_host = target_host
        self.target_port = target_port
        self.host = host
        self.port = 0
        self.reset_after_bytes: Optional[int] = None
        self.resets_injected = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: List[asyncio.StreamWriter] = []

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for writer in list(self._conns):
                writer.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(
        self,
        client_reader: asyncio.StreamReader,
        client_writer: asyncio.StreamWriter,
    ) -> None:
        try:
            upstream_reader, upstream_writer = (
                await asyncio.open_connection(
                    self.target_host, self.target_port
                )
            )
        except OSError:
            client_writer.close()
            return
        self._conns.extend((client_writer, upstream_writer))
        # the response side carries the injected fault; the request
        # side forwards verbatim
        up = asyncio.ensure_future(
            self._relay(client_reader, upstream_writer)
        )
        down = asyncio.ensure_future(
            self._relay(
                upstream_reader, client_writer,
                limit_writer=client_writer,
            )
        )
        try:
            await asyncio.gather(up, down, return_exceptions=True)
        finally:
            for writer in (client_writer, upstream_writer):
                try:
                    writer.close()
                except Exception:  # cpcheck: disable=CP-SWALLOW — teardown guard: socket already dead
                    pass
                if writer in self._conns:
                    self._conns.remove(writer)

    async def _relay(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        limit_writer: Optional[asyncio.StreamWriter] = None,
    ) -> None:
        """Pump bytes until EOF. When this is the response direction
        (``limit_writer`` set) and the proxy is armed, abort after the
        byte budget — transport.abort() sends an RST so the gateway
        sees a hard connection reset, not a tidy FIN."""
        forwarded = 0
        try:
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    break
                budget = (
                    self.reset_after_bytes
                    if limit_writer is not None else None
                )
                if budget is not None and forwarded + len(chunk) > budget:
                    writer.write(chunk[: max(0, budget - forwarded)])
                    await writer.drain()
                    self.resets_injected += 1
                    limit_writer.transport.abort()
                    return
                forwarded += len(chunk)
                writer.write(chunk)
                await writer.drain()
        except (OSError, asyncio.CancelledError):
            return
        finally:
            try:
                writer.write_eof()
            except (OSError, RuntimeError):
                return


@dataclass(frozen=True)
class Fault:
    """One scheduled fault. ``kind`` selects the harness verb; the
    scenario runner applies it when the trace clock passes ``at_s``."""

    at_s: float
    kind: str  # kill | wedge | unwedge | slow | slow_boot | lossy | flap
    replica: int = 0
    #: kind-specific magnitude: slow -> delay seconds; slow_boot ->
    #: warmup delay seconds for replicas launched after it arms (0
    #: disarms); lossy -> reset after this many response bytes (0
    #: disarms); flap -> poll count
    value: float = 0.0
