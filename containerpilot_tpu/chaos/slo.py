"""SLO accounting and the goodput metric for chaos scenarios.

Raw QPS is the wrong yardstick for a fleet under fire: a gateway that
answers every request with a fast 503 has great QPS and zero value.
Following the ML-fleet-efficiency framing (PAPERS.md: "ML Productivity
Goodput"), the harness scores **goodput** — useful work that met its
SLOs per unit wall time:

- **TTFT** (time to first token): request start to the first response
  byte (buffered) or first SSE data event (streams).
- **TPOT** (time per output token): residual stream time divided by
  the tokens after the first — the decode-rate half of the SLO.
- A request is **good** when it returned 200, met both SLO bounds,
  and was not truncated by a transport fault. Abandoned streams are
  the client's choice, not a failure: they are good if the events
  delivered before the hangup met TTFT.
- A request is **shed** when admission control refused it honestly —
  a final 429 (overload/session shed) or 504 (queued past its TTFT
  deadline) carrying a Retry-After. Sheds are the overload design
  WORKING, not the fleet failing: they are never good, but they are
  counted apart from 5xx failures and excluded from the failure
  ledger. ``goodput_fraction_admitted`` judges serving quality over
  the requests the fleet accepted **on first contact** (no shed, no
  client retry): their latency is bounded by the admission deadline
  plus service time, so the metric isolates the fleet's serving
  discipline from the (wall-clock-noisy) shed-retry dance, which is
  already accounted under ``sheds``/``client_retries``.

``goodput_rps`` = good requests / wall seconds; ``goodput_fraction``
= good / issued. 5xx counts are tracked separately because several
invariants pin them to exactly zero.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..telemetry.tracing import dominant_stage


@dataclass
class SLO:
    """Per-request latency bounds a scenario scores against. The
    defaults fit the tiny CPU-lab model the harness boots: its decode
    is milliseconds per token, so an entire quick scenario clears
    them unless a fault actually bites."""

    ttft_s: float = 2.0
    tpot_s: float = 0.5


@dataclass
class RequestRecord:
    """Outcome of one trace request, as the load driver observed it."""

    index: int
    session_id: str
    started_s: float
    finished_s: float
    status: int = 0
    ttft_s: Optional[float] = None
    tokens_out: int = 0
    stream: bool = False
    abandoned: bool = False
    #: transport-level failure talking to the GATEWAY (connection
    #: refused/reset): counted as bad, distinctly from a 5xx answer
    error: str = ""
    #: a stream that started but ended without its terminal event and
    #: without the client hanging up (upstream died mid-relay)
    truncated: bool = False
    #: final answer was an honest overload refusal (429/504 with
    #: Retry-After): counted apart from failures
    shed: bool = False
    #: the last response carried a Retry-After header
    retry_after_quoted: bool = False
    #: Retry-After-honoring re-sends the client performed
    client_retries: int = 0
    #: ANY attempt answered a non-shed 5xx (e.g. a 503 later retried
    #: to a 200): still client-VISIBLE, so zero-5xx invariants count
    #: it — polite client retries must not mask a gateway regression
    saw_5xx: bool = False
    #: trace id the gateway stamped on the final answer (X-CP-Trace):
    #: the handle that finds this request in /v1/traces and in
    #: trace-id-correlated logs, refusals included
    trace_id: str = ""
    #: per-stage seconds from the final answer's span digest
    #: (admission_queue_wait, upstream_ttfb, replica.decode, ...) —
    #: Retry-After parking is folded into admission_queue_wait by the
    #: client, since both are admission-imposed wait
    stages: Dict[str, float] = field(default_factory=dict)

    def tpot(self) -> Optional[float]:
        if self.ttft_s is None or self.tokens_out <= 1:
            return None
        span = (self.finished_s - self.started_s) - self.ttft_s
        return max(span, 0.0) / (self.tokens_out - 1)

    def is_good(self, slo: SLO) -> bool:
        if self.error or self.truncated or self.shed:
            return False
        if self.status != 200:
            return False
        if self.ttft_s is None or self.ttft_s > slo.ttft_s:
            return False
        if self.abandoned:
            # the client hung up by choice: judge only TTFT — a TPOT
            # over the tiny delivered window is noise, not decode rate
            return True
        tpot = self.tpot()
        return tpot is None or tpot <= slo.tpot_s

    def violation_class(self, slo: SLO) -> Optional[str]:
        """Which SLO this request violated — the triage ledger's
        grouping key — or None for good requests and honest sheds.
        One class per record, checked in failure-severity order (a
        transport error that ALSO missed TTFT is a transport error)."""
        if self.shed or self.is_good(slo):
            return None
        if self.error:
            return "transport"
        if self.truncated:
            return "truncated"
        if self.status != 200:
            return "5xx" if 500 <= self.status <= 599 else "bad_status"
        if self.ttft_s is None or self.ttft_s > slo.ttft_s:
            return "ttft"
        return "tpot"


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile, deterministic and dependency-free."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(int(q * len(ordered)), len(ordered) - 1)
    return ordered[idx]


@dataclass
class ScenarioScore:
    """Aggregated scenario outcome; ``as_dict`` is the JSON report."""

    records: List[RequestRecord]
    wall_s: float
    slo: SLO = field(default_factory=SLO)

    def as_dict(self) -> Dict[str, Any]:
        records = self.records
        good = [r for r in records if r.is_good(self.slo)]
        sheds = [r for r in records if r.shed]
        # first-contact admissions: no shed, no Retry-After retry —
        # the set whose latency the fleet fully controls
        first_contact = [
            r for r in records
            if not r.shed and r.client_retries == 0
        ]
        good_first = [r for r in first_contact if r.is_good(self.slo)]
        # latency percentiles describe SERVING, so a shed's
        # millisecond-fast refusal must not drag them down
        ttfts = [
            r.ttft_s
            for r in records
            if r.ttft_s is not None and not r.shed
        ]
        tpots = [t for r in records if (t := r.tpot()) is not None]
        statuses: Dict[str, int] = {}
        for r in records:
            key = str(r.status) if not r.error else "error"
            statuses[key] = statuses.get(key, 0) + 1
        wall = max(self.wall_s, 1e-9)
        return {
            "requests": len(records),
            "good": len(good),
            "goodput_rps": round(len(good) / wall, 3),
            "goodput_fraction": round(
                len(good) / len(records), 4
            ) if records else None,
            # serving quality over first-contact admissions: the
            # number burst invariants gate on while sheds absorb the
            # overload
            "goodput_fraction_admitted": round(
                len(good_first) / len(first_contact), 4
            ) if first_contact else None,
            "sheds": len(sheds),
            "shed_429": sum(1 for r in sheds if r.status == 429),
            "shed_504": sum(1 for r in sheds if r.status == 504),
            "client_retries": sum(r.client_retries for r in records),
            "wall_s": round(self.wall_s, 3),
            "slo": {"ttft_s": self.slo.ttft_s, "tpot_s": self.slo.tpot_s},
            "ttft_ms": {
                "p50": _ms(percentile(ttfts, 0.50)),
                "p95": _ms(percentile(ttfts, 0.95)),
                "p99": _ms(percentile(ttfts, 0.99)),
            },
            "tpot_ms": {
                "p50": _ms(percentile(tpots, 0.50)),
                "p95": _ms(percentile(tpots, 0.95)),
                "p99": _ms(percentile(tpots, 0.99)),
            },
            "statuses": dict(sorted(statuses.items())),
            # sheds (an honest 504 at the admission deadline) are the
            # overload defense working; 5xx here means FAILURE — and a
            # 5xx ANY attempt saw counts even when a polite retry
            # turned the final answer into a 200
            "count_5xx": sum(
                1 for r in records
                if r.saw_5xx
                or (500 <= r.status <= 599 and not r.shed)
            ),
            "transport_errors": sum(1 for r in records if r.error),
            "truncated_streams": sum(1 for r in records if r.truncated),
            "abandoned_streams": sum(1 for r in records if r.abandoned),
            "tokens_out": sum(r.tokens_out for r in records),
            # triage ledger: the first few non-good requests with
            # enough detail to replay them (trace index + session)
            # AND to blame them — the gateway trace id, the per-stage
            # latency breakdown off the span digest, and the stage
            # that dominated ("goodput dropped" becomes "goodput
            # dropped HERE")
            "failures": [
                {
                    "index": r.index,
                    "session": r.session_id,
                    "status": r.status,
                    "error": r.error,
                    "ttft_ms": _ms(r.ttft_s),
                    "truncated": r.truncated,
                    "class": r.violation_class(self.slo),
                    "trace": r.trace_id,
                    "stages_ms": {
                        stage: _ms(dur)
                        for stage, dur in sorted(r.stages.items())
                    },
                    "dominant_stage": dominant_stage(r.stages),
                }
                for r in records
                if not r.is_good(self.slo)
                and not r.abandoned
                and not r.shed
            ][:8],
            # the aggregate face of the same blame: per violation
            # class, the stage that ate the violated requests' time
            "stage_attribution": self._stage_attribution(),
        }

    def _stage_attribution(self) -> Dict[str, Dict[str, Any]]:
        """Per violation class: how many requests, the summed
        per-stage seconds across them, and the DOMINANT stage (the
        refinement discipline lives in tracing.dominant_stage: nested
        ``replica.*`` spans refine their upstream window rather than
        double-count it). The scenario report names this stage, and
        scenario specs can pin it (``expect_dominant_stage``)."""
        buckets: Dict[str, List[RequestRecord]] = {}
        for record in self.records:
            cls = record.violation_class(self.slo)
            if cls is not None:
                buckets.setdefault(cls, []).append(record)
        out: Dict[str, Dict[str, Any]] = {}
        for cls, violated in sorted(buckets.items()):
            totals: Dict[str, float] = {}
            traced = 0
            for record in violated:
                if record.stages:
                    traced += 1
                for stage, dur in record.stages.items():
                    totals[stage] = totals.get(stage, 0.0) + dur
            out[cls] = {
                "count": len(violated),
                "with_stage_data": traced,
                "dominant": dominant_stage(totals),
                "stages_ms": {
                    stage: _ms(dur)
                    for stage, dur in sorted(totals.items())
                },
            }
        return out


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 2)
