"""Trace-driven load + chaos harness, gated on SLO-goodput.

The fleet's adversarial proving ground: replay realistic multi-tenant
traffic (trace.py) through a real gateway-fronted replica fleet while
injecting the faults members actually die of (faults.py) — replica
SIGKILL, wedged health checks, brownouts, lossy transport, catalog
flaps — and judge the run by goodput, the fraction of work meeting
TTFT/TPOT SLOs per wall-second (slo.py), not raw QPS.

``python -m containerpilot_tpu.chaos`` runs scenarios from the
registry (scenarios.py); ``make chaos-smoke`` runs the quick suite.
Quick scenarios also run in tier-1 (tests/test_chaos.py), so the
zero-5xx-under-fire invariants gate every PR the way racecheck gates
races. See docs/80-chaos.md.
"""
from .faults import ChaosProxy, Fault, FlakyBackend
from .slo import SLO, RequestRecord, ScenarioScore, percentile
from .scenarios import (
    SCENARIOS,
    FleetHarness,
    ScenarioSpec,
    full_scenarios,
    quick_scenarios,
    run_scenario,
    run_scenario_async,
)
from .trace import TraceConfig, TraceRequest, generate_trace, trace_summary

__all__ = [
    "SCENARIOS",
    "SLO",
    "ChaosProxy",
    "Fault",
    "FlakyBackend",
    "FleetHarness",
    "RequestRecord",
    "ScenarioScore",
    "ScenarioSpec",
    "TraceConfig",
    "TraceRequest",
    "full_scenarios",
    "generate_trace",
    "percentile",
    "quick_scenarios",
    "run_scenario",
    "run_scenario_async",
    "trace_summary",
]
