"""CLI: ``python -m containerpilot_tpu.chaos`` — run chaos scenarios.

    # one scenario, seeded, report to stdout
    python -m containerpilot_tpu.chaos --scenario kill_spare --seed 7

    # the quick suite (the `make chaos-smoke` body), report to a file
    python -m containerpilot_tpu.chaos --suite quick --json report.json

    # everything, including the slow compound-fault marathons
    python -m containerpilot_tpu.chaos --suite full

Exit status: 0 when every scenario's invariants passed, 1 otherwise
(the report still prints — a failed run's evidence is the point).
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
from typing import List, Optional

from .scenarios import (
    SCENARIOS,
    full_scenarios,
    quick_scenarios,
    run_scenario,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m containerpilot_tpu.chaos",
        description="trace-driven load + chaos scenarios, "
        "scored on SLO-goodput",
    )
    parser.add_argument(
        "--scenario", action="append", default=[],
        help="scenario name (repeatable); see --list",
    )
    parser.add_argument(
        "--suite", choices=("quick", "full"), default=None,
        help="run a whole suite instead of named scenarios",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--json", metavar="PATH", default=None,
        help="write the JSON report here ('-' for stdout; default: "
        "pretty-print a summary)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    args = parser.parse_args(argv)

    if args.list:
        for name, spec in SCENARIOS.items():
            tier = "quick" if spec.quick else "slow "
            print(f"{tier}  {name:<18} {spec.description}")
        return 0

    names = list(args.scenario)
    if args.suite == "quick":
        names += quick_scenarios()
    elif args.suite == "full":
        names += full_scenarios()
    if not names:
        names = quick_scenarios()
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        parser.error(
            f"unknown scenario(s) {unknown}; --list shows the registry"
        )

    reports = []
    for name in names:
        with tempfile.TemporaryDirectory(prefix="chaos-catalog-") as d:
            report = run_scenario(name, d, seed=args.seed)
        reports.append(report)
        verdict = "PASS" if report["passed"] else "FAIL"
        print(
            f"[{verdict}] {name}: goodput "
            f"{report['score']['goodput_fraction']} "
            f"({report['score']['goodput_rps']} rps), "
            f"5xx={report['score']['count_5xx']}, "
            f"requests={report['score']['requests']}, "
            f"loop_lag_max={report['loop_lag_max_ms']}ms",
            file=sys.stderr,
        )
        for check in report["checks"]:
            if not check["ok"]:
                print(
                    f"       FAILED {check['name']}: {check['detail']}",
                    file=sys.stderr,
                )

    passed = all(r["passed"] for r in reports)
    payload = {
        "suite": args.suite or "named",
        "seed": args.seed,
        "passed": passed,
        "scenarios": reports,
    }
    if args.json == "-":
        print(json.dumps(payload, indent=2))
    elif args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
        print(f"report -> {args.json}", file=sys.stderr)
    else:
        print(json.dumps(payload, indent=2))
    return 0 if passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
