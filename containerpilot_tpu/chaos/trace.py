"""Trace-driven workload generation for the chaos harness.

Scenario diversity used to be whatever each test constructed by hand:
uniform arrivals, one prompt shape, no cancellations. Real multi-tenant
serving traffic looks nothing like that, and the failure modes the
fleet must survive (retry storms, hedges firing into a burst, a drain
racing a long-tail generation) only show up under realistic load. This
module generates that load deterministically:

- **Multi-tenant chat sessions with shared prefixes.** Each tenant has
  a system-prompt prefix and each session extends it; successive turns
  of a session share the session prefix (what prefix caches and sticky
  affinity exist for). Requests carry ``session_id`` so the gateway's
  affinity path is exercised, not bypassed.
- **Bursty Poisson arrivals.** A two-state modulated Poisson process
  (quiet/burst, exponential dwell times): inter-arrival gaps are
  exponential at ``mean_rps`` in the quiet state and ``mean_rps *
  burst_factor`` inside bursts. Fleet-killing load is bursty load; a
  constant-rate generator never synchronizes retries.
- **Long-tail lengths.** Prompt and output lengths are lognormal
  (capped), so a few requests decode for much longer than the median —
  the rows a drain or kill is most likely to catch in flight.
- **Abandoned streams.** A fraction of streaming requests hang up
  mid-stream after a few events, driving the replica's cancel path and
  the gateway's mid-stream disconnect relay.

Everything derives from one ``random.Random(seed)``: the same seed
yields byte-identical traces (arrival times, token ids, per-request
seeds), so every chaos run is reproducible and every regression is
replayable.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass(frozen=True)
class TraceConfig:
    """Knobs for one generated trace. Defaults fit the tiny CPU-lab
    model (vocab 64, max_len 64) the scenario harness boots."""

    seed: int = 0
    duration_s: float = 4.0
    mean_rps: float = 10.0
    burst_factor: float = 4.0
    #: mean dwell (seconds) in the quiet / burst arrival states
    quiet_dwell_s: float = 1.0
    burst_dwell_s: float = 0.4
    tenants: int = 3
    sessions_per_tenant: int = 3
    #: lognormal prompt/output length parameters (median, sigma)
    prompt_median: int = 8
    prompt_sigma: float = 0.6
    output_median: int = 6
    output_sigma: float = 0.5
    #: hard caps so a tail sample can't exceed the model's max_len
    max_prompt: int = 24
    max_output: int = 16
    #: prompt lengths snap UP to a multiple of this. Static-shape
    #: serving compiles one prefill program per distinct prompt
    #: length; quantizing keeps a scenario's compile set bounded (the
    #: harness pre-warms each bucket) while the lognormal tail still
    #: spreads requests across buckets. 0 disables snapping.
    prompt_quantum: int = 8
    #: shared-prefix structure: tenant prefix + per-session extension
    tenant_prefix: int = 4
    session_prefix: int = 4
    stream_fraction: float = 0.25
    #: of the streaming requests, how many hang up mid-stream
    abandon_fraction: float = 0.3
    #: fraction of requests tagged batch priority (X-Priority: batch)
    #: — the work admission control sheds FIRST in a burst. 0 draws
    #: nothing from the rng, so existing traces stay byte-identical.
    batch_fraction: float = 0.0
    vocab: int = 64
    #: multi-turn chat mode: each session is a CONVERSATION — turn
    #: k+1's prompt is turn k's prompt plus a simulated assistant
    #: reply plus fresh user tokens, so successive turns share an
    #: ever-growing prefix (what the KV spill tier and cache-aware
    #: routing exist for). False draws nothing from the rng, so
    #: pre-existing traces replay byte-identically.
    multiturn: bool = False
    turns_per_session: int = 4
    #: mean exponential think time between a turn's arrival and the
    #: next turn of the same session
    think_time_s: float = 0.35
    #: hard floor under every think gap (the exponential puts heavy
    #: mass near zero, where turn k+1 would arrive before turn k even
    #: completes — real users read the answer first)
    think_floor_s: float = 0.0
    #: first-turn prompt floor: at least this many ids, so the shared
    #: prefix clears the reuse threshold (serve_prefix.MIN_REUSE)
    #: from turn 2 on
    first_turn_min: int = 16
    #: simulated assistant-reply ids appended to the history per turn
    reply_median: int = 4


@dataclass
class TraceRequest:
    """One request in a trace: everything the load driver needs to
    issue it and everything the scorer needs to judge it."""

    index: int
    at_s: float
    session_id: str
    tenant: int
    tokens: List[int]
    max_new_tokens: int
    seed: int
    stream: bool = False
    #: for streams: hang up after this many SSE data events (None =
    #: read to completion)
    abandon_after_events: Optional[int] = None
    in_burst: bool = False
    #: admission priority class ("interactive" | "batch"), sent as
    #: the X-Priority header
    priority: str = "interactive"

    def payload(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            "tokens": [self.tokens],
            "max_new_tokens": self.max_new_tokens,
            "seed": self.seed,
            "session_id": self.session_id,
        }
        if self.stream:
            body["stream"] = True
        return body


def _lognormal_len(
    rng: random.Random, median: int, sigma: float, lo: int, hi: int
) -> int:
    """A capped lognormal sample: median * e^(sigma * N(0,1))."""
    value = int(round(median * rng.lognormvariate(0.0, sigma)))
    return max(lo, min(hi, value))


def generate_trace(cfg: TraceConfig) -> List[TraceRequest]:
    """Generate the full request list for one scenario run, sorted by
    arrival time. Pure function of ``cfg`` (seed included)."""
    rng = random.Random(cfg.seed)
    # per-tenant and per-session shared prefixes, fixed for the trace
    tenant_prefixes = [
        [rng.randrange(1, cfg.vocab) for _ in range(cfg.tenant_prefix)]
        for _ in range(cfg.tenants)
    ]
    session_prefixes: Dict[str, List[int]] = {}
    for tenant in range(cfg.tenants):
        for s in range(cfg.sessions_per_tenant):
            session_prefixes[f"t{tenant}-s{s}"] = tenant_prefixes[
                tenant
            ] + [rng.randrange(1, cfg.vocab) for _ in range(cfg.session_prefix)]

    if cfg.multiturn:
        return _generate_multiturn(cfg, rng, session_prefixes)

    requests: List[TraceRequest] = []
    now = 0.0
    in_burst = False
    state_until = rng.expovariate(1.0 / cfg.quiet_dwell_s)
    index = 0
    while now < cfg.duration_s:
        rate = cfg.mean_rps * (cfg.burst_factor if in_burst else 1.0)
        now += rng.expovariate(rate)
        while now > state_until:
            in_burst = not in_burst
            dwell = cfg.burst_dwell_s if in_burst else cfg.quiet_dwell_s
            state_until += rng.expovariate(1.0 / dwell)
        if now >= cfg.duration_s:
            break
        tenant = rng.randrange(cfg.tenants)
        session = f"t{tenant}-s{rng.randrange(cfg.sessions_per_tenant)}"
        prefix = session_prefixes[session]
        fresh = _lognormal_len(
            rng, cfg.prompt_median, cfg.prompt_sigma,
            1, max(1, cfg.max_prompt - len(prefix)),
        )
        total = len(prefix) + fresh
        if cfg.prompt_quantum > 0:
            q = cfg.prompt_quantum
            total = min(-(-total // q) * q, cfg.max_prompt)
            total = max(total, len(prefix) + 1)
        tokens = prefix + [
            rng.randrange(1, cfg.vocab)
            for _ in range(total - len(prefix))
        ]
        max_new = _lognormal_len(
            rng, cfg.output_median, cfg.output_sigma, 1, cfg.max_output
        )
        stream = rng.random() < cfg.stream_fraction
        abandon: Optional[int] = None
        if stream and rng.random() < cfg.abandon_fraction:
            abandon = 1 + rng.randrange(2)
        # guarded draw: batch_fraction == 0 consumes no randomness,
        # so pre-existing scenario traces replay byte-identically
        priority = "interactive"
        if cfg.batch_fraction > 0 and rng.random() < cfg.batch_fraction:
            priority = "batch"
        requests.append(
            TraceRequest(
                index=index,
                at_s=round(now, 6),
                session_id=session,
                tenant=tenant,
                tokens=tokens,
                max_new_tokens=max_new,
                seed=cfg.seed * 100003 + index,
                stream=stream,
                abandon_after_events=abandon,
                in_burst=in_burst,
                priority=priority,
            )
        )
        index += 1
    return requests


def _generate_multiturn(
    cfg: TraceConfig,
    rng: random.Random,
    session_prefixes: Dict[str, List[int]],
) -> List[TraceRequest]:
    """Multi-turn chat sessions: each turn re-sends the whole
    conversation so far (prior prompt + a simulated assistant reply)
    plus fresh user tokens, the regime where prefix KV reuse pays.
    The simulated reply STANDS IN for the model's actual output — the
    replica never checks that history matches what it generated, and
    the trace must be a pure function of the seed. Session starts
    spread over the first ``duration_s``; turns follow their
    predecessor by an exponential think time. Prompt growth stops at
    ``max_prompt`` (the conversation is truncated, like a real
    context-window limit); quantization pads with EXTRA user ids so
    the prefix-of-its-successor property always holds."""
    requests: List[TraceRequest] = []
    index = 0
    for session in sorted(session_prefixes):
        tenant = int(session[1:].split("-", 1)[0])
        history = list(session_prefixes[session])
        # first turn: pad with user ids up to the reuse floor, then
        # quantize UP (appending keeps every prefix shared)
        first = max(
            cfg.first_turn_min,
            len(history) + 1,
        )
        if cfg.prompt_quantum > 0:
            q = cfg.prompt_quantum
            first = min(-(-first // q) * q, cfg.max_prompt)
        while len(history) < first:
            history.append(rng.randrange(1, cfg.vocab))
        at_s = rng.uniform(0.0, cfg.duration_s)
        for _turn in range(cfg.turns_per_session):
            max_new = _lognormal_len(
                rng, cfg.output_median, cfg.output_sigma,
                1, cfg.max_output,
            )
            stream = rng.random() < cfg.stream_fraction
            abandon: Optional[int] = None
            if stream and rng.random() < cfg.abandon_fraction:
                abandon = 1 + rng.randrange(2)
            priority = "interactive"
            if (
                cfg.batch_fraction > 0
                and rng.random() < cfg.batch_fraction
            ):
                priority = "batch"
            requests.append(
                TraceRequest(
                    index=index,
                    at_s=round(at_s, 6),
                    session_id=session,
                    tenant=tenant,
                    tokens=list(history),
                    max_new_tokens=max_new,
                    seed=cfg.seed * 100003 + index,
                    stream=stream,
                    abandon_after_events=abandon,
                    priority=priority,
                )
            )
            index += 1
            # grow the conversation: simulated reply + next user turn
            reply = _lognormal_len(
                rng, cfg.reply_median, cfg.output_sigma, 1,
                cfg.max_output,
            )
            user = _lognormal_len(
                rng, cfg.prompt_median, cfg.prompt_sigma, 1,
                cfg.max_prompt,
            )
            total = len(history) + reply + user
            if cfg.prompt_quantum > 0:
                q = cfg.prompt_quantum
                total = -(-total // q) * q
            if total > cfg.max_prompt:
                break  # context window full: the conversation ends
            while len(history) < total:
                history.append(rng.randrange(1, cfg.vocab))
            at_s += cfg.think_floor_s + rng.expovariate(
                1.0 / cfg.think_time_s
            )
    requests.sort(key=lambda r: (r.at_s, r.index))
    # re-index in arrival order so index stays the replay handle;
    # per-request seeds were already assigned deterministically
    for i, req in enumerate(requests):
        req.index = i
    return requests


def trace_summary(requests: List[TraceRequest]) -> Dict[str, Any]:
    """Shape of a trace for reports and determinism checks."""
    if not requests:
        return {
            "requests": 0, "streams": 0, "batch": 0, "abandons": 0,
            "burst_requests": 0, "sessions": 0,
            "max_prompt_len": 0, "max_new_total": 0,
        }
    return {
        "requests": len(requests),
        "streams": sum(1 for r in requests if r.stream),
        "batch": sum(1 for r in requests if r.priority == "batch"),
        "abandons": sum(
            1 for r in requests if r.abandon_after_events is not None
        ),
        "burst_requests": sum(1 for r in requests if r.in_burst),
        "sessions": len({r.session_id for r in requests}),
        "max_prompt_len": max(len(r.tokens) for r in requests),
        "max_new_total": sum(r.max_new_tokens for r in requests),
    }
