"""CLI entrypoint (reference: main.go).

- As PID 1 (container entrypoint) we first become the init/reaper and
  fork the real supervisor (reference: main.go:23-27).
- With a subcommand flag we run the one-shot verb.
- Otherwise we run the supervisor's generation loop until shutdown.
"""
from __future__ import annotations

import asyncio
import logging
import os
import sys


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s [%(levelname)s] %(message)s",
    )
    if os.getpid() == 1 and os.environ.get("CONTAINERPILOT_SUP", "1") != "0":
        from .sup import run_sup

        # mark the forked worker so it doesn't recurse into sup mode
        os.environ["CONTAINERPILOT_SUP"] = "0"
        return run_sup(sys.argv if argv is None else ["containerpilot"] + list(argv))

    from .core import App, get_args

    handler, params = get_args(argv)
    if handler is not None:
        return handler(params)

    config_path = params["config_path"]
    try:
        app = App.from_config_path(config_path)
    except Exception as exc:
        print(f"{exc}", file=sys.stderr)
        return 1
    try:
        asyncio.run(app.run())
    except KeyboardInterrupt:  # pragma: no cover
        return 130
    except OSError as exc:
        # e.g. telemetry/control bind exhausting its retries — a clean
        # one-line fatal beats an asyncio traceback; the full trace
        # still lands in the log for diagnosis
        logging.getLogger("containerpilot").exception("fatal error")
        print(f"fatal: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
