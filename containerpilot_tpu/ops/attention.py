"""Causal attention: the XLA einsum path.

- ``causal_attention``: plain einsum + masked softmax. XLA fuses this
  well at moderate sequence lengths and it's fully differentiable.
- The pallas flash kernels (forward + backward, KV streamed through
  the grid) live in ops/flash.py; ``flash_attention_forward`` is
  re-exported here for compatibility.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .flash import NEG_INF, flash_attention_forward  # noqa: F401

__all__ = ["NEG_INF", "causal_attention", "flash_attention_forward"]


def causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, window: int = 0
) -> jax.Array:
    """[batch, seq, heads, head_dim] -> same; causal masked softmax.

    ``window > 0`` limits each query to the last ``window`` keys
    (sliding-window / Mistral-style local attention): position i
    attends j iff ``i - window < j <= i``.
    """
    *_b, s, _h, hd = q.shape
    scale = hd ** -0.5
    scores = jnp.einsum(
        "bqhk,bshk->bhqs", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    if window > 0:
        idx = jnp.arange(s)
        mask &= idx[None, :] > idx[:, None] - window
    scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bhqs,bshk->bqhk", weights, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
