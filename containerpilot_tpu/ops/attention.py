"""Causal attention: XLA einsum path + a pallas flash-attention kernel.

Two implementations with identical numerics:

- ``causal_attention``: plain einsum + masked softmax. XLA fuses this
  well at moderate sequence lengths and it's fully differentiable — the
  training path uses it.
- ``flash_attention_forward``: a pallas TPU kernel with blockwise
  online softmax — O(seq) memory instead of O(seq^2), for long-context
  inference. Grid is (batch*heads, q_blocks); each program streams KV
  blocks through VMEM with running (max, sum) rescaling. Runs in
  interpret mode off-TPU so tests cover it on the CPU mesh.

The kernel follows the standard flash-attention algorithm structure
(public technique; see PAPERS.md) implemented fresh against the pallas
API.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array
) -> jax.Array:
    """[batch, seq, heads, head_dim] -> same; causal masked softmax."""
    *_b, s, _h, hd = q.shape
    scale = hd ** -0.5
    scores = jnp.einsum(
        "bqhk,bshk->bhqs", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bhqs,bshk->bqhk", weights, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_len: int,
                  scale: float):
    """One (batch*head, q_block) program: stream KV blocks, online
    softmax with running max/sum."""
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, head_dim]
    block_q = q.shape[0]
    q_block_idx = pl.program_id(1)
    q_offset = q_block_idx * block_q

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)   # running max
    l = jnp.zeros((block_q, 1), jnp.float32)           # running sum
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    n_kv_blocks = seq_len // block_k

    def body(kv_idx, carry):
        m, l, acc = carry
        kv_offset = kv_idx * block_k
        k_blk = k_ref[0, pl.dslice(kv_offset, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kv_offset, block_k)].astype(jnp.float32)
        scores = q @ k_blk.T  # [block_q, block_k]
        # causal mask: query position >= key position
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, scores.shape, 0)
        k_pos = kv_offset + lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + p @ v_blk
        return m_new, l_new, acc_new

    # only blocks at or before this q block can contribute (causal)
    last_block = jnp.minimum((q_offset + block_q + block_k - 1) // block_k,
                             n_kv_blocks)
    m, l, acc = lax.fori_loop(0, last_block, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_attention_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention forward. [batch, seq, heads, head_dim] layout.

    Sequence length must be a multiple of the block sizes (pad upstream
    for ragged lengths — static shapes keep the MXU tiling clean).
    """
    b, s, h, hd = q.shape
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} not a multiple of block sizes")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # [b, s, h, hd] -> [b*h, s, hd]: one grid row per (batch, head)
    def to_rows(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, hd)

    qr, kr, vr = to_rows(q), to_rows(k), to_rows(v)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, seq_len=s, scale=hd ** -0.5
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda r, i: (r, i, 0)),
            pl.BlockSpec((1, s, hd), lambda r, i: (r, 0, 0)),
            pl.BlockSpec((1, s, hd), lambda r, i: (r, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), lambda r, i: (r, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, hd), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, s, hd).transpose(0, 2, 1, 3)
