"""Pallas flash attention, forward AND backward, with grid-streamed KV.

This is the TPU training/long-context kernel family. Three kernels:

- forward: online-softmax over a (batch*heads, q_blocks, kv_blocks)
  grid. KV blocks arrive through the grid's innermost axis via the
  BlockSpec index_map — each program holds ONE [block_k, head_dim] K/V
  tile in VMEM, never the full row, so a 32k-sequence forward fits
  comfortably in v5e VMEM (the round-1 kernel pinned the whole K/V row:
  ~16 MB at 32k/hd128). The online-softmax carry (running max, running
  sum, output accumulator) lives in VMEM scratch, which persists across
  the sequential innermost grid axis. The forward also emits the
  per-row logsumexp needed by the backward.
- backward dq: same grid, accumulates dQ for one q block while
  streaming KV blocks; recomputes p from (q, k, lse) — standard flash
  recomputation, nothing O(seq^2) is ever saved.
- backward dk/dv: transposed grid (batch*heads, kv_blocks, q_blocks)
  with the Q/dO/lse blocks streaming through the innermost axis,
  accumulating dK and dV for one kv block.

``flash_attention`` glues them together behind a ``jax.custom_vjp`` so
``jax.grad`` through the model trains entirely on pallas kernels. The
flash algorithm is the public technique (see PAPERS.md); the kernels
are written fresh against the pallas TPU API. Off-TPU the kernels run
in interpret mode so the CPU test mesh covers them.

The reference supervisor has no tensor code (see SURVEY.md §2); these
kernels serve the supervised TPU workload half of the framework.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _contributes(
    qi: jax.Array, ki: jax.Array, block_q: int, block_k: int,
    window: int = 0,
):
    """True iff kv block ki overlaps the causal past of q block qi —
    and, with a sliding window, is not entirely older than the window
    (the skip that makes windowed attention O(s*window) not O(s^2))."""
    causal = ki * block_k <= qi * block_q + (block_q - 1)
    if window <= 0:
        return causal
    newest_k = ki * block_k + (block_k - 1)
    oldest_needed = qi * block_q - (window - 1)
    return jnp.logical_and(causal, newest_k >= oldest_needed)


def _causal_mask(
    qi, ki, block_q: int, block_k: int, window: int = 0
) -> jax.Array:
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = q_pos >= k_pos
    if window > 0:
        mask = jnp.logical_and(mask, q_pos - k_pos < window)
    return mask


def _kv_block_base(qi, block_q: int, block_k: int, window: int,
                   total_kv: int, n_grid):
    """First kv block the windowed grid visits for q block qi (0 when
    no window). Clipped so the n_grid visited blocks are always
    in-range AND unique; non-contributing visits are masked off."""
    if window <= 0 or not total_kv:
        return 0
    first = lax.div(qi * block_q - (window - 1), block_k)
    return jnp.clip(first, 0, total_kv - n_grid)


def _windowed_kv_grid(total_kv: int, block_q: int, block_k: int,
                      window: int) -> int:
    """Number of kv blocks a q block can overlap under a window: the
    needed key span has length window + block_q - 1 and arbitrary
    alignment, so worst-case it touches
    (len + block_k - 2)//block_k + 1 blocks."""
    if window <= 0:
        return total_kv
    span = window + block_q - 1
    return min(total_kv, (span + block_k - 2) // block_k + 1)


def _q_block_base(ki, block_q: int, block_k: int, window: int,
                  total_q: int, n_grid):
    """First q block the windowed dk/dv grid visits for kv block ki
    (queries attending kv block ki span [ki*bk, ki*bk+bk-1+window-1])."""
    if window <= 0 or not total_q:
        return 0
    return jnp.clip(lax.div(ki * block_k, block_q), 0, total_q - n_grid)


def _windowed_q_grid(total_q: int, block_q: int, block_k: int,
                     window: int) -> int:
    """Number of q blocks a kv block can influence under a window
    (query span length window + block_k - 1, arbitrary alignment)."""
    if window <= 0:
        return total_q
    span = window + block_k - 1
    return min(total_q, (span + block_q - 2) // block_q + 1)


def _dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """f32 matmul on the MXU."""
    return lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_t(a: jax.Array, b: jax.Array) -> jax.Array:
    """a @ b.T without materializing the transpose."""
    return lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tt(a: jax.Array, b: jax.Array) -> jax.Array:
    """a.T @ b without materializing the transpose."""
    return lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, scale: float, window: int = 0,
    total_kv: int = 0,
):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)
    # windowed grids only span the contributing kv blocks; the real
    # block index is the per-q-block offset (same formula as the
    # BlockSpec index_map) plus the grid position
    ki = _kv_block_base(qi, block_q, block_k, window, total_kv, n_kv) + j

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(_contributes(qi, ki, block_q, block_k, window))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        scores = _dot_t(q, k)  # [block_q, block_k]
        scores = jnp.where(
            _causal_mask(qi, ki, block_q, block_k, window), scores, NEG_INF
        )
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        # rows fully masked in THIS block still carry their old max;
        # exp(NEG_INF - finite) underflows to exactly 0 as required
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + _dot(p, v)

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _fwd_rows(
    qr: jax.Array, kr: jax.Array, vr: jax.Array,
    block_q: int, block_k: int, interpret: bool, window: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """[rows, s, hd] x3 -> (out [rows, s, hd], lse [rows, s, 1] f32).

    lse keeps a trailing unit axis so its blocks are (1, block_q, 1) —
    sublane-aligned for the TPU tiling rules and broadcastable against
    [block_q, block_k] score tiles in the backward without transposes.

    Grouped-query attention: kr/vr may carry fewer rows than qr (one
    per (batch, kv_head)). With group = q_rows // kv_rows, q row
    r = b*h + head reads kv row r // group = b*kv_heads + head//group —
    exact because h = kv_heads * group. The kernel then streams each
    K/V block once per query head from HBM *without* a materialized
    repeat_kv copy.
    """
    rows, s, hd = qr.shape
    kv_rows = kr.shape[0]
    if rows % kv_rows:
        raise ValueError(
            f"q rows {rows} not a multiple of kv rows {kv_rows}"
        )
    group = rows // kv_rows
    total_kv = s // block_k
    n_kv_grid = _windowed_kv_grid(total_kv, block_q, block_k, window)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=hd ** -0.5,
        window=window, total_kv=total_kv,
    )

    def kv_map(r, i, j):
        base = _kv_block_base(i, block_q, block_k, window, total_kv,
                              n_kv_grid)
        return (r // group, base + j, 0)

    return pl.pallas_call(
        kernel,
        grid=(rows, s // block_q, n_kv_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda r, i, j: (r, i, 0)),
            pl.BlockSpec((1, block_k, hd), kv_map),
            pl.BlockSpec((1, block_k, hd), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, hd), lambda r, i, j: (r, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda r, i, j: (r, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, s, hd), qr.dtype),
            jax.ShapeDtypeStruct((rows, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),  # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref, dq_ref, acc_ref,
    *, block_q: int, block_k: int, scale: float, window: int = 0,
    total_kv: int = 0,
):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)
    ki = _kv_block_base(qi, block_q, block_k, window, total_kv, n_kv) + j

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(_contributes(qi, ki, block_q, block_k, window))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]       # [block_q, 1]
        d_rows = d_ref[0]      # [block_q, 1]
        mask = _causal_mask(qi, ki, block_q, block_k, window)
        # p_ij = exp(s_ij - lse_i), exactly the forward's normalized
        # weights (lse folds in the running max and sum)
        s = _dot_t(q, k)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = _dot_t(do, v)
        ds = p * (dp - d_rows)
        acc_ref[...] = acc_ref[...] + _dot(ds, k)

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkdv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, d_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, block_q: int, block_k: int, scale: float,
    window: int = 0, total_q: int = 0,
):
    ki = pl.program_id(1)
    j = pl.program_id(2)
    n_q = pl.num_programs(2)
    qi = _q_block_base(ki, block_q, block_k, window, total_q, n_q) + j

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_contributes(qi, ki, block_q, block_k, window))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]       # [block_q, 1]
        d_rows = d_ref[0]      # [block_q, 1]
        mask = _causal_mask(qi, ki, block_q, block_k, window)
        s = _dot_t(q, k)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc[...] = dv_acc[...] + _dot_tt(p, do)
        dp = _dot_t(do, v)
        ds = p * (dp - d_rows)
        # d(s_scaled)/dk = q*scale, already folded into q above
        dk_acc[...] = dk_acc[...] + _dot_tt(ds, q)

    @pl.when(j == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_rows(
    qr, kr, vr, do_r, lse, d_rows, block_q: int, block_k: int,
    interpret: bool, window: int = 0,
):
    rows, s, hd = qr.shape
    scale = hd ** -0.5
    total_kv = s // block_k
    total_q = s // block_q
    n_kv_grid = _windowed_kv_grid(total_kv, block_q, block_k, window)
    n_q_grid = _windowed_q_grid(total_q, block_q, block_k, window)

    def kv_map(r, i, j):
        base = _kv_block_base(i, block_q, block_k, window, total_kv,
                              n_kv_grid)
        return (r, base + j, 0)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            window=window, total_kv=total_kv,
        ),
        grid=(rows, s // block_q, n_kv_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda r, i, j: (r, i, 0)),
            pl.BlockSpec((1, block_k, hd), kv_map),
            pl.BlockSpec((1, block_k, hd), kv_map),
            pl.BlockSpec((1, block_q, hd), lambda r, i, j: (r, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda r, i, j: (r, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda r, i, j: (r, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), lambda r, i, j: (r, i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, s, hd), qr.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr, do_r, lse, d_rows)
    def q_map(r, kj, i):
        base = _q_block_base(kj, block_q, block_k, window, total_q,
                             n_q_grid)
        return (r, base + i, 0)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkdv_kernel, block_q=block_q, block_k=block_k, scale=scale,
            window=window, total_q=total_q,
        ),
        grid=(rows, s // block_k, n_q_grid),
        in_specs=[
            pl.BlockSpec((1, block_k, hd), lambda r, j, i: (r, j, 0)),
            pl.BlockSpec((1, block_k, hd), lambda r, j, i: (r, j, 0)),
            pl.BlockSpec((1, block_q, hd), q_map),
            pl.BlockSpec((1, block_q, hd), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, hd), lambda r, j, i: (r, j, 0)),
            pl.BlockSpec((1, block_k, hd), lambda r, j, i: (r, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, s, hd), kr.dtype),
            jax.ShapeDtypeStruct((rows, s, hd), vr.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(kr, vr, qr, do_r, lse, d_rows)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _to_rows(x: jax.Array) -> jax.Array:
    b, s, h, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, hd)


def _from_rows(x: jax.Array, b: int, h: int) -> jax.Array:
    bh, s, hd = x.shape
    return x.reshape(b, h, s, hd).transpose(0, 2, 1, 3)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _check_shapes(q, block_q: int, block_k: int) -> None:
    s = q.shape[1]
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq len {s} not a multiple of blocks ({block_q}, {block_k})"
        )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_core(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int,
    block_k: int,
    interpret: Optional[bool],
    window: int,
) -> jax.Array:
    out, _lse = _flash_fwd_impl(
        q, k, v, block_q, block_k, interpret, window
    )
    return out


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    window: int = 0,
) -> jax.Array:
    """Causal flash attention, differentiable, all-pallas.

    [batch, seq, heads, head_dim] layout, same contract as
    ``causal_attention``; seq must be a multiple of both block sizes
    (pad upstream — static shapes keep the MXU tiling clean).

    ``window > 0`` = sliding-window attention: kv blocks entirely
    older than the window are skipped in all three kernels, so fwd
    AND bwd FLOPs are O(s*window). A plain wrapper so callers may use
    keywords; the custom_vjp core takes positions only.
    """
    return _flash_attention_core(
        q, k, v, block_q, block_k, interpret, window
    )


def _flash_fwd_impl(q, k, v, block_q, block_k, interpret, window=0):
    _check_shapes(q, block_q, block_k)
    if k.shape != q.shape or v.shape != q.shape:
        # the backward kernels index k/v by q-row; grouped (GQA) kv
        # would produce wrong-shaped, wrong-valued dk/dv here
        raise ValueError(
            f"flash_attention requires full-head k/v matching q "
            f"{q.shape}, got k {k.shape} — repeat GQA kv upstream, or "
            "use flash_attention_forward for GQA-native inference"
        )
    b, s, h, hd = q.shape
    interp = _resolve_interpret(interpret)
    out, lse = _fwd_rows(
        _to_rows(q), _to_rows(k), _to_rows(v), block_q, block_k, interp,
        window,
    )
    return _from_rows(out, b, h), lse


def _flash_fwd(q, k, v, block_q, block_k, interpret, window):
    out, lse = _flash_fwd_impl(
        q, k, v, block_q, block_k, interpret, window
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(block_q, block_k, interpret, window, residuals, d_out):
    q, k, v, out, lse = residuals
    b, s, h, hd = q.shape
    interp = _resolve_interpret(interpret)
    out_r = _to_rows(out)
    do_r = _to_rows(d_out)
    # D_i = rowsum(dO * O): tiny elementwise reduction, XLA fuses it.
    # keepdims matches lse's [rows, s, 1] kernel-friendly layout.
    d_rows = jnp.sum(
        do_r.astype(jnp.float32) * out_r.astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    dq, dk, dv = _bwd_rows(
        _to_rows(q), _to_rows(k), _to_rows(v), do_r, lse, d_rows,
        block_q, block_k, interp, window,
    )
    return (
        _from_rows(dq, b, h).astype(q.dtype),
        _from_rows(dk, b, h).astype(k.dtype),
        _from_rows(dv, b, h).astype(v.dtype),
    )


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret", "window")
)
def flash_attention_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    window: int = 0,
) -> jax.Array:
    """Forward-only entry point (inference/serving). Same kernel as the
    differentiable path, KV grid-streamed: VMEM use is O(block) per
    program regardless of sequence length.

    Grouped-query attention is native: k/v may carry fewer heads than
    q (n_heads % kv_heads == 0) and the kernel reads the shared K/V
    rows directly — no repeat_kv materialization."""
    _check_shapes(q, block_q, block_k)
    b, s, h, hd = q.shape
    if k.shape != v.shape:
        raise ValueError(f"k {k.shape} and v {v.shape} must agree")
    kb, ks, kvh, khd = k.shape
    if kb != b or ks != s or khd != hd or kvh < 1 or h % kvh:
        raise ValueError(
            f"kv shape {k.shape} incompatible with q {q.shape}: need "
            "(batch, seq, kv_heads, head_dim) with kv_heads >= 1 "
            "dividing the query heads"
        )
    out, _lse = _fwd_rows(
        _to_rows(q), _to_rows(k), _to_rows(v), block_q, block_k,
        _resolve_interpret(interpret), window,
    )
    return _from_rows(out, b, h)
