"""Weight-only int8 quantization with a fused dequant-matmul kernel.

Serving-oriented: weights stored int8 with per-output-channel float
scales (half the HBM footprint and half the weight-streaming traffic —
the bottleneck for small-batch decode). Activations stay bf16/f32.

Two implementations with identical numerics:

- ``int8_matmul`` (XLA): dequantize-and-multiply; XLA fuses the convert
  into the matmul operand read where it can.
- ``int8_matmul_pallas``: a pallas TPU kernel that tiles the GEMM,
  loads int8 weight blocks into VMEM, dequantizes there, and
  accumulates f32 over the K dimension — the int8→f32 upcast happens
  on-chip so HBM only ever sees int8 weights. Interpret mode covers it
  off-TPU (quantization pattern per the pallas guide; implemented
  fresh).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def quantize_int8_axes(
    w: jax.Array, axes: Tuple[int, ...]
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the given (input) axes; scales come back
    keepdims-shaped so dequant is a single broadcast multiply. The one
    quantization formula in the codebase — model-level quantization
    (models/quantized.py) calls this too."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
    scales = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(wf / scales), -127, 127).astype(jnp.int8)
    return w_q, scales


def quantize_int8(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-output-channel symmetric int8 quantization.

    w: [in_features, out_features] float -> (w_q int8 same shape,
    scales f32 [out_features]); w ≈ w_q * scales.
    """
    w_q, scales = quantize_int8_axes(w, (0,))
    return w_q, scales[0, :]


def int8_matmul(
    x: jax.Array, w_q: jax.Array, scales: jax.Array
) -> jax.Array:
    """XLA reference path: x [m, k] @ (w_q [k, n] * scales [n])."""
    wf = w_q.astype(jnp.float32) * scales[None, :]
    return jnp.einsum(
        "mk,kn->mn", x.astype(jnp.float32), wf,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def _int8_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    """One (m_tile, n_tile) program; iterate K blocks via the grid's
    innermost dimension, accumulating into a VMEM scratch."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x_blk = x_ref[:].astype(jnp.float32)          # [bm, bk]
    w_blk = w_ref[:].astype(jnp.float32)          # [bk, bn] (int8 -> f32)
    acc_ref[:] += jnp.dot(
        x_blk, w_blk, preferred_element_type=jnp.float32
    )

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] * s_ref[0, :].astype(jnp.float32)[None, :]).astype(
            o_ref.dtype
        )


def int8_matmul_padded(
    x: jax.Array,
    w_q: jax.Array,
    scales: jax.Array,
    block_m: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """``int8_matmul_pallas`` for arbitrary row counts: decode
    microbatches are far below the 128-row tile, so rows pad up to one
    tile and slice back — the padding rows are dead weight the MXU
    doesn't notice in the weight-streaming-bound regime this kernel
    serves."""
    m = x.shape[0]
    pad = (-m) % block_m
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = int8_matmul_pallas(
        x, w_q, scales, block_m=block_m, interpret=interpret
    )
    return out[:m] if pad else out


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret")
)
def int8_matmul_pallas(
    x: jax.Array,
    w_q: jax.Array,
    scales: jax.Array,
    block_m: int = 128,
    block_n: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused dequant GEMM: x [m, k] @ dequant(w_q [k, n]) -> [m, n].

    Dimensions must divide by their block sizes (pad upstream).
    """
    m, k = x.shape
    k2, n = w_q.shape
    if k != k2:
        raise ValueError(f"inner dims disagree: {k} vs {k2}")
    if m % block_m or n % block_n or k % block_k:
        raise ValueError(
            f"shape ({m},{k})x({k2},{n}) not divisible by blocks "
            f"({block_m},{block_k},{block_n})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_k = k // block_k
    kernel = functools.partial(_int8_matmul_kernel, n_k=n_k)
    return pl.pallas_call(
        kernel,
        grid=(m // block_m, n // block_n, n_k),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((block_k, block_n), lambda i, j, ki: (ki, j)),
            # scales ride as a [1, block_n] tile (TPU tiles are >= 2-D)
            pl.BlockSpec((1, block_n), lambda i, j, ki: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, ki: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_m, block_n), jnp.float32)
        ],
        interpret=interpret,
    )(x, w_q, scales[None, :])
