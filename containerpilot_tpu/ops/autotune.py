"""Flash-attention block autotuner: measure, choose, persist.

Produces the per-platform tuning table ops/tuning.py serves
(``ops/tuned/<platform>.json``). For each sequence length it times the
pallas kernels across candidate (block_q, block_k) pairs — 'train'
(one differentiable call: fwd+bwd through the custom_vjp) and 'fwd'
(inference/prefill) separately — against the XLA fused-attention
baseline, keeps the fastest blocks, and records the flash/XLA
crossover that ``TransformerConfig.flash_min_seq = AUTO`` resolves to.

    python -m containerpilot_tpu.ops.autotune \
        --seqs 1024,2048,4096,8192 --write

Timing mirrors bench.py's tunnel-aware methodology: n back-to-back
dispatches + one sync, the fixed tunnel roundtrip subtracted, min over
repetitions (ratios are what matter; the floor subtraction keeps
absolute numbers honest on tunneled devices).
"""
from __future__ import annotations

import argparse
import itertools
import json
import logging
import os
import time
from typing import Dict, Iterable, List, Tuple

log = logging.getLogger("containerpilot.autotune")

from .tuning import DEFAULT_BLOCK

CANDIDATE_BLOCKS = (128, 256, 512)
# the untuned baseline every accepted pair must measurably beat —
# derived, so the guard can't drift from pick_blocks' actual fallback
DEFAULT_PAIR = (DEFAULT_BLOCK, DEFAULT_BLOCK)


def _sync(x) -> None:
    """Force completion. Plain block_until_ready can return early
    through the axon device tunnel; a tiny host fetch cannot."""
    import jax.numpy as jnp
    import numpy as np

    while hasattr(x, "shape") and len(x.shape) > 3:
        x = x[0]
    np.asarray(jnp.ravel(x)[:1].astype(jnp.float32))


_FLOOR_MS = None


def _floor_ms() -> float:
    """The fixed dispatch+fetch roundtrip through the device tunnel
    (~tens of ms on axon), measured once with a trivial program. Real
    kernel timings subtract it so numbers reflect device time, not
    tunnel latency."""
    global _FLOOR_MS
    if _FLOOR_MS is None:
        import jax
        import jax.numpy as jnp

        trivial = jax.jit(lambda x: x + 1.0)
        x = jnp.zeros((8,), jnp.float32)
        _sync(trivial(x))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            _sync(trivial(x))
            best = min(best, (time.perf_counter() - t0) * 1e3)
        _FLOOR_MS = best
    return _FLOOR_MS


def _time_ms(fn, *args, n: int = 5, reps: int = 3) -> float:
    """Amortized timing: n back-to-back dispatches, one sync
    (in-order execution makes the final fetch wait for all), the
    tunnel's fixed roundtrip subtracted once; min over ``reps``
    repetitions discards tunnel latency spikes."""
    floor = _floor_ms()
    _sync(fn(*args))  # warm / compile

    def run(nn: int) -> float:
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            r = None
            for _ in range(nn):
                r = fn(*args)
            _sync(r)
            best = min(best, (time.perf_counter() - t0) * 1e3)
        return best

    best = run(n)
    # Tunnel noise guard: when the whole batch of dispatches finishes
    # indistinguishably from the bare roundtrip floor, the compute is
    # hidden under the roundtrip and `best - floor` is noise — a
    # noise-level "0.0002 ms" must never win block selection or ship
    # as a speedup_vs_default. Scale the dispatch count until the
    # signal clears the floor (each 4x amortizes the roundtrip 4x).
    while best < 2.0 * floor and n < 320:
        n = min(n * 4, 320)  # cap is the ceiling, not a pre-check
        best = run(n)
    return max(best - floor, 1e-3) / n


def _candidates(seq: int, blocks: Iterable[int]) -> List[Tuple[int, int]]:
    divs = [b for b in blocks if seq % b == 0]
    pairs = list(itertools.product(divs, divs))
    # build_table's honesty guard compares every pick against the
    # 128/128 baseline, so it must be measured even when --blocks
    # excludes 128 (any flash-eligible seq is a 128-multiple)
    if seq % DEFAULT_PAIR[0] == 0 and DEFAULT_PAIR not in pairs:
        pairs.insert(0, DEFAULT_PAIR)
    return pairs


def measure(
    seqs: Iterable[int],
    blocks: Iterable[int] = CANDIDATE_BLOCKS,
    batch: int = 2,
    heads: int = 8,
    head_dim: int = 128,
    n: int = 5,
    reps: int = 3,
) -> dict:
    """Raw measurements: per seq, XLA fwd/train baselines and every
    candidate block pair's flash fwd/train times (ms)."""
    import jax
    import jax.numpy as jnp

    from .attention import causal_attention
    from .flash import flash_attention

    results: dict = {}
    for seq in seqs:
        ks = jax.random.split(jax.random.PRNGKey(seq), 4)
        q, k, v = (
            jax.random.normal(kk, (batch, seq, heads, head_dim),
                              jnp.bfloat16)
            for kk in ks[:3]
        )
        cot = jax.random.normal(
            ks[3], (batch, seq, heads, head_dim), jnp.bfloat16
        )

        def train_of(attn):
            # jit created ONCE per attention variant and reused for
            # every timed dispatch — rebuilding it inside the timed
            # callable would miss jax's jit cache and time retraces
            return jax.jit(
                jax.grad(
                    lambda q, k, v: jnp.sum(
                        (attn(q, k, v) * cot).astype(jnp.float32)
                    ),
                    argnums=(0, 1, 2),
                )
            )

        xla_train = train_of(causal_attention)
        entry = {
            "xla_fwd_ms": _time_ms(
                jax.jit(causal_attention), q, k, v, n=n, reps=reps
            ),
            "xla_train_ms": _time_ms(
                lambda *a: xla_train(*a)[0], q, k, v, n=n, reps=reps,
            ),
            "flash": {},
        }
        for bq, bk in _candidates(seq, blocks):
            fa = lambda q, k, v, _bq=bq, _bk=bk: flash_attention(  # noqa: E731
                q, k, v, block_q=_bq, block_k=_bk
            )
            flash_train = train_of(fa)
            entry["flash"][f"{bq}x{bk}"] = {
                "fwd_ms": _time_ms(jax.jit(fa), q, k, v, n=n, reps=reps),
                "train_ms": _time_ms(
                    lambda *a: flash_train(*a)[0], q, k, v, n=n,
                    reps=reps,
                ),
            }
        results[str(seq)] = entry
        log.info("autotune seq %d: %s", seq, json.dumps(entry))
    return results


def build_table(results: dict, platform: str) -> dict:
    """Choose per-seq best blocks and the flash/XLA crossover per kind.

    The crossover is the smallest measured seq from which flash (at
    its best blocks) beats XLA at EVERY measured seq onward — a seq
    where XLA still wins keeps routing below-it traffic to XLA.

    Honesty guard: a non-default block pair only enters the table if
    its measured time actually beats the 128/128 default at that seq —
    a noise-level "win" must not ship as tuning. Every entry carries
    its measured ``speedup_vs_default`` (default_ms / chosen_ms, 1.0
    when the default itself is chosen) so the table is
    self-evidencing."""
    default_key = f"{DEFAULT_PAIR[0]}x{DEFAULT_PAIR[1]}"
    blocks: Dict[str, Dict[str, list]] = {"train": {}, "fwd": {}}
    speedup: Dict[str, Dict[str, float]] = {"train": {}, "fwd": {}}
    wins: Dict[str, Dict[int, bool]] = {"train": {}, "fwd": {}}
    for seq_s, entry in results.items():
        seq = int(seq_s)
        for kind, flash_key, xla_key in (
            ("train", "train_ms", "xla_train_ms"),
            ("fwd", "fwd_ms", "xla_fwd_ms"),
        ):
            best_pair, best_ms = None, float("inf")
            for pair, times in entry["flash"].items():
                if times[flash_key] < best_ms:
                    best_ms = times[flash_key]
                    best_pair = [int(x) for x in pair.split("x")]
            if best_pair is None:
                continue
            default_times = entry["flash"].get(default_key)
            if default_times is not None:
                default_ms = default_times[flash_key]
                if best_pair != list(DEFAULT_PAIR) and best_ms >= default_ms:
                    best_pair, best_ms = list(DEFAULT_PAIR), default_ms
                speedup[kind][seq_s] = round(default_ms / best_ms, 4)
            else:
                # shouldn't happen via measure() (which always includes
                # the default pair); a hand-built results dict without
                # it ships unguarded — say so rather than imply tuning
                log.warning(
                    "autotune seq %s %s: %s baseline unmeasured; "
                    "honesty guard skipped", seq_s, kind, default_key,
                )
            blocks[kind][seq_s] = best_pair
            wins[kind][seq] = best_ms <= entry[xla_key]

    min_seq: Dict[str, int] = {}
    for kind, seq_wins in wins.items():
        measured = sorted(seq_wins)
        crossover = 0
        for seq in reversed(measured):
            if seq_wins[seq]:
                crossover = seq
            else:
                break
        # 0 would mean "flash always wins, even unmeasured tiny seqs";
        # never extrapolate below the smallest measured win
        min_seq[kind] = crossover if crossover else (
            (measured[-1] + 1) if measured else 0
        )
    return {
        "platform": platform,
        "flash_min_seq": min_seq,
        "blocks": blocks,
        "speedup_vs_default": speedup,
        "measurements": results,
    }


def main(argv=None) -> int:
    from . import tuning

    parser = argparse.ArgumentParser(description="flash block autotuner")
    parser.add_argument("--seqs", default="1024,2048,4096,8192")
    parser.add_argument(
        "--blocks", default=",".join(map(str, CANDIDATE_BLOCKS))
    )
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--head-dim", type=int, default=128)
    parser.add_argument("--n", type=int, default=5)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument(
        "--write", action="store_true",
        help="persist to ops/tuned/<platform>.json (the auto-discovery "
        "path); otherwise print the table to stdout only",
    )
    parser.add_argument("--out", default="", help="explicit output path")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    seqs = [int(s) for s in args.seqs.split(",") if s]
    blocks = [int(b) for b in args.blocks.split(",") if b]

    platform = tuning.platform_slug()
    results = measure(
        seqs, blocks, batch=args.batch, heads=args.heads,
        head_dim=args.head_dim, n=args.n, reps=args.reps,
    )
    table = build_table(results, platform)
    print(json.dumps(table, indent=1))
    if args.write or args.out:
        path = args.out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tuned",
            f"{platform}.json",
        )
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(table, fh, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
