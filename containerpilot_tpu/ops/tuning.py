"""Flash-attention block tuning: measured, per-platform, persistent.

The pallas kernels' performance hinges on (block_q, block_k) — the
right choice varies with sequence length and mode (a training step
runs fwd+bwd through one custom_vjp call, so blocks are chosen per
call, not per direction). Hardcoded 128/128 left the 2k-4k training
range losing to plain XLA attention. This module holds a small tuned
table, produced by ``python -m containerpilot_tpu.ops.autotune`` on
the actual device (ops/autotune.py) and shipped per platform under
``ops/tuned/<platform>.json``:

    {"platform": "tpu-v5-lite",
     "flash_min_seq": {"train": 2048, "fwd": 1024},
     "blocks": {"train": {"2048": [256, 128], ...},
                "fwd":   {"8192": [256, 256], ...}}}

Consumers:
- ``pick_blocks(kind, seq)`` -> (block_q, block_k) for the flash call
  (exact seq entry, else the nearest tuned seq at/below, else the
  128/128 default), clamped to divisors of seq so the kernels' static
  grids stay exact.
- ``auto_min_seq(kind)`` -> the measured flash/XLA crossover:
  sequences shorter than this run faster through XLA's fused
  attention than through the pallas kernels, so the model's
  ``flash_min_seq: AUTO`` resolves here (models/transformer.py
  flash_eligible).

No table (fresh checkout, unknown platform) degrades to the previous
behavior exactly: 128/128 blocks, crossover 1024. Override the table
path with CONTAINERPILOT_FLASH_TABLE; ``set_table(None)`` reverts to
auto-discovery.
"""
from __future__ import annotations

import json
import logging
import os
import re
from typing import Dict, Optional, Tuple

log = logging.getLogger("containerpilot.tuning")

DEFAULT_BLOCK = 128
DEFAULT_MIN_SEQ = 1024  # pre-tuning crossover default
AUTO = -1               # TransformerConfig.flash_min_seq sentinel

_TUNED_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tuned")

# module state: the active table, and whether discovery already ran
_table: Optional[dict] = None
_loaded = False


def platform_slug() -> str:
    """Normalized device kind of the default backend, e.g.
    'tpu-v5-lite'; 'cpu' on the test mesh."""
    import jax

    kind = jax.devices()[0].device_kind
    return re.sub(r"[^a-z0-9]+", "-", kind.lower()).strip("-")


def _table_path() -> Optional[str]:
    override = os.environ.get("CONTAINERPILOT_FLASH_TABLE")
    if override:
        return override
    try:
        path = os.path.join(_TUNED_DIR, f"{platform_slug()}.json")
    except Exception:  # no backend at all
        return None
    return path if os.path.exists(path) else None


def set_table(table: Optional[dict]) -> None:
    """Install a table dict directly (tests, autotune); None reverts
    to on-disk auto-discovery at the next lookup."""
    global _table, _loaded
    _table = table
    _loaded = table is not None


def _get_table() -> Optional[dict]:
    global _table, _loaded
    if not _loaded:
        _loaded = True
        path = _table_path()
        if path:
            try:
                with open(path) as fh:
                    _table = json.load(fh)
                log.info("flash tuning table: %s", path)
            except (OSError, ValueError) as exc:
                log.warning("flash tuning table unreadable (%s): %s",
                            path, exc)
                _table = None
    return _table


def _largest_divisor_block(seq: int, block: int) -> int:
    """The largest block <= ``block`` dividing seq (halving from
    ``block``, floored at DEFAULT_BLOCK — the kernels require exact
    grids). Fails loudly on seq not a multiple
    of DEFAULT_BLOCK: pick_blocks is a public helper (bench/autotune
    call it), and silently clamping to a non-tile block (e.g. 100, or
    a degenerate 2) would hand pallas a grid Mosaic rejects — every
    flash call site gates on seq % 128 == 0 (flash_eligible), so such
    a seq here is a caller bug, not a tuning decision."""
    if seq % DEFAULT_BLOCK != 0:
        raise ValueError(
            f"flash blocks require seq % {DEFAULT_BLOCK} == 0; got "
            f"seq={seq} (gate the call on flash_eligible)"
        )
    b = block
    while b > DEFAULT_BLOCK and seq % b != 0:
        b //= 2
    # halving an odd-multiple block can undershoot DEFAULT_BLOCK with
    # a non-divisor; the floor is always a divisor thanks to the gate
    return max(b, DEFAULT_BLOCK)


def pick_blocks(kind: str, seq: int) -> Tuple[int, int]:
    """(block_q, block_k) for a flash call of ``kind`` ('train' = the
    differentiable fwd+bwd path, 'fwd' = inference/prefill) at ``seq``."""
    bq, bk = DEFAULT_BLOCK, DEFAULT_BLOCK
    table = _get_table()
    if table is not None:
        entries: Dict[str, list] = table.get("blocks", {}).get(kind, {})
        tuned_seqs = sorted(int(s) for s in entries)
        at_or_below = [s for s in tuned_seqs if s <= seq]
        if at_or_below:
            bq, bk = entries[str(at_or_below[-1])]
    return _largest_divisor_block(seq, bq), _largest_divisor_block(seq, bk)


def auto_min_seq(kind: str = "train") -> int:
    """The measured crossover below which XLA attention wins; the
    pre-tuning default when no table is shipped for this platform."""
    table = _get_table()
    if table is not None:
        value = table.get("flash_min_seq", {}).get(kind)
        if isinstance(value, int) and value >= 0:
            return value
    return DEFAULT_MIN_SEQ


def resolve_min_seq(configured: int, kind: str = "train") -> int:
    """Map a TransformerConfig.flash_min_seq to an effective threshold:
    AUTO (-1) asks the tuned table; explicit values win unchanged
    (0 keeps meaning 'never use flash')."""
    return auto_min_seq(kind) if configured == AUTO else configured
