"""Memory-efficient causal attention for training: the flash-attention
algorithm with a custom VJP, O(seq) activation memory both ways.

The einsum path (attention.py) materializes the [seq, seq] score matrix
in both passes; this op streams KV blocks with online softmax in the
forward (saving only out + per-row logsumexp) and replays blocks in the
backward using the standard flash gradients:

    D_i   = rowsum(dO_i * O_i)
    dP_ij = dO_i @ V_j^T
    dS_ij = P_ij * (dP_ij - D_i)
    dQ_i += dS_ij @ K_j ;  dK_j += dS_ij^T @ Q_i ;  dV_j += P_ij^T @ dO_i

Everything is lax.scan over blocks: the backward carries the full dQ
accumulator (one [b,s,h,hd] buffer) and emits per-block dK/dV, so peak
activation memory is O(seq), never O(seq^2). Fully-future (qi < kj)
block pairs are skipped with lax.cond — causal attention does ~half
the block-pair work. The public flash technique (see PAPERS.md),
implemented fresh on jax.

Composable: per-device memory-bounded attention here, cross-device
sequence sharding via ops/ring_attention.py.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .attention import NEG_INF


def _blocks(x: jax.Array, block: int) -> jax.Array:
    """[b, s, h, hd] -> [n_blocks, b, block, h, hd]."""
    b, s, h, hd = x.shape
    return x.reshape(b, s // block, block, h, hd).transpose(1, 0, 2, 3, 4)


def _unblocks(x: jax.Array) -> jax.Array:
    """[n_blocks, b, block, h, hd] -> [b, s, h, hd]."""
    n, b, blk, h, hd = x.shape
    return x.transpose(1, 0, 2, 3, 4).reshape(b, n * blk, h, hd)


def _block_causal_mask(qi: jax.Array, kj: jax.Array, block: int) -> jax.Array:
    """[block, block] bool: global causal mask for block pair (qi, kj).
    Shared by forward and backward so the passes can never disagree."""
    q_pos = qi * block + lax.broadcasted_iota(jnp.int32, (block, block), 0)
    k_pos = kj * block + lax.broadcasted_iota(jnp.int32, (block, block), 1)
    return q_pos >= k_pos


def _fwd_pass(
    q: jax.Array, k: jax.Array, v: jax.Array, block: int
) -> Tuple[jax.Array, jax.Array]:
    """Returns (out [b,s,h,hd], lse [b,h,s])."""
    b, s, h, hd = q.shape
    if s % block:
        # validated here (not only in the public wrapper) so the
        # custom_vjp fwd rule under jax.grad errors just as cleanly
        raise ValueError(f"seq len {s} not a multiple of block {block}")
    scale = hd ** -0.5
    qb = _blocks(q, block)  # [nq, b, blk, h, hd]
    kb = _blocks(k, block)
    vb = _blocks(v, block)
    n_blocks = s // block

    def per_q_block(qi, q_blk):
        qf = q_blk.astype(jnp.float32) * scale

        def inner(carry, inputs):
            kj, k_blk, v_blk = inputs

            def compute(carry):
                m, l, acc = carry
                scores = jnp.einsum(
                    "bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                mask = _block_causal_mask(qi, kj, block)
                scores = jnp.where(mask[None, None], scores, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
                m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
                p = jnp.exp(scores - m_safe[..., None])
                p = jnp.where(mask[None, None], p, 0.0)
                corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe))
                l_new = l * corr + jnp.sum(p, axis=-1)
                acc_new = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
                    "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                return m_new, l_new, acc_new

            # fully-future blocks are skipped, not computed-and-discarded
            carry = lax.cond(kj <= qi, compute, lambda c: c, carry)
            return carry, None

        m0 = jnp.full((b, h, block), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, block), jnp.float32)
        acc0 = jnp.zeros((b, block, h, hd), jnp.float32)
        (m, l, acc), _ = lax.scan(
            inner, (m0, l0, acc0), (jnp.arange(n_blocks), kb, vb)
        )
        l_safe = jnp.maximum(l, 1e-30)
        out = acc / l_safe.transpose(0, 2, 1)[..., None]
        lse = m + jnp.log(l_safe)  # [b, h, block]
        return out.astype(q.dtype), lse

    outs, lses = lax.map(
        lambda args: per_q_block(*args), (jnp.arange(n_blocks), qb)
    )
    out = _unblocks(outs)
    # lses: [nq, b, h, block] -> [b, h, s]
    lse = lses.transpose(1, 2, 0, 3).reshape(b, h, s)
    return out, lse


def _bwd_pass(q, k, v, out, lse, d_out, block: int):
    b, s, h, hd = q.shape
    scale = hd ** -0.5
    qb, kb, vb = _blocks(q, block), _blocks(k, block), _blocks(v, block)
    ob, dob = _blocks(out, block), _blocks(d_out, block)
    n_blocks = s // block
    lse_b = lse.reshape(b, h, n_blocks, block).transpose(2, 0, 1, 3)
    # D_i = rowsum(dO * O)  [nq, b, h, block]
    d_rows = jnp.einsum(
        "nbqhd,nbqhd->nbhq", dob.astype(jnp.float32), ob.astype(jnp.float32)
    )

    def per_kv_block(dq_total, inputs):
        kj, k_blk, v_blk = inputs
        kf = k_blk.astype(jnp.float32)
        vf = v_blk.astype(jnp.float32)

        def inner(carry, inputs2):
            qi, q_blk, do_blk, lse_blk, d_blk = inputs2

            def compute(carry):
                dk, dv = carry
                qf = q_blk.astype(jnp.float32) * scale
                scores = jnp.einsum(
                    "bqhd,bkhd->bhqk", qf, kf,
                    preferred_element_type=jnp.float32,
                )
                mask = _block_causal_mask(qi, kj, block)
                p = jnp.exp(scores - lse_blk[..., None])
                p = jnp.where(mask[None, None], p, 0.0)
                dof = do_blk.astype(jnp.float32)
                dp = jnp.einsum(
                    "bqhd,bkhd->bhqk", dof, vf,
                    preferred_element_type=jnp.float32,
                )
                ds = p * (dp - d_blk[..., None])
                dk_new = dk + jnp.einsum(
                    "bhqk,bqhd->bkhd", ds, qf,
                    preferred_element_type=jnp.float32,
                )
                dv_new = dv + jnp.einsum(
                    "bhqk,bqhd->bkhd", p, dof,
                    preferred_element_type=jnp.float32,
                )
                dq_part = jnp.einsum(
                    "bhqk,bkhd->bqhd", ds, kf,
                    preferred_element_type=jnp.float32,
                )
                return (dk_new, dv_new), dq_part

            def skip(carry):
                return carry, jnp.zeros((b, block, h, hd), jnp.float32)

            # only past-or-diagonal block pairs contribute
            carry, dq_part = lax.cond(qi >= kj, compute, skip, carry)
            return carry, dq_part

        dk0 = jnp.zeros((b, block, h, hd), jnp.float32)
        dv0 = jnp.zeros((b, block, h, hd), jnp.float32)
        (dk, dv), dq_parts = lax.scan(
            inner, (dk0, dv0),
            (jnp.arange(n_blocks), qb, dob, lse_b, d_rows),
        )
        # fold this kv block's dQ contribution into the single running
        # accumulator — O(seq) carry, no [nk, nq, ...] stacking
        dq_total = dq_total + _unblocks(dq_parts)
        return dq_total, (dk, dv)

    dq0 = jnp.zeros((b, s, h, hd), jnp.float32)
    dq, (dks, dvs) = lax.scan(
        per_kv_block, dq0, (jnp.arange(n_blocks), kb, vb)
    )
    dq = dq * scale
    dk = _unblocks(dks)
    dv = _unblocks(dvs)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def memory_efficient_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, block: int = 256
) -> jax.Array:
    """Causal attention with O(seq) activation memory in both passes.

    Same [batch, seq, heads, head_dim] contract as causal_attention;
    seq must be a multiple of ``block`` (pad upstream for ragged
    lengths).
    """
    out, _lse = _fwd_pass(q, k, v, block)
    return out


def _mea_fwd(q, k, v, block):
    out, lse = _fwd_pass(q, k, v, block)
    return out, (q, k, v, out, lse)


def _mea_bwd(block, residuals, d_out):
    q, k, v, out, lse = residuals
    return _bwd_pass(q, k, v, out, lse, d_out, block)


memory_efficient_attention.defvjp(_mea_fwd, _mea_bwd)
