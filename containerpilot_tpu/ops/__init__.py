"""Op library for the TPU workload: attention four ways — XLA einsum,
pallas flash (fwd+bwd, differentiable), memory-efficient XLA training
fallback (custom VJP), and ring/context-parallel."""
from .attention import causal_attention
from .flash import flash_attention, flash_attention_forward
from .flash_training import memory_efficient_attention
from .quant import (
    int8_matmul,
    int8_matmul_padded,
    int8_matmul_pallas,
    quantize_int8,
)
from .ring_attention import ring_attention

__all__ = [
    "causal_attention",
    "flash_attention",
    "flash_attention_forward",
    "memory_efficient_attention",
    "ring_attention",
    "quantize_int8",
    "int8_matmul",
    "int8_matmul_pallas",
    "int8_matmul_padded",
]
