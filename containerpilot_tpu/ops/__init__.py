"""Op library for the TPU workload: attention (XLA + pallas flash)."""
from .attention import causal_attention, flash_attention_forward

__all__ = ["causal_attention", "flash_attention_forward"]
