"""Op library for the TPU workload: attention (XLA + pallas flash +
ring/context-parallel)."""
from .attention import causal_attention, flash_attention_forward
from .ring_attention import ring_attention

__all__ = ["causal_attention", "flash_attention_forward", "ring_attention"]
