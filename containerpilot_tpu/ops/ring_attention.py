"""Ring attention: causal attention with the sequence sharded over a
mesh axis.

Long-context sequence/context parallelism, TPU-native: each device
holds a contiguous sequence shard of Q, K, V. K/V blocks rotate around
the ring via ``lax.ppermute`` (neighbor exchange rides ICI) while every
device accumulates its queries' attention with blockwise online softmax
— O(local_seq) memory per device, full-sequence numerics identical to
single-device causal attention.

Step s gives device i the K/V block that originated on device
``(i - s) mod P``; global positions make the causal mask exact across
shards. Step 0 is the device's own (diagonal) block, so every query row
is live from the first step and the running max is never -inf when it
matters.

The public technique (blockwise ring attention; see PAPERS.md) is
implemented fresh against jax shard_map/ppermute.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .attention import NEG_INF

import inspect

try:  # stable API from jax 0.6+; experimental path for older
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax versions; resolve once
_SHARD_MAP_PARAMS = inspect.signature(_shard_map).parameters
_CHECK_KWARG = (
    "check_vma" if "check_vma" in _SHARD_MAP_PARAMS else "check_rep"
)
del inspect


_HAS_AXIS_NAMES = "axis_names" in _SHARD_MAP_PARAMS


def shard_map(f, *, mesh, in_specs, out_specs, auto=None):
    """Version-compat shard_map. ``auto`` names mesh axes left to the
    automatic partitioner inside the manual region (pp×tp composition:
    pipe is manual, model stays auto so XLA inserts the tensor-parallel
    collectives inside each stage). Newer jax expresses this as
    ``axis_names`` = the manual complement; older jax as ``auto``."""
    kwargs = {_CHECK_KWARG: False}
    if auto:
        if _HAS_AXIS_NAMES:
            kwargs["axis_names"] = frozenset(mesh.axis_names) - frozenset(
                auto
            )
        else:  # pragma: no cover - older jax
            kwargs["auto"] = frozenset(auto)
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **kwargs,
    )

def _ring_shard_fn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Per-device body; runs under shard_map. Shapes are the local
    shards: [batch, local_seq, heads, head_dim].

    Grouped-query attention is native: k/v may carry fewer heads than
    q. The ring rotates the SMALL grouped K/V over ICI — the whole
    point of GQA — and the einsums keep K/V at kv-head width by
    carrying the query heads as a [kv_heads, group] pair of axes, so
    no repeated copy is ever materialized."""
    idx = lax.axis_index(axis_name)
    b, lq, h, hd = q.shape
    kvh = k.shape[2]
    group = h // kvh
    scale = hd ** -0.5
    # queries grouped by the kv head they attend with: [b,lq,kvh,g,hd]
    qf = q.astype(jnp.float32).reshape(b, lq, kvh, group, hd) * scale

    q_pos = idx * lq + jnp.arange(lq, dtype=jnp.int32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(s, carry):
        k_blk, v_blk, m, l, acc = carry  # m/l: [b,kvh,g,lq]
        src = (idx - s) % axis_size
        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs",
            qf,
            k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # [b, kvh, g, lq, lk]
        k_pos = src * lq + jnp.arange(lq, dtype=jnp.int32)
        mask = q_pos[:, None] >= k_pos[None, :]  # [lq, lk] global causal
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))  # [b,kvh,g,lq]
        # fully-masked-so-far rows keep m at NEG_INF; guard the exps
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        correction = jnp.where(
            m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe)
        )
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(mask[None, None, None], p, 0.0)
        l_new = l * correction + jnp.sum(p, axis=-1)
        # correction: [b,kvh,g,lq] -> [b,lq,kvh,g,1] to scale acc
        corr_acc = correction.transpose(0, 3, 1, 2)[..., None]
        acc_new = acc * corr_acc + jnp.einsum(
            "bkgqs,bskd->bqkgd",
            p,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # rotate K/V to the next device in the ring; the final
        # iteration's rotation would be discarded, so skip it
        k_blk, v_blk = lax.cond(
            s < axis_size - 1,
            lambda kv: (
                lax.ppermute(kv[0], axis_name, perm),
                lax.ppermute(kv[1], axis_name, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_blk, v_blk, m_new, l_new, acc_new

    m0 = jnp.full((b, kvh, group, lq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, group, lq), jnp.float32)
    acc0 = jnp.zeros((b, lq, kvh, group, hd), jnp.float32)
    _k, _v, _m, l, acc = lax.fori_loop(
        0, axis_size, step, (k, v, m0, l0, acc0)
    )
    # l: [b,kvh,g,lq] -> [b,lq,kvh,g,1]
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return (acc / denom).reshape(b, lq, h, hd).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "seq",
) -> jax.Array:
    """Causal attention with [batch, seq, heads, head_dim] inputs whose
    sequence dimension is sharded over ``axis_name`` of ``mesh``.

    The global sequence length must divide evenly by the axis size.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by {axis_name}={axis_size}"
        )
    kvh = k.shape[2]
    if k.shape != v.shape or kvh < 1 or q.shape[2] % kvh:
        raise ValueError(
            f"kv shape {k.shape} incompatible with q {q.shape}: kv heads "
            "must divide the query heads and k/v must agree"
        )
    # keep batch/head sharding on their own axes inside the shard_map so
    # entering it doesn't all-gather what dp/tp already sharded
    batch_axis = "data" if "data" in mesh.axis_names else None
    head_axis = "model" if "model" in mesh.axis_names else None
    if (
        head_axis is not None
        and kvh != q.shape[2]
        and kvh % mesh.shape[head_axis]
    ):
        # grouped kv heads don't divide the tp axis: the per-device
        # group factor would be wrong, so give up the GQA ICI saving
        # and rotate full heads (correctness first)
        k = jnp.repeat(k, q.shape[2] // kvh, axis=2)
        v = jnp.repeat(v, q.shape[2] // kvh, axis=2)
    spec = P(batch_axis, axis_name, head_axis, None)
    fn = shard_map(
        functools.partial(
            _ring_shard_fn, axis_name=axis_name, axis_size=axis_size
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
