"""Device-mesh construction.

The workload's scaling axes:

- ``data``  — data parallelism (batch sharding; gradient psum rides ICI)
- ``model`` — tensor parallelism (attention heads + MLP hidden sharding)

The factorization favors keeping ``model`` small (tensor parallelism is
ICI-bandwidth hungry) and pushing the rest onto ``data``; multi-host
deployments put ``data`` on the outer (DCN-crossing) axis, which is the
standard TPU recipe (scaling-book: pick mesh, annotate, let XLA insert
collectives).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshPlan:
    """A named factorization of the device count.

    ``seq`` > 1 adds a context-parallel axis for ring attention over
    long sequences (ops/ring_attention.py); ``pipe`` > 1 adds a
    pipeline-stage axis for GPipe microbatching (parallel/pipeline.py).
    """

    data: int
    model: int
    seq: int = 1
    pipe: int = 1

    @property
    def n_devices(self) -> int:
        return self.data * self.model * self.seq * self.pipe


def _factor(n: int, max_model: int) -> MeshPlan:
    """Largest power-of-two model axis up to max_model that divides n."""
    model = 1
    m = 2
    while m <= max_model and n % m == 0:
        model = m
        m *= 2
    return MeshPlan(data=n // model, model=model)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    plan: Optional[MeshPlan] = None,
    max_model: int = 4,
) -> Mesh:
    """Build a mesh over the given (or all) devices.

    Axis names are ("data", "model") for 2D plans,
    ("data", "seq", "model") when the plan's ``seq`` > 1 (context
    parallelism — see ops/ring_attention.py), or
    ("data", "pipe", "model") when ``pipe`` > 1 (pipeline stages —
    see parallel/pipeline.py). pipe is placed outside model so the
    per-tick activation ppermute crosses the slower links once while
    the chatty tensor-parallel collectives stay on the innermost axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if plan is None:
        plan = _factor(n, max_model)
    if plan.n_devices != n:
        raise ValueError(
            f"mesh plan {plan} does not cover {n} devices"
        )
    if plan.seq > 1 and plan.pipe > 1:
        raise ValueError("seq and pipe axes cannot be combined (yet)")
    if plan.seq > 1:
        grid = np.asarray(devices).reshape(plan.data, plan.seq, plan.model)
        return Mesh(grid, axis_names=("data", "seq", "model"))
    if plan.pipe > 1:
        grid = np.asarray(devices).reshape(plan.data, plan.pipe, plan.model)
        return Mesh(grid, axis_names=("data", "pipe", "model"))
    grid = np.asarray(devices).reshape(plan.data, plan.model)
    return Mesh(grid, axis_names=("data", "model"))
