"""Pipeline parallelism: GPipe-style microbatching over a ``pipe`` mesh
axis.

The scan-over-stacked-layers model design makes stage partitioning
natural: the layer-stacked parameter arrays ``[L, ...]`` shard their
leading axis over ``pipe`` (each device holds L/S contiguous layers),
and microbatches stream through the stages with ``lax.ppermute``
activation handoffs — the classic SPMD collective-permute pipeline
(public recipe; see the scaling-book pattern, implemented fresh here).

Schedule: S stages, M microbatches, M + S - 1 ticks. At tick t stage 0
ingests microbatch ``min(t, M-1)`` (masked once t >= M), every stage
applies its local layers, the result permutes to the next stage, and
the last stage banks its output for microbatch ``t - S + 1``. The
pipeline bubble is the standard (S-1)/(M+S-1); raise ``n_microbatches``
to amortize it.

Embedding/unembedding run replicated outside the pipelined stack, and
the final activations are broadcast off the last stage with a masked
psum, so the loss (and grads — ppermute is differentiable) compose with
data parallelism on an outer ``data`` axis.

Logits are numerically equivalent to the unpipelined forward — same
math, tolerance-level float differences from microbatched reduction
tiling. For MoE models the aux load-balance loss is the mean of
per-*microbatch* statistics rather than the full-batch statistic (the
loss is nonlinear in batch partitioning) — the standard behavior of
microbatched MoE training.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import (
    Params,
    TransformerConfig,
    _layer,
    _rms_norm,
    next_token_loss,
)
from ..ops.ring_attention import shard_map  # version-compat wrapper


def _stage_fn(
    x: jax.Array, local_layers: Any, cfg: TransformerConfig
) -> Tuple[jax.Array, jax.Array]:
    """Apply this stage's layer slice: scan over local layers."""

    def body(carry, layer_params):
        x, aux = carry
        x, layer_aux = _layer(x, layer_params, cfg)
        return (x, aux + layer_aux), None

    (x, aux), _ = lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), local_layers
    )
    return x, aux


def _pipeline_body(
    layers: Any,
    x_mb: jax.Array,  # [M, mb, s, d] microbatched embeddings (replicated)
    *,
    cfg: TransformerConfig,
    axis_name: str,
    n_stages: int,
    n_microbatches: int,
    data_axis: str = None,
):
    """Per-device body under shard_map; ``layers`` leaves are the local
    [L/S, ...] slices."""
    stage = lax.axis_index(axis_name)
    _, mb, s, d = x_mb.shape
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ticks = n_microbatches + n_stages - 1

    def tick(t, carry):
        acts, outputs, aux = carry
        # stage 0 ingests microbatch t (clamped; masked when t >= M)
        feed_idx = jnp.clip(t, 0, n_microbatches - 1)
        fresh = lax.dynamic_index_in_dim(x_mb, feed_idx, 0, keepdims=False)
        my_in = jnp.where(stage == 0, fresh, acts)
        y, stage_aux = _stage_fn(my_in, layers, cfg)
        # the last stage banks microbatch t-S+1's result once it's real
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
        is_valid = (t >= n_stages - 1) & (stage == n_stages - 1)
        banked = lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), out_idx, 0
        )
        outputs = jnp.where(is_valid, banked, outputs)
        # every stage contributes aux for the ticks where it held a
        # real microbatch (stage s is busy during ticks s..s+M-1)
        busy = (t >= stage) & (t < stage + n_microbatches)
        aux = aux + jnp.where(busy, stage_aux, 0.0)
        acts = lax.ppermute(y, axis_name, perm)
        return acts, outputs, aux

    acts0 = jnp.zeros((mb, s, d), cfg.dtype)
    outputs0 = jnp.zeros((n_microbatches, mb, s, d), cfg.dtype)
    aux0 = jnp.zeros((), jnp.float32)
    _acts, outputs, aux = lax.fori_loop(
        0, ticks, tick, (acts0, outputs0, aux0)
    )
    # broadcast the last stage's results to every device
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs, 0.0).astype(jnp.float32),
        axis_name,
    ).astype(cfg.dtype)
    aux = lax.psum(aux, axis_name)
    if data_axis is not None:
        # the aux out_spec is replicated, so it must agree across the
        # data axis: average the per-shard statistics
        aux = lax.pmean(aux, data_axis)
    return outputs, aux


def pipeline_forward_with_aux(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    n_microbatches: int = 4,
    axis_name: str = "pipe",
):
    """Forward through pipeline-sharded layers.

    tokens: [batch, seq]; batch must divide by n_microbatches; n_layers
    by the pipe axis size. Returns (logits, aux) like forward_with_aux.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )
    b, s = tokens.shape
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {n_microbatches} microbatches"
        )
    mb = b // n_microbatches
    data_size = mesh.shape.get("data", 1)
    if mb % data_size:
        raise ValueError(
            f"microbatch size {mb} not divisible by data axis {data_size}"
        )
    layer_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), params["layers"]
    )
    # compose with data parallelism: microbatch contents shard over an
    # outer "data" axis (everything in the body is per-sample)
    data_axis = "data" if "data" in mesh.axis_names else None
    # compose with tensor parallelism: any remaining mesh axes (e.g.
    # "model") stay AUTO inside the manual region, so XLA partitions
    # each stage's layer math over them and inserts the tp collectives
    # — pp x tp without hand-writing the tp collectives here. Size-1
    # axes need no partitioning at all and are kept manual(-and-
    # unused), so a trivial model axis doesn't force the auto-region
    # restrictions (no pallas flash, f32-on-CPU) onto plain dp x pp.
    auto = {
        a
        for a in mesh.axis_names
        if a != axis_name and a != data_axis and mesh.shape[a] > 1
    }
    if auto:
        import dataclasses

        if cfg.attention_fn is None and cfg.flash_min_seq:
            # pallas calls can't be partitioned by the AUTO axes inside
            # this manual region, so the auto-selected flash path must
            # stay off here: the einsum attention partitions fine over
            # the auto model axis. (pp x tp flash needs manual-tp
            # kernels — future work.)
            cfg = dataclasses.replace(cfg, flash_min_seq=0)
        if jax.default_backend() == "cpu" and cfg.dtype == jnp.bfloat16:
            # XLA CPU's AllReducePromotion pass CHECK-crashes cloning
            # the bf16 all-reduces that auto partitioning inserts
            # around this manual region; run the whole pipelined
            # forward in f32 on the CPU test/dryrun backend (TPU is
            # unaffected)
            cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    x = params["embed"].astype(cfg.dtype)[tokens]
    x_mb = x.reshape(n_microbatches, mb, s, -1)
    x_spec = P(None, data_axis, None, None)
    fn = shard_map(
        functools.partial(
            _pipeline_body,
            cfg=cfg,
            axis_name=axis_name,
            n_stages=n_stages,
            n_microbatches=n_microbatches,
            data_axis=data_axis,
        ),
        mesh=mesh,
        in_specs=(layer_specs, x_spec),
        out_specs=(x_spec, P()),
        auto=auto or None,
    )
    outputs, aux = fn(params["layers"], x_mb)
    x = outputs.reshape(b, s, -1)
    x = _rms_norm(x, params["norm_out"])
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["unembed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, aux / n_microbatches


def pipeline_loss_fn(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    n_microbatches: int = 4,
) -> jax.Array:
    """Next-token CE through the pipeline (drop-in for loss_fn)."""
    logits, aux = pipeline_forward_with_aux(
        params, tokens[:, :-1], cfg, mesh, n_microbatches
    )
    return next_token_loss(logits, aux, tokens, cfg)


def pipeline_sharding_rules(cfg: Any = None, mesh: Mesh = None) -> Any:
    """Param specs for a ("data", "pipe"[, "model"]) mesh: layer stacks
    shard their leading layer axis over ``pipe`` while KEEPING the
    tensor-parallel ``model`` shardings inside each stage (pp x tp).
    Without a model axis on the mesh, the in-stage specs replicate."""
    from .sharding import param_sharding_rules

    rules = param_sharding_rules(cfg, mesh)
    has_model = mesh is not None and "model" in mesh.axis_names

    def stage_spec(spec: P) -> P:
        rest = tuple(spec)[1:]  # the leading dim is the layer axis
        if not has_model:
            rest = tuple(None if a == "model" else a for a in rest)
        return P("pipe", *rest)

    rules["layers"] = jax.tree_util.tree_map(
        stage_spec, rules["layers"]
    )
    if not has_model:
        rules["embed"] = P(None, None)
        rules["unembed"] = P(None, None)
    return rules
