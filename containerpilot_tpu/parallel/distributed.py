"""Multi-host initialization: jax.distributed wired to the supervisor's
catalog.

A multi-host pod needs every process to agree on (coordinator address,
process count, process id) before JAX's collectives can span hosts over
DCN. Two paths:

- ``initialize_from_env()``: standard TPU-pod metadata / explicit env
  (``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID``) — on
  Cloud TPU pods ``jax.distributed.initialize()`` with no args reads
  the platform metadata itself.
- ``initialize_from_catalog(backend, ...)``: the supervisor's service
  catalog elects the coordinator — process 0 registers
  ``jax-coordinator`` (its supervisor health-checks and advertises it
  like any service); other hosts poll the catalog until it appears.
  This is the TPU-native analog of the reference's pattern where
  cross-host dependencies are *only* expressed through the catalog
  (reference: docs/10-lifecycle.md behavior, SURVEY.md §2 checklist).

Either way the actual data plane is XLA collectives over ICI/DCN; this
module only solves the rendezvous.
"""
from __future__ import annotations

import logging
import os
import socket
import time
from typing import Optional

import jax

from ..discovery import Backend, ServiceRegistration

log = logging.getLogger("containerpilot.distributed")

COORDINATOR_SERVICE = "jax-coordinator"
DEFAULT_COORDINATOR_PORT = 8476


def initialize_from_env() -> None:
    """Initialize jax.distributed from environment variables, or let
    JAX read platform metadata when none are set."""
    address = os.environ.get("COORDINATOR_ADDRESS")
    if address:
        num = int(os.environ.get("NUM_PROCESSES", "1"))
        pid = int(os.environ.get("PROCESS_ID", "0"))
        jax.distributed.initialize(
            coordinator_address=address, num_processes=num, process_id=pid
        )
    else:
        jax.distributed.initialize()
    log.info(
        "distributed: process %d/%d ready",
        jax.process_index(),
        jax.process_count(),
    )


def initialize_from_catalog(
    backend: Backend,
    process_id: int,
    num_processes: int,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
    advertise_address: str = "",
    timeout: float = 300.0,
    poll_interval: float = 2.0,
) -> None:
    """Rendezvous through the supervisor's catalog.

    Process 0 registers the ``jax-coordinator`` service (passing, with
    a generous TTL) and starts the coordinator; other processes poll
    the catalog for it.
    """
    if process_id == 0:
        address = advertise_address or _routable_address()
        # the coordinator role is singular: clear any stale registration
        # from a previous pod incarnation so workers can't rendezvous
        # with a dead host
        for stale in backend.instances(COORDINATOR_SERVICE):
            log.info(
                "distributed: removing stale coordinator %s", stale.id
            )
            try:
                backend.service_deregister(stale.id)
            except Exception as exc:  # noqa: BLE001
                # best-effort: on Consul, another agent's registration
                # can't be deregistered locally — never abort rendezvous
                log.warning(
                    "distributed: could not remove %s: %s", stale.id, exc
                )
        registration = ServiceRegistration(
            id=f"{COORDINATOR_SERVICE}-{socket.gethostname()}",
            name=COORDINATOR_SERVICE,
            port=coordinator_port,
            address=address,
            # rendezvous info is static for the pod's lifetime and the
            # coordinator never heartbeats it, so the TTL must outlive
            # the pod: a restarted worker must still find it
            ttl=max(int(timeout), 7 * 24 * 3600),
        )
        backend.service_register(registration, status="passing")
        coordinator = f"{address}:{coordinator_port}"
        log.info("distributed: registered coordinator at %s", coordinator)
    else:
        coordinator = _discover_coordinator(
            backend, coordinator_port, timeout, poll_interval
        )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "distributed: process %d/%d ready via catalog rendezvous",
        jax.process_index(),
        jax.process_count(),
    )


def _routable_address() -> str:
    """This host's DCN-routable IP. ``gethostbyname(hostname)`` often
    resolves to 127.0.0.1 (Debian-style /etc/hosts), which would make
    every worker rendezvous with itself — prefer the interface a real
    outbound route uses."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet is sent
            address = s.getsockname()[0]
        if not address.startswith("127."):
            return address
    except OSError:
        pass
    address = socket.gethostbyname(socket.gethostname())
    if address.startswith("127."):
        log.warning(
            "distributed: advertising loopback %s as coordinator; pass "
            "advertise_address= for multi-host pods",
            address,
        )
    return address


def _discover_coordinator(
    backend: Backend,
    coordinator_port: int,
    timeout: float,
    poll_interval: float,
) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        instances = backend.instances(COORDINATOR_SERVICE)
        if instances:
            inst = instances[0]
            port = inst.port or coordinator_port
            return f"{inst.address}:{port}"
        time.sleep(poll_interval)
    raise TimeoutError(
        f"no {COORDINATOR_SERVICE!r} appeared in the catalog within "
        f"{timeout:.0f}s"
    )
