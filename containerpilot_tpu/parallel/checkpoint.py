"""Checkpoint save/restore for supervised training.

The supervisor restarts a crashed trainer (restart budgets,
health-check failures); the trainer resumes from its latest checkpoint
— together they give crash-fault tolerance the reference can't express
(its closest analog is config reload preserving container uptime,
reference: SURVEY.md §5 checkpoint/resume row).

Layout: <dir>/step_<n>/ orbax checkpoints; ``latest_step`` scans for
the newest complete one. Saves are atomic (orbax writes to a tmp dir
and renames), so a crash mid-save can't corrupt the resume point.

Multi-process pods: orbax is a GLOBAL checkpointer under
``jax.distributed`` — every process must call save/restore in lockstep
on the SAME directory (shared storage; on real pods, GCS). Data for
replicated arrays is written by the primary process only and save
holds cross-process barriers, so per-process directories would leave
the non-primary dirs empty — and a later lopsided restore (one process
finds a checkpoint, its peer finds none and skips) deadlocks the pod
before any step runs. One directory per pod makes the resume-step
decision identical everywhere by construction.
"""
from __future__ import annotations

import logging
import os
import re
import shutil
from typing import Any, Optional

import jax

log = logging.getLogger("containerpilot.checkpoint")

_STEP_DIR = re.compile(r"^step_(\d+)$")

# older checkpoints kept after each save (crash tolerance only needs
# the latest; one spare guards against a corrupt newest)
KEEP_CHECKPOINTS = 2

_checkpointer = None
_async_checkpointer = None


def _get_checkpointer():
    """One orbax checkpointer per process; orbax imported lazily so the
    supervisor half never needs it installed."""
    global _checkpointer
    if _checkpointer is None:
        import orbax.checkpoint as ocp

        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _get_async_checkpointer():
    """The async variant: ``save`` copies device arrays to host
    synchronously (so the caller may donate/overwrite its buffers
    immediately) and writes to disk on a background thread."""
    global _async_checkpointer
    if _async_checkpointer is None:
        import orbax.checkpoint as ocp

        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler()
        )
    return _async_checkpointer


def wait_for_checkpoints() -> None:
    """Block until every in-flight async save has committed. Call
    before process exit (or before reading back a just-saved step)."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()


def _step_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def latest_step(directory: str) -> Optional[int]:
    """Newest complete checkpoint step in the directory, if any."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for entry in entries:
        # the anchored regex admits only completed "step_<n>" dirs;
        # orbax's in-progress tmp dirs carry a suffix and never match
        m = _STEP_DIR.match(entry)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def _prune(directory: str, keep: int) -> None:
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    steps = sorted(
        int(m.group(1)) for e in entries if (m := _STEP_DIR.match(e))
    )
    for step in steps[:-keep] if keep > 0 else []:
        path = _step_path(directory, step)
        log.debug("checkpoint: pruning %s", path)
        shutil.rmtree(path, ignore_errors=True)


def save_checkpoint(
    directory: str, step: int, state: Any, keep: int = KEEP_CHECKPOINTS,
    wait: bool = True,
) -> None:
    """Write a checkpoint. ``wait=False`` returns as soon as the
    device->host copy is done and commits to disk on a background
    thread — the train loop overlaps the write with the next steps
    (donating its buffers is safe; the copy already happened). A
    second async save first drains the previous one; incomplete saves
    never match latest_step's anchored step_<n> regex, so a crash
    mid-write cannot corrupt the resume point."""
    if wait:
        ckptr = _get_checkpointer()
        ckptr.save(_step_path(directory, step), state, force=True)
        ckptr.wait_until_finished()
        _prune(directory, keep)
        log.info("checkpoint: saved step %d to %s", step, directory)
        return
    import orbax.checkpoint as ocp

    ckptr = _get_async_checkpointer()
    ckptr.save(
        _step_path(directory, step), args=ocp.args.StandardSave(state),
        force=True,
    )
    # prune committed older steps now (different dirs; the in-flight
    # write is untouched)
    _prune(directory, keep)
    log.info(
        "checkpoint: async save of step %d to %s started", step, directory
    )


def _to_abstract(x: Any) -> Any:
    # carry shardings through so the restore lands arrays exactly
    # where the training step expects them (replicated scalars
    # included)
    sharding = getattr(x, "sharding", None)
    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)


def restore_checkpoint(directory: str, state_like: Any) -> Optional[Any]:
    """Restore the latest checkpoint into the structure (and shardings)
    of ``state_like``; None when no checkpoint exists."""
    step = latest_step(directory)
    if step is None:
        return None

    abstract = jax.tree.map(_to_abstract, state_like)
    restored = _get_checkpointer().restore(_step_path(directory, step), abstract)
    log.info("checkpoint: restored step %d from %s", step, directory)
    return restored


def _swap_in_ema(node: Any, replacement: Any):
    """Replace the EMA shadow subtree (an EmaState namedtuple, or the
    single-key {"ema": ...} mapping orbax metadata renders it as) with
    ``replacement``. Returns (new_node, found)."""
    fields = getattr(node, "_fields", None)
    if fields == ("ema",):
        return type(node)(ema=replacement), True
    if isinstance(node, dict):
        if set(node) == {"ema"}:
            return {"ema": replacement}, True
        out, found = {}, False
        for k, v in node.items():
            out[k], f = _swap_in_ema(v, replacement)
            found = found or f
        return out, found
    if isinstance(node, (tuple, list)):
        out, found = [], False
        for v in node:
            nv, f = _swap_in_ema(v, replacement)
            out.append(nv)
            found = found or f
        if fields is not None:  # other namedtuples: rebuild by position
            return type(node)(*out), found
        return type(node)(out) if isinstance(node, list) else tuple(out), found
    return node, False


def _extract_ema(node: Any) -> Optional[Any]:
    """The EMA subtree's contents from a restored opt_state, whichever
    container shape the restore produced it in."""
    fields = getattr(node, "_fields", None)
    if fields == ("ema",):
        return node.ema
    if isinstance(node, dict):
        if set(node) == {"ema"}:
            return node["ema"]
        for v in node.values():
            found = _extract_ema(v)
            if found is not None:
                return found
        return None
    if isinstance(node, (tuple, list)):
        for v in node:
            found = _extract_ema(v)
            if found is not None:
                return found
    return None


class RestoredParams(tuple):
    """The ``(params, step)`` pair restore_params hands back, which
    additionally records on ``.ema`` whether the EMA shadow is what
    was actually restored — consumers report what they scored from
    the restore itself, not from a separate metadata probe that can
    disagree with it (e.g. a transient metadata-read failure on a
    checkpoint that does carry a shadow)."""

    ema: bool

    def __new__(cls, params: Any, step: Any, ema: bool):
        self = super().__new__(cls, (params, step))
        self.ema = ema
        return self

    def __getnewargs__(self):
        # tuple's default supplies one arg; __new__ needs three, so
        # pickle/deepcopy would otherwise TypeError
        return (self[0], self[1], self.ema)


def restore_params(
    directory: str, state_like: Any, prefer_ema: bool = False
) -> Optional["RestoredParams"]:
    """Restore ONLY the params (and step) of the latest train-state
    checkpoint — optimizer moments are orbax PLACEHOLDERs and never
    leave disk. Serving pays params-sized memory instead of the full
    train state (adam's mu/nu alone double it).

    ``state_like`` is a TrainState-shaped pytree of arrays or
    ShapeDtypeStructs (e.g. from abstract_train_state). Returns a
    RestoredParams (a ``(params, step)`` tuple with ``.ema``) or None
    when no checkpoint exists.

    ``prefer_ema``: when the checkpoint was written by a with_ema
    optimizer (train.with_ema), return the EMA shadow weights instead
    of the raw params — still params-sized (the shadow mirrors the
    param tree and restores onto the same shardings; adam's mu/nu stay
    on disk). Falls back to the raw params with a warning if the
    checkpoint carries no EMA.
    """
    step = latest_step(directory)
    if step is None:
        return None
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(_to_abstract, state_like)
    # TrainState is a registered pytree (params, opt_state, step);
    # rebuild it with real abstract leaves only where we want data.
    # StandardCheckpointer rejects PLACEHOLDER leaves; the PyTree
    # handler (same on-disk format) honors them. The opt_state
    # skeleton's STRUCTURE comes from the checkpoint's own metadata,
    # not the caller: the serving process doesn't know the training
    # optimizer's layout (an lr schedule adds a count state), and a
    # placeholder-only subtree needs structure, nothing else.
    from .train import TrainState

    try:
        meta = ocp.PyTreeCheckpointer().metadata(
            _step_path(directory, step)
        ).item_metadata
        meta_tree = meta.tree if hasattr(meta, "tree") else meta
        opt_skeleton = jax.tree.map(lambda _: ocp.PLACEHOLDER, meta_tree[1])
    except (KeyError, IndexError, TypeError, AttributeError):
        # metadata shape surprised us: fall back to the caller's layout
        opt_skeleton = jax.tree.map(
            lambda _: ocp.PLACEHOLDER, abstract.opt_state
        )
    ema_found = False
    if prefer_ema:
        # materialize the EMA shadow (param-shaped, param-sharded)
        # while every other optimizer leaf stays a placeholder
        opt_skeleton, ema_found = _swap_in_ema(
            opt_skeleton, abstract.params
        )
        if not ema_found:
            log.warning(
                "checkpoint: prefer_ema requested but %s step %d has "
                "no EMA shadow; restoring raw params", directory, step,
            )
    # with the EMA materialized the raw params stay on disk too, so the
    # restore is params-sized either way
    params_target = (
        jax.tree.map(lambda _: ocp.PLACEHOLDER, abstract.params)
        if ema_found else abstract.params
    )
    target = TrainState(
        params=params_target,
        opt_state=opt_skeleton,
        step=abstract.step,
    )
    # explicit per-leaf restore_args: PyTreeRestore ignores the
    # shardings carried on abstract leaves and would otherwise fall
    # back to the sharding file saved at TRAINING time — wrong (or
    # fatal) when serving on a different topology
    def restore_arg(leaf: Any) -> Any:
        if leaf is ocp.PLACEHOLDER:
            return ocp.RestoreArgs()
        return ocp.ArrayRestoreArgs(sharding=leaf.sharding)

    restore_args = jax.tree.map(
        restore_arg, target, is_leaf=lambda x: x is ocp.PLACEHOLDER
    )
    restored = ocp.PyTreeCheckpointer().restore(
        _step_path(directory, step),
        ocp.args.PyTreeRestore(item=target, restore_args=restore_args),
    )
    log.info(
        "checkpoint: restored params-only step %d from %s", step, directory
    )
    if ema_found:
        ema = _extract_ema(restored.opt_state)
        if ema is not None:
            return RestoredParams(ema, restored.step, True)
        # restored.params are placeholders here (swapped out above);
        # re-restore the raw params rather than hand back sentinels
        log.warning(
            "checkpoint: EMA subtree lost in restore; re-restoring "
            "raw params"
        )
        return restore_params(directory, state_like, prefer_ema=False)
    return RestoredParams(restored.params, restored.step, False)
