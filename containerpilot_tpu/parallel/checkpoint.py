"""Checkpoint save/restore for supervised training.

The supervisor restarts a crashed trainer (restart budgets,
health-check failures); the trainer resumes from its latest checkpoint
— together they give crash-fault tolerance the reference can't express
(its closest analog is config reload preserving container uptime,
reference: SURVEY.md §5 checkpoint/resume row).

Layout: <dir>/step_<n>/ orbax checkpoints; ``latest_step`` scans for
the newest complete one. Saves are atomic (orbax writes to a tmp dir
and renames), so a crash mid-save can't corrupt the resume point.
"""
from __future__ import annotations

import logging
import os
import re
import shutil
from typing import Any, Optional

import jax

log = logging.getLogger("containerpilot.checkpoint")

_STEP_DIR = re.compile(r"^step_(\d+)$")

# older checkpoints kept after each save (crash tolerance only needs
# the latest; one spare guards against a corrupt newest)
KEEP_CHECKPOINTS = 2

_checkpointer = None


def _get_checkpointer():
    """One orbax checkpointer per process; orbax imported lazily so the
    supervisor half never needs it installed."""
    global _checkpointer
    if _checkpointer is None:
        import orbax.checkpoint as ocp

        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _step_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def latest_step(directory: str) -> Optional[int]:
    """Newest complete checkpoint step in the directory, if any."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for entry in entries:
        # the anchored regex admits only completed "step_<n>" dirs;
        # orbax's in-progress tmp dirs carry a suffix and never match
        m = _STEP_DIR.match(entry)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def _prune(directory: str, keep: int) -> None:
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    steps = sorted(
        int(m.group(1)) for e in entries if (m := _STEP_DIR.match(e))
    )
    for step in steps[:-keep] if keep > 0 else []:
        path = _step_path(directory, step)
        log.debug("checkpoint: pruning %s", path)
        shutil.rmtree(path, ignore_errors=True)


def save_checkpoint(
    directory: str, step: int, state: Any, keep: int = KEEP_CHECKPOINTS
) -> None:
    ckptr = _get_checkpointer()
    ckptr.save(_step_path(directory, step), state, force=True)
    ckptr.wait_until_finished()
    _prune(directory, keep)
    log.info("checkpoint: saved step %d to %s", step, directory)


def restore_checkpoint(directory: str, state_like: Any) -> Optional[Any]:
    """Restore the latest checkpoint into the structure (and shardings)
    of ``state_like``; None when no checkpoint exists."""
    step = latest_step(directory)
    if step is None:
        return None

    def to_abstract(x: Any) -> Any:
        # carry shardings through so the restore lands arrays exactly
        # where the training step expects them (replicated scalars
        # included)
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    abstract = jax.tree.map(to_abstract, state_like)
    restored = _get_checkpointer().restore(_step_path(directory, step), abstract)
    log.info("checkpoint: restored step %d from %s", step, directory)
    return restored
