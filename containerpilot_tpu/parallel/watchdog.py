"""Step-deadline watchdog: in-process failure detection for
distributed training.

When a peer host dies mid-step, the survivors block inside a
collective — no exception, no exit, nothing for the supervisor to
restart. A multi-host trainer therefore self-monitors: beat() every
completed step; if no beat lands within the deadline the watchdog
hard-exits the process (``os._exit`` — a wedged collective cannot be
unwound by Python exception handling, and atexit/finally handlers may
themselves block). The supervisor then sees a dead child, applies the
restart budget, and the reincarnated pod re-rendezvouses through the
catalog and resumes from the latest checkpoint — turning a silent hang
into the crash/restart/resume path the rest of the stack already
handles (SURVEY.md §5 failure detection; the reference's analog is
health-check TTL expiry driving catalog criticality).
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time

log = logging.getLogger("containerpilot.watchdog")

EXIT_CODE = 86  # distinguishable from a crash (1) or a signal (>128)


class StepWatchdog:
    """Exit the process if ``beat()`` stops arriving.

    >>> dog = StepWatchdog(timeout_s=60).start()
    >>> for batch in data:
    ...     state = train_step(state, batch)
    ...     dog.beat()
    >>> dog.stop()

    The deadline should comfortably exceed the slowest legitimate step
    (including any compile the step might trigger): a false positive
    costs a restart-budget slot.

    ``start(grace_s=...)`` widens the deadline for the FIRST beat only:
    arm the watchdog before rendezvous/restore/first-compile and the
    whole startup window is covered (a peer that died between catalog
    rendezvous and its first collective wedges the survivor's restore
    barrier or first all-reduce just as silently as a mid-run death),
    while steady-state steps still get the tight deadline.
    """

    def __init__(self, timeout_s: float, exit_code: int = EXIT_CODE) -> None:
        if timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")
        self.timeout_s = timeout_s
        self.exit_code = exit_code
        self._deadline_s = timeout_s
        self._last = time.monotonic()
        self._stopped = threading.Event()
        self._thread: threading.Thread = None

    def beat(self) -> None:
        self._last = time.monotonic()
        self._deadline_s = self.timeout_s

    def start(self, grace_s: float = None) -> "StepWatchdog":
        self._last = time.monotonic()  # the clock starts now
        if grace_s is not None:
            if grace_s < self.timeout_s:
                raise ValueError("grace_s must be >= timeout_s")
            self._deadline_s = grace_s
        self._thread = threading.Thread(
            target=self._watch, name="step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _watch(self) -> None:
        # poll at a fraction of the deadline: detection latency is at
        # most timeout + poll, and a sleeping thread costs nothing
        poll = min(self.timeout_s / 4, 1.0)
        while not self._stopped.wait(poll):
            overdue = time.monotonic() - self._last
            if overdue > self._deadline_s:
                log.error(
                    "watchdog: no step in %.1fs (deadline %.1fs); "
                    "exiting %d for the supervisor to restart",
                    overdue, self._deadline_s, self.exit_code,
                )
                # best effort: get the log line out before dying
                for stream in (sys.stderr, sys.stdout):
                    try:
                        stream.flush()
                    except Exception:  # noqa: BLE001 — cpcheck: disable=CP-SWALLOW best-effort flush on the road to os._exit
                        pass
                os._exit(self.exit_code)
