"""Parallelism: device meshes, sharding rules, distributed train step.

This is the TPU-native "distributed communication backend" of the
framework's workload half. Where the reference supervisor coordinates
*processes* through a catalog (reference: discovery/), the workload it
supervises coordinates *chips* through jax.sharding: pick a Mesh,
annotate shardings, and let XLA insert the collectives over ICI/DCN
(SURVEY.md §5 distributed-backend mapping).
"""
from .checkpoint import (
    wait_for_checkpoints,
    latest_step,
    restore_checkpoint,
    restore_params,
    save_checkpoint,
)
from .context import (
    context_parallel_config,
    cp_generate,
    flash_parallel_config,
)
from .distributed import initialize_from_catalog, initialize_from_env
from .watchdog import StepWatchdog
from .mesh import MeshPlan, make_mesh
from .pipeline import (
    pipeline_forward_with_aux,
    pipeline_loss_fn,
    pipeline_sharding_rules,
)
from .sharding import (
    fsdp_sharding_rules,
    param_sharding_rules,
    shard_params,
)
from .train import (
    TrainState,
    abstract_train_state,
    ema_params,
    with_ema,
    init_train_state,
    lora_abstract_state,
    make_lora_train_step,
    make_optimizer,
    make_pipeline_train_step,
    make_train_step,
    train_state_shardings,
)

__all__ = [
    "MeshPlan",
    "context_parallel_config",
    "cp_generate",
    "flash_parallel_config",
    "make_pipeline_train_step",
    "make_mesh",
    "fsdp_sharding_rules",
    "param_sharding_rules",
    "shard_params",
    "TrainState",
    "abstract_train_state",
    "ema_params",
    "with_ema",
    "make_train_step",
    "init_train_state",
    "lora_abstract_state",
    "make_lora_train_step",
    "make_optimizer",
    "train_state_shardings",
    "save_checkpoint",
    "wait_for_checkpoints",
    "restore_checkpoint",
    "restore_params",
    "latest_step",
    "initialize_from_catalog",
    "initialize_from_env",
    "StepWatchdog",
    "pipeline_forward_with_aux",
    "pipeline_loss_fn",
    "pipeline_sharding_rules",
]
