"""Context parallelism: bind ring attention into the model config.

Long sequences are sharded over the mesh's ``seq`` axis; attention runs
as a ring (ops/ring_attention.py) while every other op stays local and
XLA partitions it from the shard_map boundary's in/out specs. The rest
of the stack — sharding rules, optimizer, train step — is unchanged:
context parallelism composes with tensor and data parallelism by
construction.

Serving gets the same long-context story through ``cp_generate``: the
PREFILL — the quadratic, activation-heavy part of a long-prompt
request — runs ring attention over the seq axis, then the KV cache
gathers off the ring once and the decode scan runs on the existing
(unsharded) path with the full sampling contract.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig, flash_eligible
from ..ops.ring_attention import ring_attention, shard_map


def flash_parallel_config(
    cfg: TransformerConfig, mesh: Mesh
) -> TransformerConfig:
    """Bind mesh-aware attention auto-selection for pjit'd training.

    pallas calls don't partition under automatic pjit sharding, so the
    flash path must run under shard_map. Causal attention is
    independent per (batch, head), and the tensor-parallel rules shard
    heads over ``model`` and batch over ``data``
    (parallel/sharding.py) — so the manual region needs no collectives
    at all: each device runs the flash kernel on its local
    [b/data, s, h/model, hd] block. Below the flash threshold the
    plain einsum path is returned and XLA partitions it as before.
    """
    spec = P("data", None, "model", None)

    def attn(q, k, v):
        if not flash_eligible(cfg, q.shape[1]):
            from ..ops.attention import causal_attention

            return causal_attention(q, k, v, window=cfg.window)
        from ..ops import tuning
        from ..ops.flash import flash_attention

        # seq is unsharded here (spec leaves axis 1 unpartitioned), so
        # the tuned 'train' blocks for the global seq apply locally too
        bq, bk = tuning.pick_blocks("train", q.shape[1])
        f = shard_map(
            lambda q, k, v: flash_attention(
                q, k, v, block_q=bq, block_k=bk, window=cfg.window
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return f(q, k, v)

    return dataclasses.replace(cfg, attention_fn=attn)


def context_parallel_config(
    cfg: TransformerConfig, mesh: Mesh, axis_name: str = "seq"
) -> TransformerConfig:
    """A config whose attention runs as a ring over ``axis_name``."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names}"
        )
    if cfg.window > 0:
        raise ValueError(
            "sliding-window attention does not compose with ring "
            "attention yet: a window shorter than the shard makes "
            "most ring hops no-ops — use the flash window path on a "
            "(data, model) mesh instead"
        )

    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name)

    # the ring handles grouped kv itself (rotates the SMALL K/V over
    # ICI); the layer passes unrepeated heads through
    attn.gqa_native = True
    return dataclasses.replace(cfg, attention_fn=attn)


@functools.lru_cache(maxsize=8)
def _cp_prefill_fn(cfg: TransformerConfig, mesh: Mesh, max_len: int,
                   axis_name: str):
    """One compiled context-parallel prefill per (config, mesh,
    max_len): ring attention over the seq axis while every other op
    stays seq-local under XLA's partitioner, then ONE gather point —
    the decode scan reads the whole cache every step, so the cache
    leaves the ring replicated here rather than re-gathering per
    step. Cached at this level because context_parallel_config builds
    a fresh attention closure per call (a fresh closure would defeat
    jit's own cache)."""
    cfg_cp = context_parallel_config(cfg, mesh, axis_name)
    from ..models.decode import prefill

    replicated = NamedSharding(mesh, P())

    def fn(params, prompt):
        logits, cache = prefill(params, prompt, cfg_cp, max_len)
        cache = jax.tree.map(
            lambda x: lax.with_sharding_constraint(x, replicated),
            cache,
        )
        return lax.with_sharding_constraint(logits, replicated), cache

    return jax.jit(fn)


def resolve_cp_min_len(cp_min_len: int, seq_axis: int, max_len: int,
                       flag: str = "cp") -> int:
    """The ONE copy of the cp threshold policy both servers apply
    (workload/serve.py and serve_dist.py): derive an unset threshold
    to something that amortizes a ring (self-clamped so it always CAN
    engage), clamp an explicit value below the axis up to the floor
    (the prompt's head must cover the axis), and refuse configurations
    where cp could never engage. Raises ValueError (callers map to
    their own exit types)."""
    if seq_axis >= max_len:
        # no admissible prompt can cover the axis: cp could never
        # engage no matter the threshold
        raise ValueError(
            f"--{flag} never engages: the seq axis ({seq_axis}) is "
            f"not below max_len ({max_len})"
        )
    if cp_min_len == 0:
        return min(8 * seq_axis, max_len - 1)
    if cp_min_len < seq_axis:
        return seq_axis
    if cp_min_len >= max_len:
        # the user's own threshold excludes every admissible prompt
        # (prompt_len + max_new <= max_len): fail at startup, not as
        # a feature that silently never runs
        raise ValueError(
            f"--{flag} never engages: cp_min_len {cp_min_len} >= "
            f"max_len {max_len} (lower the threshold or raise "
            "max_len)"
        )
    return cp_min_len


def cp_head_buckets(cp_min_len: int, max_len: int, axis: int):
    """The static set of ring-head lengths a multi-process server
    compiles AT STARTUP: the smallest axis-divisible length that can
    satisfy cp_min_len, then doubling below max_len.

    Why static: a ring program's ppermute needs a cross-process
    communicator whose initialization carries a hard ~30s deadline
    (observed as 'Gloo context initialization failed: GetKeyValue()
    timed out' killing a live pod when two processes compiled a
    first-use ring program with >30s skew). Replicated programs can
    compile per-shape at request time — compile skew just delays the
    slower process — but COLLECTIVE programs must all exist before
    traffic, which means their shape set must be finite. Heads
    bucket; the (local, collective-free) remainder extend stays
    per-length."""
    if axis < 2:
        return []
    floor = max(cp_min_len - cp_min_len % axis, axis)
    out = []
    b = floor
    while b < max_len:
        out.append(b)
        b *= 2
    return out


def pick_cp_head(plen: int, buckets) -> int:
    """Largest startup-compiled ring head that fits the prompt
    (0 = none fits; take the plain path)."""
    head = 0
    for b in buckets:
        if b <= plen:
            head = b
    return head


def cp_prefill_with_remainder(
    params,
    prompt_host,
    cfg: TransformerConfig,
    mesh: Mesh,
    max_len: int,
    axis_name: str = "seq",
    head: int = 0,
    prefill_chunk: int = 0,
):
    """The ONE copy of the cp prefill recipe both ``cp_generate`` and
    the pod's slot admission (workload/serve_dist.py) run: a HEAD of
    the prompt rings through prefill sharded over ``axis_name``, the
    remainder extends the gathered cache with one (local,
    collective-free) chunk. Returns (last logits, cache), both
    replicated.

    ``head`` = 0 takes the largest axis-divisible head (the
    single-process ``cp_generate`` default — maximal ring work); a
    multi-process pod passes a STARTUP-COMPILED bucket from
    ``cp_head_buckets`` instead, because a first-use ring program's
    communicator init has a hard ~30s deadline that request-time
    compile skew between processes can blow (see cp_head_buckets).

    ``prompt_host`` is a host array ([1, plen], identical on every
    process); placement uses ``make_array_from_callback`` so the same
    code serves single-process meshes and multi-host pods (where a
    plain device_put of a global sharding is not allowed).

    ``prefill_chunk`` caps the remainder's extend pieces at
    ``max(axis, prefill_chunk)`` — the pod passes its
    ``--prefill-chunk`` so the per-device activation guarantee holds
    even for the bucketed-head worst case (see the step cap below)."""
    import numpy as np

    plen = int(prompt_host.shape[1])
    axis = mesh.shape[axis_name]
    if head == 0:
        head = plen - plen % axis
    if head <= 0:
        raise ValueError(
            f"prompt len {plen} is shorter than the {axis_name} axis "
            f"({axis}): nothing to shard — use the plain path"
        )
    if head % axis or head > plen:
        raise ValueError(
            f"head {head} must be a multiple of the {axis_name} axis "
            f"({axis}) and <= prompt len {plen}"
        )
    head_host = np.ascontiguousarray(prompt_host[:, :head], np.int32)
    sharding = NamedSharding(mesh, P(None, axis_name))
    sharded = jax.make_array_from_callback(
        head_host.shape, sharding, lambda idx: head_host[idx]
    )
    logits, cache = _cp_prefill_fn(cfg, mesh, max_len, axis_name)(
        params, sharded
    )
    # Extend the remainder in power-of-two chunks down to a < axis
    # tail, NOT one remainder-length call: a bucketed head can leave a
    # remainder up to head-1 tokens, and a single extend of that would
    # (a) compile one program per distinct remainder length —
    # unbounded shape set — and (b) run one local chunk-x-cache
    # attention at up to half the full quadratic prefill, defeating
    # the memory bound cp exists to provide. The power-of-two steps
    # are CAPPED at max(axis, prefill_chunk): without the cap the
    # largest step can reach head-1 tokens, whose chunk-x-cache
    # attention peaks ~axis/2 times the ring's per-device bound —
    # exactly the worst case --sp advertises protection against
    # (ADVICE r5). The chunk shapes stay data-independent:
    # {2^k : axis <= 2^k <= cap} plus the < axis tail lengths —
    # finite, so a long-lived server stops compiling and the pod's
    # compile-skew story is unchanged. With a maximal head
    # (head == plen - plen % axis, the cp_generate default) the
    # remainder is < axis and this loop is exactly the original
    # one-tiny-chunk behavior.
    if head < plen:
        from ..models.decode import _jitted_extend

        cap = max(axis, prefill_chunk)
        pos = head
        extend = _jitted_extend(cfg)
        while pos < plen:
            left = plen - pos
            step = left
            if left >= axis:
                step = 1
                while step * 2 <= min(left, cap):
                    step *= 2
            logits, cache = extend(
                params, cache,
                jax.numpy.asarray(
                    prompt_host[:, pos:pos + step], jax.numpy.int32
                ),
            )
            pos += step
    return logits, cache


def cp_generate(
    params,
    prompt: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    max_new_tokens: int,
    max_len: int,
    axis_name: str = "seq",
    **sampling,
):
    """Long-prompt generation with context-parallel prefill: the
    prompt shards over ``axis_name`` (each device holds seq/P tokens;
    the quadratic attention runs as a ring, activations stay
    seq-local), the cache gathers once, and the decode runs
    ``generate_from_cache`` with the full sampling contract
    (temperature/top_k/top_p/eos/min_new/penalties/logit_bias).

    Ring attention needs the sharded length to divide by the seq
    axis, so the largest axis-divisible HEAD of the prompt rings
    through prefill and any remainder (< axis tokens) extends the
    gathered cache with one short decode_chunk — arbitrary prompt
    lengths, exact semantics, at most axis-1 tiny extend programs.
    Numerics: ring attention's online softmax is the same math as
    single-device attention up to float reassociation — greedy output
    matches the unsharded path away from argmax ties.
    """
    plen = int(prompt.shape[1])
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names} "
            "(build it with MeshPlan(seq=...))"
        )
    if plen + max_new_tokens > max_len:
        raise ValueError(
            f"prompt_len {plen} + max_new_tokens {max_new_tokens} "
            f"exceeds max_len {max_len}"
        )
    import numpy as np

    from ..models.decode import generate_from_cache

    logits, cache = cp_prefill_with_remainder(
        params, np.asarray(jax.device_get(prompt)), cfg, mesh,
        max_len, axis_name,
    )
    return generate_from_cache(
        params, cache, logits, cfg, max_new_tokens, pos=plen,
        **sampling,
    )
