"""Context parallelism: bind ring attention into the model config.

Long sequences are sharded over the mesh's ``seq`` axis; attention runs
as a ring (ops/ring_attention.py) while every other op stays local and
XLA partitions it from the shard_map boundary's in/out specs. The rest
of the stack — sharding rules, optimizer, train step — is unchanged:
context parallelism composes with tensor and data parallelism by
construction.
"""
from __future__ import annotations

import dataclasses

from jax.sharding import Mesh

from ..models.transformer import TransformerConfig
from ..ops.ring_attention import ring_attention


def context_parallel_config(
    cfg: TransformerConfig, mesh: Mesh, axis_name: str = "seq"
) -> TransformerConfig:
    """A config whose attention runs as a ring over ``axis_name``."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names}"
        )

    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name)

    return dataclasses.replace(cfg, attention_fn=attn)
