"""Context parallelism: bind ring attention into the model config.

Long sequences are sharded over the mesh's ``seq`` axis; attention runs
as a ring (ops/ring_attention.py) while every other op stays local and
XLA partitions it from the shard_map boundary's in/out specs. The rest
of the stack — sharding rules, optimizer, train step — is unchanged:
context parallelism composes with tensor and data parallelism by
construction.
"""
from __future__ import annotations

import dataclasses

from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import TransformerConfig, flash_eligible
from ..ops.ring_attention import ring_attention, shard_map


def flash_parallel_config(
    cfg: TransformerConfig, mesh: Mesh
) -> TransformerConfig:
    """Bind mesh-aware attention auto-selection for pjit'd training.

    pallas calls don't partition under automatic pjit sharding, so the
    flash path must run under shard_map. Causal attention is
    independent per (batch, head), and the tensor-parallel rules shard
    heads over ``model`` and batch over ``data``
    (parallel/sharding.py) — so the manual region needs no collectives
    at all: each device runs the flash kernel on its local
    [b/data, s, h/model, hd] block. Below the flash threshold the
    plain einsum path is returned and XLA partitions it as before.
    """
    spec = P("data", None, "model", None)

    def attn(q, k, v):
        if not flash_eligible(cfg, q.shape[1]):
            from ..ops.attention import causal_attention

            return causal_attention(q, k, v, window=cfg.window)
        from ..ops import tuning
        from ..ops.flash import flash_attention

        # seq is unsharded here (spec leaves axis 1 unpartitioned), so
        # the tuned 'train' blocks for the global seq apply locally too
        bq, bk = tuning.pick_blocks("train", q.shape[1])
        f = shard_map(
            lambda q, k, v: flash_attention(
                q, k, v, block_q=bq, block_k=bk, window=cfg.window
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return f(q, k, v)

    return dataclasses.replace(cfg, attention_fn=attn)


def context_parallel_config(
    cfg: TransformerConfig, mesh: Mesh, axis_name: str = "seq"
) -> TransformerConfig:
    """A config whose attention runs as a ring over ``axis_name``."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names}"
        )
    if cfg.window > 0:
        raise ValueError(
            "sliding-window attention does not compose with ring "
            "attention yet: a window shorter than the shard makes "
            "most ring hops no-ops — use the flash window path on a "
            "(data, model) mesh instead"
        )

    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name)

    # the ring handles grouped kv itself (rotates the SMALL K/V over
    # ICI); the layer passes unrepeated heads through
    attn.gqa_native = True
    return dataclasses.replace(cfg, attention_fn=attn)
