"""Context parallelism: bind ring attention into the model config.

Long sequences are sharded over the mesh's ``seq`` axis; attention runs
as a ring (ops/ring_attention.py) while every other op stays local and
XLA partitions it from the shard_map boundary's in/out specs. The rest
of the stack — sharding rules, optimizer, train step — is unchanged:
context parallelism composes with tensor and data parallelism by
construction.

Serving gets the same long-context story through ``cp_generate``: the
PREFILL — the quadratic, activation-heavy part of a long-prompt
request — runs ring attention over the seq axis, then the KV cache
gathers off the ring once and the decode scan runs on the existing
(unsharded) path with the full sampling contract.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig, flash_eligible
from ..ops.ring_attention import ring_attention, shard_map


def flash_parallel_config(
    cfg: TransformerConfig, mesh: Mesh
) -> TransformerConfig:
    """Bind mesh-aware attention auto-selection for pjit'd training.

    pallas calls don't partition under automatic pjit sharding, so the
    flash path must run under shard_map. Causal attention is
    independent per (batch, head), and the tensor-parallel rules shard
    heads over ``model`` and batch over ``data``
    (parallel/sharding.py) — so the manual region needs no collectives
    at all: each device runs the flash kernel on its local
    [b/data, s, h/model, hd] block. Below the flash threshold the
    plain einsum path is returned and XLA partitions it as before.
    """
    spec = P("data", None, "model", None)

    def attn(q, k, v):
        if not flash_eligible(cfg, q.shape[1]):
            from ..ops.attention import causal_attention

            return causal_attention(q, k, v, window=cfg.window)
        from ..ops import tuning
        from ..ops.flash import flash_attention

        # seq is unsharded here (spec leaves axis 1 unpartitioned), so
        # the tuned 'train' blocks for the global seq apply locally too
        bq, bk = tuning.pick_blocks("train", q.shape[1])
        f = shard_map(
            lambda q, k, v: flash_attention(
                q, k, v, block_q=bq, block_k=bk, window=cfg.window
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return f(q, k, v)

    return dataclasses.replace(cfg, attention_fn=attn)


def context_parallel_config(
    cfg: TransformerConfig, mesh: Mesh, axis_name: str = "seq"
) -> TransformerConfig:
    """A config whose attention runs as a ring over ``axis_name``."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names}"
        )
    if cfg.window > 0:
        raise ValueError(
            "sliding-window attention does not compose with ring "
            "attention yet: a window shorter than the shard makes "
            "most ring hops no-ops — use the flash window path on a "
            "(data, model) mesh instead"
        )

    def attn(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name)

    # the ring handles grouped kv itself (rotates the SMALL K/V over
    # ICI); the layer passes unrepeated heads through
    attn.gqa_native = True
    return dataclasses.replace(cfg, attention_fn=attn)


@functools.lru_cache(maxsize=8)
def _cp_prefill_fn(cfg: TransformerConfig, mesh: Mesh, max_len: int,
                   axis_name: str):
    """One compiled context-parallel prefill per (config, mesh,
    max_len): ring attention over the seq axis while every other op
    stays seq-local under XLA's partitioner, then ONE gather point —
    the decode scan reads the whole cache every step, so the cache
    leaves the ring replicated here rather than re-gathering per
    step. Cached at this level because context_parallel_config builds
    a fresh attention closure per call (a fresh closure would defeat
    jit's own cache)."""
    cfg_cp = context_parallel_config(cfg, mesh, axis_name)
    from ..models.decode import prefill

    replicated = NamedSharding(mesh, P())

    def fn(params, prompt):
        logits, cache = prefill(params, prompt, cfg_cp, max_len)
        cache = jax.tree.map(
            lambda x: lax.with_sharding_constraint(x, replicated),
            cache,
        )
        return lax.with_sharding_constraint(logits, replicated), cache

    return jax.jit(fn)


def cp_generate(
    params,
    prompt: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    max_new_tokens: int,
    max_len: int,
    axis_name: str = "seq",
    **sampling,
):
    """Long-prompt generation with context-parallel prefill: the
    prompt shards over ``axis_name`` (each device holds seq/P tokens;
    the quadratic attention runs as a ring, activations stay
    seq-local), the cache gathers once, and the decode runs
    ``generate_from_cache`` with the full sampling contract
    (temperature/top_k/top_p/eos/min_new/penalties/logit_bias).

    Ring attention needs the sharded length to divide by the seq
    axis, so the largest axis-divisible HEAD of the prompt rings
    through prefill and any remainder (< axis tokens) extends the
    gathered cache with one short decode_chunk — arbitrary prompt
    lengths, exact semantics, at most axis-1 tiny extend programs.
    Numerics: ring attention's online softmax is the same math as
    single-device attention up to float reassociation — greedy output
    matches the unsharded path away from argmax ties.
    """
    plen = int(prompt.shape[1])
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis: {mesh.axis_names} "
            "(build it with MeshPlan(seq=...))"
        )
    axis = mesh.shape[axis_name]
    head = plen - plen % axis
    if head == 0:
        raise ValueError(
            f"prompt len {plen} is shorter than the {axis_name} axis "
            f"({axis}): nothing to shard — use the plain path"
        )
    if plen + max_new_tokens > max_len:
        raise ValueError(
            f"prompt_len {plen} + max_new_tokens {max_new_tokens} "
            f"exceeds max_len {max_len}"
        )
    from ..models.decode import _jitted_extend, generate_from_cache

    sharded_head = jax.device_put(
        prompt[:, :head], NamedSharding(mesh, P(None, axis_name))
    )
    logits, cache = _cp_prefill_fn(cfg, mesh, max_len, axis_name)(
        params, sharded_head
    )
    if head < plen:
        logits, cache = _jitted_extend(cfg)(
            params, cache, prompt[:, head:]
        )
    return generate_from_cache(
        params, cache, logits, cfg, max_new_tokens, pos=plen,
        **sampling,
    )
