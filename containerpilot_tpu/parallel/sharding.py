"""Sharding rules for the flagship transformer.

Megatron-style tensor parallelism expressed as PartitionSpecs (XLA
inserts the collectives):

- attention: heads sharded over ``model`` — q/k/v projections split
  column-wise by head, the output projection row-wise, so one
  all-reduce per attention block rides ICI;
- SwiGLU: gate/up sharded column-wise on the hidden axis, down
  row-wise — one all-reduce per MLP block;
- embed/unembed: vocab sharded over ``model``;
- activations: batch over ``data`` (gradient psum over ``data`` is the
  data-parallel all-reduce).

These are *rules over the param pytree*, so new models get sharding by
writing specs, not by rewriting layers.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_sharding_rules(
    cfg: Optional[Any] = None, mesh: Optional[Mesh] = None
) -> Dict[str, Any]:
    """PartitionSpec pytree matching models.transformer.init_params.

    With an MoE config (cfg.moe_experts > 0) the feed-forward specs are
    expert-parallel: the expert axis shards over ``model`` and XLA
    inserts all-to-alls at the dispatch/combine einsums.

    Under GQA, wk/wv's kv-head axis may be smaller than the model axis;
    when ``mesh`` is provided and kv_heads doesn't divide by it, those
    two (small) tensors replicate instead of crashing placement.
    """
    kv_spec = P(None, None, "model", None)
    if cfg is not None and mesh is not None:
        kv_heads = getattr(cfg, "kv_heads", None)
        model_size = mesh.shape.get("model", 1)
        if kv_heads is not None and kv_heads % model_size:
            kv_spec = P(None, None, None, None)
    layers: Dict[str, Any] = {
        # [L, d, heads, head_dim]: shard heads over model axis
        "wq": P(None, None, "model", None),
        "wk": kv_spec,
        "wv": kv_spec,
        # [L, heads, head_dim, d]: row-parallel output projection
        "wo": P(None, "model", None, None),
        "norm_attn": P(None, None),  # replicated
        "norm_mlp": P(None, None),
    }
    if cfg is not None and getattr(cfg, "moe_experts", 0) > 0:
        layers.update(
            {
                "router": P(None, None, None),  # replicated router
                # [L, E, d, ff] / [L, E, ff, d]: experts over model axis
                "moe_w_in": P(None, "model", None, None),
                "moe_w_out": P(None, "model", None, None),
            }
        )
    else:
        layers.update(
            {
                # [L, d, ff]: column-parallel
                "w_gate": P(None, None, "model"),
                "w_up": P(None, None, "model"),
                # [L, ff, d]: row-parallel
                "w_down": P(None, "model", None),
            }
        )
    return {
        "embed": P("model", None),  # vocab sharded
        "layers": layers,
        "norm_out": P(None),
        "unembed": P(None, "model"),
    }


def batch_spec() -> P:
    """Activations/tokens: batch over the data axis."""
    return P("data", None)


def shard_params(
    params: Any, mesh: Mesh, cfg: Optional[Any] = None, rules: Any = None
) -> Any:
    """Place a param pytree onto the mesh per the rules."""
    if rules is None:
        rules = param_sharding_rules(cfg, mesh)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        rules,
    )
