"""Sharding rules for the flagship transformer.

Megatron-style tensor parallelism expressed as PartitionSpecs (XLA
inserts the collectives):

- attention: heads sharded over ``model`` — q/k/v projections split
  column-wise by head, the output projection row-wise, so one
  all-reduce per attention block rides ICI;
- SwiGLU: gate/up sharded column-wise on the hidden axis, down
  row-wise — one all-reduce per MLP block;
- embed/unembed: vocab sharded over ``model``;
- activations: batch over ``data`` (gradient psum over ``data`` is the
  data-parallel all-reduce).

These are *rules over the param pytree*, so new models get sharding by
writing specs, not by rewriting layers.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_sharding_rules(
    cfg: Optional[Any] = None, mesh: Optional[Mesh] = None
) -> Dict[str, Any]:
    """PartitionSpec pytree matching models.transformer.init_params.

    With an MoE config (cfg.moe_experts > 0) the feed-forward specs are
    expert-parallel: the expert axis shards over ``model`` and XLA
    inserts all-to-alls at the dispatch/combine einsums.

    Under GQA, wk/wv's kv-head axis may be smaller than the model axis;
    when ``mesh`` is provided and kv_heads doesn't divide by it, those
    two (small) tensors replicate instead of crashing placement.
    """
    kv_spec = P(None, None, "model", None)
    if cfg is not None and mesh is not None:
        kv_heads = getattr(cfg, "kv_heads", None)
        model_size = mesh.shape.get("model", 1)
        if kv_heads is not None and kv_heads % model_size:
            kv_spec = P(None, None, None, None)
    layers: Dict[str, Any] = {
        # [L, d, heads, head_dim]: shard heads over model axis
        "wq": P(None, None, "model", None),
        "wk": kv_spec,
        "wv": kv_spec,
        # [L, heads, head_dim, d]: row-parallel output projection
        "wo": P(None, "model", None, None),
        "norm_attn": P(None, None),  # replicated
        "norm_mlp": P(None, None),
    }
    if cfg is not None and getattr(cfg, "moe_experts", 0) > 0:
        layers.update(
            {
                "router": P(None, None, None),  # replicated router
                # [L, E, d, ff] / [L, E, ff, d]: experts over model axis
                "moe_w_in": P(None, "model", None, None),
                "moe_w_out": P(None, "model", None, None),
            }
        )
    else:
        layers.update(
            {
                # [L, d, ff]: column-parallel
                "w_gate": P(None, None, "model"),
                "w_up": P(None, None, "model"),
                # [L, ff, d]: row-parallel
                "w_down": P(None, "model", None),
            }
        )
    return {
        "embed": P("model", None),  # vocab sharded
        "layers": layers,
        "norm_out": P(None),
        "unembed": P(None, "model"),
    }


def fsdp_sharding_rules(
    cfg: Any, mesh: Mesh, rules: Any = None
) -> Dict[str, Any]:
    """FSDP (ZeRO-3 analogue): the tensor-parallel rules with every
    large parameter *additionally* sharded over the ``data`` axis.

    On TPU this is purely a placement decision — under ``pjit`` XLA
    inserts the per-use all-gathers (and turns the grad all-reduce
    into reduce-scatter) so parameters, gradients, and optimizer
    moments all live 1/dp-sized per device, exactly the scaling-book
    "fully sharded" recipe. Reference analog: none (the reference is a
    supervisor); this is the workload half's answer to torch FSDP.

    Per leaf, ``data`` goes on the largest dimension that is not
    already mesh-sharded and divides by the data-axis size. The
    stacked-layer (scan) axis is never sharded: slicing a scan operand
    across devices would force a layer-N gather on every iteration of
    the compiled loop *and* break donation aliasing; sharding the
    feature dims instead gives XLA one clean all-gather per use site.
    """
    from ..models.transformer import init_params

    if rules is None:
        rules = param_sharding_rules(cfg, mesh)
    data_size = mesh.shape.get("data", 1)
    if data_size <= 1:
        return rules
    shapes = jax.eval_shape(
        lambda r: init_params(r, cfg), jax.random.PRNGKey(0)
    )

    def add_data(path, spec: P, leaf) -> P:
        shape = leaf.shape
        entries = list(spec) + [None] * (len(shape) - len(spec))
        if "data" in entries:
            return spec  # already data-sharded (idempotent re-apply)
        # skip the scan-stacked layer axis (dim 0 of "layers" leaves)
        start = 1 if any(
            getattr(k, "key", None) == "layers" for k in path
        ) else 0
        best = None
        for i in range(start, len(shape)):
            if entries[i] is None and shape[i] % data_size == 0:
                if best is None or shape[i] > shape[best]:
                    best = i
        if best is None:
            return spec
        entries[best] = "data"
        return P(*entries)

    return jax.tree_util.tree_map_with_path(
        add_data, rules, shapes,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec() -> P:
    """Activations/tokens: batch over the data axis."""
    return P("data", None)


def shard_params(
    params: Any, mesh: Mesh, cfg: Optional[Any] = None, rules: Any = None
) -> Any:
    """Place a param pytree onto the mesh per the rules."""
    if rules is None:
        rules = param_sharding_rules(cfg, mesh)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        rules,
    )
