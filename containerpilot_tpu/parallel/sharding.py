"""Sharding rules for the flagship transformer.

Megatron-style tensor parallelism expressed as PartitionSpecs (XLA
inserts the collectives):

- attention: heads sharded over ``model`` — q/k/v projections split
  column-wise by head, the output projection row-wise, so one
  all-reduce per attention block rides ICI;
- SwiGLU: gate/up sharded column-wise on the hidden axis, down
  row-wise — one all-reduce per MLP block;
- embed/unembed: vocab sharded over ``model``;
- activations: batch over ``data`` (gradient psum over ``data`` is the
  data-parallel all-reduce).

These are *rules over the param pytree*, so new models get sharding by
writing specs, not by rewriting layers.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_sharding_rules() -> Dict[str, Any]:
    """PartitionSpec pytree matching models.transformer.init_params."""
    return {
        "embed": P("model", None),  # vocab sharded
        "layers": {
            # [L, d, heads, head_dim]: shard heads over model axis
            "wq": P(None, None, "model", None),
            "wk": P(None, None, "model", None),
            "wv": P(None, None, "model", None),
            # [L, heads, head_dim, d]: row-parallel output projection
            "wo": P(None, "model", None, None),
            # [L, d, ff]: column-parallel
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            # [L, ff, d]: row-parallel
            "w_down": P(None, "model", None),
            "norm_attn": P(None, None),  # replicated
            "norm_mlp": P(None, None),
        },
        "norm_out": P(None),
        "unembed": P(None, "model"),
    }


def batch_spec() -> P:
    """Activations/tokens: batch over the data axis."""
    return P("data", None)


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a param pytree onto the mesh per the rules."""
    rules = param_sharding_rules()
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        rules,
    )
