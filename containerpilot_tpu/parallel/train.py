"""The distributed training step: pjit over the (data, model) mesh.

One jitted function does forward, backward, and the optimizer update;
XLA inserts the gradient all-reduce over ``data`` and the tensor-
parallel collectives over ``model``. Buffers are donated so the update
is in-place in HBM.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.transformer import TransformerConfig, init_params, loss_fn
from .sharding import batch_spec, shard_params


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(
    learning_rate: float = 3e-4,
    *,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    min_lr_ratio: float = 0.1,
    clip_norm: float = 1.0,
) -> optax.GradientTransformation:
    """Global-norm-clipped AdamW, optionally under a linear-warmup +
    cosine-decay schedule (the standard LLM pretraining shape).

    - ``warmup_steps > 0``: lr ramps 0 -> learning_rate linearly;
    - ``decay_steps > 0``: cosine decay from the peak down to
      ``learning_rate * min_lr_ratio`` over that many post-warmup
      steps, then holds the floor;
    - both zero (the default): constant lr, state layout unchanged.
    """
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(
            lr_schedule(learning_rate, warmup_steps, decay_steps,
                        min_lr_ratio),
            b1=0.9, b2=0.95, weight_decay=0.1,
        ),
    )


class EmaState(NamedTuple):
    """Shadow (exponential-moving-average) copy of the params."""

    ema: Any


def with_ema(
    inner: optax.GradientTransformation, decay: float
) -> optax.GradientTransformation:
    """Wrap an optimizer so its state also carries an EMA of the
    *updated* params (``ema = decay*ema + (1-decay)*params_next``).

    Living inside ``opt_state`` keeps the TrainState pytree structure
    unchanged — checkpoints, sharding resolution (the ema subtree
    mirrors the param tree, so param rules resolve), and the donated
    train step all work untouched. Extract with ``ema_params(state)``.
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"ema decay must be in (0, 1), got {decay}")

    def init(params):
        return (
            inner.init(params),
            EmaState(jax.tree_util.tree_map(jnp.array, params)),
        )

    def update(grads, state, params=None):
        inner_state, ema_state = state
        updates, inner_state = inner.update(grads, inner_state, params)
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree_util.tree_map(
            lambda e, p: decay * e + (1.0 - decay) * p,
            ema_state.ema, new_params,
        )
        return updates, (inner_state, EmaState(ema))

    return optax.GradientTransformation(init, update)


def ema_params(state: "TrainState") -> Any:
    """The EMA shadow params from a with_ema-wrapped state (None if
    the optimizer has no EMA)."""
    found = []

    def visit(node):
        if isinstance(node, EmaState):
            found.append(node.ema)
            return
        if isinstance(node, (tuple, list)):
            for child in node:
                visit(child)

    visit(state.opt_state)
    return found[0] if found else None


def lr_schedule(
    learning_rate: float,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    min_lr_ratio: float = 0.1,
):
    """The lr trajectory make_optimizer uses: a float when constant,
    else an optax schedule (step -> lr)."""
    if decay_steps > 0:
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps > 0 else learning_rate,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=warmup_steps + decay_steps,
            end_value=learning_rate * min_lr_ratio,
        )
    if warmup_steps > 0:
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, learning_rate, warmup_steps),
                optax.constant_schedule(learning_rate),
            ],
            boundaries=[warmup_steps],
        )
    return learning_rate


def init_train_state(
    rng: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    rules: Any = None,
    optimizer: optax.GradientTransformation = None,
    zero1: bool = False,
) -> TrainState:
    """Initialize params already sharded onto the mesh. ``rules``
    overrides the tensor-parallel param specs (e.g. pipeline rules);
    ``optimizer`` overrides the default make_optimizer(learning_rate)
    (pass the same one to make_train_step and abstract_train_state);
    ``zero1`` shards adam moments over the data axis (see
    train_state_shardings)."""
    params = shard_params(init_params(rng, cfg), mesh, cfg, rules=rules)
    optimizer = optimizer or make_optimizer(learning_rate)
    opt_state = optimizer.init(params)
    # commit every piece of optimizer state to its canonical sharding
    # (moments normally inherit the param placement — a no-op put —
    # but zero1 re-shards them over data; scalars commit replicated so
    # checkpoint-restored states match exactly)
    shardings = train_state_shardings(
        cfg, mesh, learning_rate, rules=rules, optimizer=optimizer,
        zero1=zero1,
    )
    opt_state = jax.tree.map(
        jax.device_put, opt_state, shardings.opt_state
    )
    return TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
    )


def _abstract_init(
    rng: jax.Array, cfg: TransformerConfig, learning_rate: float,
    optimizer: optax.GradientTransformation = None,
) -> TrainState:
    def init_fn(rng):
        params = init_params(rng, cfg)
        opt_state = (optimizer or make_optimizer(learning_rate)).init(params)
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    return jax.eval_shape(init_fn, rng)


def train_state_shardings(
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    abstract: "TrainState" = None,
    rules: Any = None,
    optimizer: optax.GradientTransformation = None,
    zero1: bool = False,
) -> TrainState:
    """A TrainState-shaped pytree of NamedShardings: the canonical
    placement of every piece of training state on the mesh.

    Built by walking each leaf's tree path against
    param_sharding_rules — adam's mu/nu subtrees mirror the param tree,
    so the same rules resolve; scalar leaves replicate. Used both as
    the train step's pinned in/out shardings (so state placement can
    never drift across steps) and as the checkpoint-restore target.

    ``zero1`` additionally shards adam's mu/nu over the ``data`` axis
    (ZeRO stage 1): optimizer moments — 2x the params in f32 — stop
    being replicated across data-parallel replicas, dividing their
    memory by the data-axis size. Params stay replicated over data;
    XLA partitions the elementwise optimizer math over ``data`` and
    all-gathers the updates (reduce-scatter/all-gather in place of the
    plain grad all-reduce). Moment tensors whose dims don't divide stay
    on the param sharding.
    """
    from .sharding import param_sharding_rules

    if abstract is None:
        abstract = _abstract_init(
            jax.random.PRNGKey(0), cfg, learning_rate, optimizer
        )
    if rules is None:
        rules = param_sharding_rules(cfg, mesh)
    replicated = NamedSharding(mesh, P())
    data_size = mesh.shape.get("data", 1)

    def with_data_axis(spec: P, shape) -> P:
        """Put ``data`` on the first unsharded dim that divides."""
        entries = list(spec) + [None] * (len(shape) - len(spec))
        if "data" in entries:
            return spec  # fsdp rules already consumed the data axis
        for i, (entry, dim) in enumerate(zip(entries, shape)):
            if entry is None and dim % data_size == 0 and dim > 0:
                entries[i] = "data"
                return P(*entries)
        return spec  # nothing divides: keep the param sharding

    def resolve(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return replicated
        cursor: Any = rules
        in_moments = False
        for key in path:
            name = getattr(key, "key", getattr(key, "name", None))
            if not isinstance(name, str):
                continue  # tuple/namedtuple positions carry no rule info
            if name in ("mu", "nu"):
                in_moments = True
            # descend first; re-anchor at the top only on a miss (mu/nu
            # subtrees mirror the param tree), so a nested param that
            # happens to share a top-level name can't mis-resolve
            if isinstance(cursor, dict) and name in cursor:
                cursor = cursor[name]
            elif name in rules:
                cursor = rules[name]
        if not isinstance(cursor, P):
            # fail as loudly as shard_params' tree_map does on a
            # rules/params mismatch — a silently replicated tensor is a
            # multi-GB placement bug at real scale
            raise ValueError(
                f"no sharding rule resolves for state leaf at path "
                f"{jax.tree_util.keystr(path)} (shape {leaf.shape})"
            )
        if zero1 and in_moments and data_size > 1:
            cursor = with_data_axis(cursor, leaf.shape)
        return NamedSharding(mesh, cursor)

    return jax.tree_util.tree_map_with_path(resolve, abstract)


def abstract_train_state(
    rng: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    shardings: "TrainState" = None,
    rules: Any = None,
    optimizer: optax.GradientTransformation = None,
    zero1: bool = False,
) -> TrainState:
    """The shape/dtype/sharding skeleton of init_train_state's result,
    without materializing any arrays — the restore target for resuming
    from a checkpoint (checkpoint.restore_checkpoint accepts it), so
    resume never pays init + double residency. Pass ``shardings`` (from
    train_state_shardings) to avoid re-deriving them."""
    abstract = _abstract_init(rng, cfg, learning_rate, optimizer)
    if shardings is None:
        shardings = train_state_shardings(
            cfg, mesh, learning_rate, abstract, rules=rules, zero1=zero1
        )
    return jax.tree_util.tree_map(
        lambda leaf, s: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=s
        ),
        abstract,
        shardings,
    )


def make_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    optimizer: optax.GradientTransformation = None,
    accum_steps: int = 1,
    zero1: bool = False,
    fsdp: bool = False,
    rules: Any = None,
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, jax.Array]]:
    """Build the jitted, donated, sharded train step.

    ``zero1`` pins adam's moments sharded over the data axis (ZeRO
    stage 1) — optimizer memory per device drops by the data-parallel
    factor; XLA swaps the grad all-reduce for reduce-scatter +
    all-gather around the partitioned optimizer math.

    ``fsdp`` shards params/grads/moments themselves over ``data``
    (ZeRO-3; sharding.fsdp_sharding_rules) — per-device model state
    drops by the dp factor and XLA all-gathers weights at each use.
    ``rules`` overrides the param specs outright (rare; fsdp wins if
    both are given).

    ``accum_steps > 1`` runs gradient accumulation: the batch splits
    into that many sequential chunks inside one compiled step
    (``lax.scan``), grads average across chunks, one optimizer update —
    the effective batch stays the full batch while activation memory
    drops to one chunk's worth. Batch size must divide by it.
    """
    if cfg.attention_fn is None and mesh.size > 1 and "seq" not in mesh.axis_names:
        # multi-device without context parallelism: the flash path (if
        # the seq length triggers it) must run under shard_map — pallas
        # calls don't partition under automatic pjit sharding
        from .context import flash_parallel_config

        cfg = flash_parallel_config(cfg, mesh)
    if accum_steps < 1:
        raise ValueError("accum_steps must be >= 1")
    optimizer = optimizer or make_optimizer(learning_rate)
    data_sharding = NamedSharding(mesh, batch_spec())
    if fsdp:
        from .sharding import fsdp_sharding_rules

        rules = fsdp_sharding_rules(cfg, mesh, rules)
    # pin the state's placement on both sides of the step so shardings
    # can never drift from the rules across steps/restores
    state_shardings = train_state_shardings(
        cfg, mesh, learning_rate, optimizer=optimizer, zero1=zero1,
        rules=rules,
    )

    def grads_of(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, tokens, cfg)
        chunks = tokens.reshape(
            accum_steps, tokens.shape[0] // accum_steps, tokens.shape[1]
        )

        def acc(carry, chunk):
            loss_sum, grad_sum = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, chunk, cfg)
            return (
                loss_sum + loss,
                jax.tree_util.tree_map(jnp.add, grad_sum, grads),
            ), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (loss_sum, grad_sum), _ = jax.lax.scan(
            acc, (jnp.zeros((), jnp.float32), zeros), chunks
        )
        # equal-sized chunks: mean-of-chunk-means == full-batch mean
        return (
            loss_sum / accum_steps,
            jax.tree_util.tree_map(lambda g: g / accum_steps, grad_sum),
        )

    def step_fn(state: TrainState, tokens: jax.Array):
        loss, grads = grads_of(state.params, tokens)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_params,
                opt_state=new_opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    jitted = jax.jit(
        step_fn,
        in_shardings=(state_shardings, data_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )

    def run(state: TrainState, tokens: jax.Array):
        if tokens.shape[0] % accum_steps:
            raise ValueError(
                f"batch {tokens.shape[0]} not divisible by "
                f"accum_steps {accum_steps}"
            )
        with mesh:
            return jitted(state, tokens)

    # register TrainState as a pytree once, lazily
    return run


def lora_abstract_state(
    cfg: TransformerConfig,
    rank: int,
    mesh: Mesh,
    learning_rate: float = 1e-4,
    optimizer: optax.GradientTransformation = None,
) -> TrainState:
    """Checkpoint-restore skeleton for a LoRA TrainState: adapter
    pairs + optimizer state, every leaf replicated on ``mesh``. Used
    by the trainer (resume) and by serve (params-only adapter
    restore) — both must build it over the SAME mesh the base weights
    live on, or the merge add commits to conflicting device sets."""
    from ..models.lora import init_lora_params

    optimizer = optimizer or make_optimizer(learning_rate)

    def fresh(rng):
        lora = init_lora_params(rng, cfg, rank)
        return TrainState(
            params=lora,
            opt_state=optimizer.init(lora),
            step=jnp.zeros((), jnp.int32),
        )

    replicated = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=replicated
        ),
        jax.eval_shape(fresh, jax.random.PRNGKey(0)),
    )


def make_lora_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    rank: int,
    learning_rate: float = 1e-4,
    optimizer: optax.GradientTransformation = None,
    alpha: float = 2.0,
):
    """LoRA fine-tuning step: returns ``(init_fn, step_fn, abstract)``.

    The TrainState's params are the (tiny, replicated) LoRA pairs;
    the sharded base params ride along as a frozen operand —
    ``step_fn(state, base_params, tokens)``. Gradients are taken only
    w.r.t. the LoRA pytree (the base is frozen by construction), so
    optimizer state is ~2*d*rank per target per layer instead of a
    full model copy. ``abstract`` is the checkpoint-restore target for
    resuming (same contract as abstract_train_state).
    """
    from ..models.lora import apply_lora, init_lora_params

    if cfg.attention_fn is None and mesh.size > 1 and "seq" not in mesh.axis_names:
        from .context import flash_parallel_config

        cfg = flash_parallel_config(cfg, mesh)
    optimizer = optimizer or make_optimizer(learning_rate)
    data_sharding = NamedSharding(mesh, batch_spec())
    abstract = lora_abstract_state(
        cfg, rank, mesh, learning_rate, optimizer
    )
    state_shardings = jax.tree_util.tree_map(
        lambda leaf: leaf.sharding, abstract
    )

    def init_fn(rng) -> TrainState:
        lora = init_lora_params(rng, cfg, rank)
        state = TrainState(
            params=lora,
            opt_state=optimizer.init(lora),
            step=jnp.zeros((), jnp.int32),
        )
        return jax.tree_util.tree_map(
            jax.device_put, state, state_shardings
        )

    def loss_of(lora, base, tokens):
        return loss_fn(apply_lora(base, lora, cfg, alpha), tokens, cfg)

    def step_fn(state: TrainState, base: Any, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_of)(
            state.params, base, tokens
        )
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_lora = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_lora,
                opt_state=new_opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    jitted = jax.jit(
        step_fn,
        in_shardings=(state_shardings, None, data_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )

    def run(state: TrainState, base: Any, tokens: jax.Array):
        with mesh:
            return jitted(state, base, tokens)

    return init_fn, run, abstract


def make_pipeline_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
    n_microbatches: int = 4,
    optimizer: optax.GradientTransformation = None,
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, jax.Array]]:
    """The pipelined (GPipe) train step over a ("data","pipe"[,"model"])
    mesh: layers shard over pipe stages, microbatches stream with
    ppermute handoffs, tensor parallelism stays live inside each stage
    (pipeline.py). Same TrainState/optimizer contract as
    make_train_step, so checkpointing and the supervised trainer reuse
    everything."""
    from .pipeline import pipeline_loss_fn, pipeline_sharding_rules

    if "pipe" not in mesh.axis_names:
        raise ValueError(f"mesh has no 'pipe' axis: {mesh.axis_names}")
    optimizer = optimizer or make_optimizer(learning_rate)
    data_sharding = NamedSharding(
        mesh, P("data") if "data" in mesh.axis_names else P()
    )
    rules = pipeline_sharding_rules(cfg, mesh)
    state_shardings = train_state_shardings(
        cfg, mesh, learning_rate, rules=rules, optimizer=optimizer
    )

    def step_fn(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            state.params, tokens, cfg, mesh, n_microbatches
        )
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_params,
                opt_state=new_opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    jitted = jax.jit(
        step_fn,
        in_shardings=(state_shardings, data_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )

    def run(state: TrainState, tokens: jax.Array):
        with mesh:
            return jitted(state, tokens)

    return run


def _trainstate_flatten(s: TrainState):
    return (s.params, s.opt_state, s.step), None


def _trainstate_unflatten(_aux, children):
    return TrainState(*children)


jax.tree_util.register_pytree_node(
    TrainState, _trainstate_flatten, _trainstate_unflatten
)
