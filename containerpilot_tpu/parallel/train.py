"""The distributed training step: pjit over the (data, model) mesh.

One jitted function does forward, backward, and the optimizer update;
XLA inserts the gradient all-reduce over ``data`` and the tensor-
parallel collectives over ``model``. Buffers are donated so the update
is in-place in HBM.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.transformer import TransformerConfig, init_params, loss_fn
from .sharding import batch_spec, shard_params


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def make_optimizer(learning_rate: float = 3e-4) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=0.1),
    )


def init_train_state(
    rng: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    learning_rate: float = 3e-4,
) -> TrainState:
    """Initialize params already sharded onto the mesh."""
    params = shard_params(init_params(rng, cfg), mesh, cfg)
    optimizer = make_optimizer(learning_rate)
    opt_state = optimizer.init(params)
    # moment tensors inherit the param shardings; scalar leaves (adam
    # count etc.) land on the default device — commit them replicated so
    # checkpoint-restored states (which ARE committed) match exactly
    replicated = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, replicated)
        if getattr(x, "ndim", None) == 0
        else x,
        opt_state,
    )
    return TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.device_put(jnp.zeros((), jnp.int32), replicated),
    )


def make_train_step(
    cfg: TransformerConfig, mesh: Mesh, learning_rate: float = 3e-4
) -> Callable[[TrainState, jax.Array], Tuple[TrainState, jax.Array]]:
    """Build the jitted, donated, sharded train step."""
    optimizer = make_optimizer(learning_rate)
    data_sharding = NamedSharding(mesh, batch_spec())

    def step_fn(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, cfg)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                params=new_params,
                opt_state=new_opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    jitted = jax.jit(
        step_fn,
        in_shardings=(None, data_sharding),
        donate_argnums=(0,),
    )

    def run(state: TrainState, tokens: jax.Array):
        with mesh:
            return jitted(state, tokens)

    # register TrainState as a pytree once, lazily
    return run


def _trainstate_flatten(s: TrainState):
    return (s.params, s.opt_state, s.step), None


def _trainstate_unflatten(_aux, children):
    return TrainState(*children)


jax.tree_util.register_pytree_node(
    TrainState, _trainstate_flatten, _trainstate_unflatten
)
