"""Core runtime: the App generation loop, signals, CLI flags
(reference: core/ package)."""
from .app import App
from .flags import get_args

__all__ = ["App", "get_args"]
