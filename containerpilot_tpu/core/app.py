"""The App: one supervisor process, many config generations.

Capability parity with the reference's core runtime
(reference: core/app.go). The generation loop:

1. build everything from config (jobs, watches, telemetry, control);
2. per generation: fresh bus, bind the control socket, subscribe every
   job *before* running any (race rule, reference: core/app.go:201-207),
   start watches/metrics/telemetry, publish GLOBAL_STARTUP;
3. a completion watcher cancels the generation once every job reports
   complete — the supervisor is NOT a server and must exit when its jobs
   are done (reference: core/app.go:100-140);
4. ``await bus.wait()`` → reload=True: rebuild from the same config path
   and loop (reference: core/app.go:183-196); reload=False: give
   stragglers ``stopTimeout`` of grace then group-SIGKILL and exit
   (reference: core/app.go:147-156).

Signals (reference: core/signals.go): SIGTERM/SIGINT terminate;
SIGHUP/SIGUSR2 are *events* jobs can start on (v3 semantics — SIGHUP
does not reload); SIGUSR1 reopens the log file for rotation.
"""
from __future__ import annotations

import asyncio
import logging
import os
import signal
from typing import List, Optional

from ..commands import env_name
from ..config.loader import AppConfig, load_config
from ..config.logger import reopen_log_file
from ..control import ControlServer
from ..events import Event, EventBus, EventCode, GLOBAL_STARTUP
from ..jobs import Job, from_configs as jobs_from_configs
from ..telemetry import Metric, Telemetry
from ..utils.tasks import spawn
from ..watches import Watch, from_configs as watches_from_configs

log = logging.getLogger("containerpilot.core")


class App:
    def __init__(self, cfg: AppConfig) -> None:
        self.cfg = cfg
        self.config_path = cfg.config_path
        self.stop_timeout = cfg.stop_timeout
        self.jobs: List[Job] = jobs_from_configs(cfg.jobs)
        self.watches: List[Watch] = watches_from_configs(cfg.watches)
        self.control_server = ControlServer(cfg.control)
        self.telemetry: Optional[Telemetry] = (
            Telemetry(cfg.telemetry) if cfg.telemetry is not None else None
        )
        self.bus: Optional[EventBus] = None
        self._export_job_ips()

    @classmethod
    def from_config_path(cls, path: str) -> "App":
        """Load + validate config and build the app
        (reference: core/app.go:45-98)."""
        cfg = load_config(path)
        cfg.init_logging()
        return cls(cfg)

    def _export_job_ips(self) -> None:
        """Export CONTAINERPILOT_<JOB>_IP for advertised jobs
        (reference: core/app.go:81-97)."""
        for job in self.jobs:
            if job.service is not None:
                os.environ[f"CONTAINERPILOT_{env_name(job.name)}_IP"] = (
                    job.service.registration.address
                )

    # -- signals (reference: core/signals.go) ---------------------------

    def handle_signals(self) -> None:
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, self.terminate)
        for sig, name in ((signal.SIGHUP, "SIGHUP"), (signal.SIGUSR2, "SIGUSR2")):
            loop.add_signal_handler(sig, self.signal_event, name)
        loop.add_signal_handler(signal.SIGUSR1, reopen_log_file)

    def _remove_signal_handlers(self) -> None:
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP,
                    signal.SIGUSR2, signal.SIGUSR1):
            try:
                loop.remove_signal_handler(sig)
            except (ValueError, RuntimeError):  # pragma: no cover
                pass

    def terminate(self) -> None:
        """SIGTERM/SIGINT: shut the generation down
        (reference: core/app.go:166-171)."""
        if self.bus is not None:
            self.bus.shutdown()

    def signal_event(self, name: str) -> None:
        """SIGHUP/SIGUSR2 become job-triggerable events
        (reference: core/app.go:173-178)."""
        if self.bus is not None:
            self.bus.publish(Event(EventCode.SIGNAL, name))

    def reload(self) -> None:
        """Programmatic reload (what POST /v3/reload does)."""
        if self.bus is not None:
            self.bus.set_reload_flag()
            self.bus.shutdown()

    # -- the generation loop --------------------------------------------

    async def run(self) -> None:
        """Run generations until shutdown (reference: core/app.go:100-163)."""
        self.handle_signals()
        try:
            while True:
                reload = await self._run_generation()
                if not reload:
                    if self.stop_timeout > 0:
                        log.debug(
                            "killing all processes in %s seconds",
                            self.stop_timeout,
                        )
                        await asyncio.sleep(self.stop_timeout)
                    for job in self.jobs:
                        log.info("killing processes for job %r", job.name)
                        job.kill()
                    # give the SIGKILL waiters a beat to reap
                    await asyncio.sleep(0.05)
                    break
                if not self._reload_app():
                    break
        finally:
            self._remove_signal_handlers()

    async def _run_generation(self) -> bool:
        bus = EventBus()
        self.bus = bus
        stop_task: Optional["asyncio.Task[None]"] = None

        def on_job_complete(_job: Job) -> None:
            # escape hatch: all jobs complete -> tear the generation
            # down even without a shutdown event
            # (reference: core/app.go:110-140)
            nonlocal stop_task
            if stop_task is not None:
                return
            if all(j.is_complete for j in self.jobs):
                stop_task = spawn(
                    self._stop_generation(), name="stop-generation"
                )

        await self.control_server.run(bus)

        # subscribe-before-run so no job misses another's early events
        # (reference: core/app.go:201-207)
        for job in self.jobs:
            job.subscribe(bus)
            job.register(bus)
        job_tasks = [job.run(on_complete=on_job_complete) for job in self.jobs]
        for watch in self.watches:
            watch.run(bus)
        if self.telemetry is not None:
            for metric in self.telemetry.metrics:
                metric.run(bus)
            self.telemetry.monitor_jobs(self.jobs)
            self.telemetry.monitor_watches(self.watches)
            await self.telemetry.run()
        bus.publish(GLOBAL_STARTUP)

        reload = await bus.wait()
        await asyncio.gather(*job_tasks, return_exceptions=True)
        # the completion watcher may have scheduled teardown; it MUST
        # finish before a reload rebinds the same control socket, or
        # gen N's unlink would race gen N+1's fresh bind
        if stop_task is not None:
            await stop_task
        else:
            await self._stop_generation()
        return reload

    async def _stop_generation(self) -> None:
        """Serialize teardown of the non-job actors after jobs finish
        (reference: ctx-cancel cascade, core/app.go:113-121)."""
        for watch in self.watches:
            watch.stop()
        if self.telemetry is not None:
            for metric in self.telemetry.metrics:
                metric.stop()
            await self.telemetry.stop()
        await self.control_server.stop()

    def _reload_app(self) -> bool:
        """Rebuild everything from the same config path
        (reference: core/app.go:183-196)."""
        try:
            new_app = App.from_config_path(self.config_path)
        except Exception as exc:
            log.error("error initializing config: %s", exc)
            return False
        # old-generation execs got SIGTERM in their jobs' cleanup; give
        # them the old stopTimeout of grace, then SIGKILL stragglers so
        # a TERM-ignoring child can't double-run alongside the new
        # generation (improvement over the reference, which only kills
        # on final shutdown — core/app.go:147-156)
        old_jobs = self.jobs
        old_grace = self.stop_timeout

        async def _kill_stragglers() -> None:
            await asyncio.sleep(old_grace)
            for job in old_jobs:
                if job.exec is not None and job.exec.running:
                    log.info(
                        "reload: killing straggler processes for job %r",
                        job.name,
                    )
                    job.kill()

        # fire-and-forget by design, but never unreferenced: spawn's
        # module-level pending set keeps the killer alive across the
        # generation swap, and its done-callback logs a death
        spawn(_kill_stragglers(), name="reload-kill-stragglers")
        self.cfg = new_app.cfg
        self.jobs = new_app.jobs
        self.watches = new_app.watches
        self.stop_timeout = new_app.stop_timeout
        self.telemetry = new_app.telemetry
        self.control_server = new_app.control_server
        return True
