"""CLI argument parsing and subcommand dispatch
(reference: core/flags.go, main.go).

Flags mirror the reference: -config, -version, -template/-out, -reload,
-maintenance enable|disable, -putenv k=v (repeatable), -putmetric k=v
(repeatable), -ping. With no subcommand flag, the supervisor itself
runs.
"""
from __future__ import annotations

import argparse
import os
from typing import Callable, Optional, Tuple

from .. import subcommands


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="containerpilot-tpu",
        description=(
            "An application-lifecycle supervisor for TPU-VM pods: runs, "
            "health-checks, and service-registers per-host processes."
        ),
    )
    parser.add_argument(
        "-config",
        dest="config",
        default="",
        help="File path to JSON5 configuration file. "
        "Defaults to the CONTAINERPILOT env var.",
    )
    parser.add_argument(
        "-version", dest="version", action="store_true",
        help="Show version identifier and quit.",
    )
    parser.add_argument(
        "-template", dest="template", action="store_true",
        help="Render template and quit.",
    )
    parser.add_argument(
        "-out", dest="out", default="-",
        help="File path to save the rendered config when '-template' is "
        "used. Defaults to stdout ('-').",
    )
    parser.add_argument(
        "-reload", dest="reload", action="store_true",
        help="Reload a running supervisor through its control socket.",
    )
    parser.add_argument(
        "-maintenance", dest="maintenance", default="",
        choices=["", "enable", "disable"],
        help="Toggle maintenance mode through the control socket.",
    )
    parser.add_argument(
        "-putenv", dest="putenv", action="append", default=[],
        metavar="KEY=VALUE",
        help="Update the environ of a running supervisor (repeatable).",
    )
    parser.add_argument(
        "-putmetric", dest="putmetric", action="append", default=[],
        metavar="KEY=VALUE",
        help="Update metrics of a running supervisor (repeatable).",
    )
    parser.add_argument(
        "-ping", dest="ping", action="store_true",
        help="Check that the control socket is up.",
    )
    parser.add_argument(
        "-catalog-server", dest="catalog_server", default="",
        metavar="HOST:PORT",
        help="Run the Consul-API-compatible catalog server for pods "
        "without an external catalog (e.g. '0.0.0.0:8500').",
    )
    parser.add_argument(
        "-catalog-snapshot", dest="catalog_snapshot", default="",
        metavar="PATH",
        help="With -catalog-server: journal catalog state to this file "
        "and restore it on start, so a restarted daemon serves its "
        "last known registrations immediately.",
    )
    return parser


def get_args(
    argv: Optional[list] = None,
) -> Tuple[Optional[Callable[[dict], int]], dict]:
    """Returns (subcommand_handler, params); handler None means "run the
    supervisor" (reference: core/flags.go:46-130)."""
    args = build_parser().parse_args(argv)
    config_path = args.config or os.environ.get("CONTAINERPILOT", "")
    params = {
        "config_path": config_path,
        "render_flag": args.out,
        "maintenance_flag": args.maintenance,
        "env": args.putenv,
        "metrics": args.putmetric,
    }
    if args.version:
        return subcommands.version_handler, params
    if args.template:
        return subcommands.render_handler, params
    if args.reload:
        return subcommands.reload_handler, params
    if args.maintenance:
        return subcommands.maintenance_handler, params
    if args.putenv:
        return subcommands.put_env_handler, params
    if args.putmetric:
        return subcommands.put_metrics_handler, params
    if args.ping:
        return subcommands.ping_handler, params
    if args.catalog_server:
        params["catalog_addr"] = args.catalog_server
        params["catalog_snapshot"] = args.catalog_snapshot
        return subcommands.catalog_server_handler, params
    return None, params
