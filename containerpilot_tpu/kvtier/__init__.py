"""Fleet-wide KV reuse: the host-RAM spill tier and the prefix
digest the cache-aware gateway routes on.

Two coupled halves of one idea — stop recomputing prefixes anywhere
in the fleet:

- :mod:`.spill` keeps the KV rows a replica's ``PrefixCache`` LRU
  would have dropped, in a byte-budgeted host-RAM store. A later
  match readmits them through the existing ``reuse_admission``
  protocol (a ``jax.device_put`` roundtrip is far cheaper than
  re-prefilling the prefix).
- :mod:`.digest` is the wire format replicas use to advertise WHAT
  they have cached: a compact, versioned fingerprint set of cached
  prompt prefixes, published through heartbeat notes and
  ``/v1/model``, which the gateway blends into its routing pick.
- :mod:`.handoff` moves one cached entry BETWEEN replicas — the
  disaggregated prefill/decode fleet's live KV transfer: a
  length-prefixed, digest-verified cp-mux/1 stream (the PR 13
  weight-transfer discipline) whose receiver injects into the spill
  tier and readmits through the same ``reuse_admission`` path.

The package is import-light by design (no JAX at import time): the
gateway imports the digest codec without pulling an accelerator
stack, and the spill tier defers its ``jax`` imports to the first
transfer.
"""
from .digest import (
    DIGEST_MAX_BYTES,
    FP_TOKENS,
    encode_fingerprints,
    encode_migration_note,
    parse_digest,
    parse_kv_counters,
    parse_kv_note,
    parse_migration_note,
    prefix_fingerprint,
)
from .handoff import (
    KV_PATH,
    KV_PULL_PATH,
    KVTransferError,
    MIGRATE_PATH,
    fetch_kv,
    kv_transfer_plan,
    plan_migration,
    push_kv,
    rebuild_kv,
)
from .spill import HostSpillTier

__all__ = [
    "DIGEST_MAX_BYTES",
    "FP_TOKENS",
    "HostSpillTier",
    "KVTransferError",
    "KV_PATH",
    "KV_PULL_PATH",
    "MIGRATE_PATH",
    "encode_fingerprints",
    "encode_migration_note",
    "fetch_kv",
    "kv_transfer_plan",
    "parse_digest",
    "parse_kv_counters",
    "parse_kv_note",
    "parse_migration_note",
    "plan_migration",
    "prefix_fingerprint",
    "push_kv",
    "rebuild_kv",
]
