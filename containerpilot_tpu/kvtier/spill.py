"""Host-RAM KV spill tier: the floor under the prefix cache's LRU.

A replica's ``PrefixCache`` holds completed prompts' KV caches in
device memory, and device memory is the scarcest resource on the box
— so the LRU is small, and under multi-tenant chat traffic entries
are evicted while their sessions are still alive. Re-prefilling an
evicted prefix costs a full quadratic pass; copying it back from host
RAM costs one ``jax.device_put``. Following the CPU-GPU-coupled
characterization (PAPERS.md), this tier keeps evicted entries in host
memory instead of dropping them:

- **Spill**: on LRU eviction the cache dict (a pytree of device
  arrays) is fetched to host numpy (``jax.device_get``) and stored in
  a byte-budgeted OrderedDict LRU of its own. Entries larger than the
  whole budget are refused (counted), and inserts evict
  least-recently-used spilled entries until the budget holds.
- **Readmit**: ``take()`` pops the host copy and ``jax.device_put``\\ s
  it back. The roundtrip is byte-exact — device_get/device_put
  preserve dtype and contents bit-for-bit — so the rewind+extend
  reuse path and its byte-parity test discipline are untouched; the
  readmitted entry re-enters the device LRU as most-recently-used.

Thread safety: spills run on the inference executor thread while
matching runs on the event-loop thread, so the index is locked; the
device transfers themselves happen OUTSIDE the lock (they can take
milliseconds, and a transfer must not block a concurrent
``match_len`` scan). ``take`` pops atomically, so two concurrent
readmits of one key cannot double-serve it.

Single-host placement only: the pod mirror's replicated repin gives
its cache entries multi-device shardings that a plain ``device_put``
would collapse, so the pod path does not attach a spill tier.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set, Tuple

from .digest import prefix_fingerprint


def _tree_nbytes(host_tree: Any) -> int:
    """Total bytes of a host pytree's array leaves."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(host_tree)
    )


class HostSpillTier:
    """Byte-budgeted host-RAM LRU of evicted KV cache entries."""

    def __init__(self, max_bytes: int) -> None:
        if max_bytes < 1:
            raise ValueError("spill tier max_bytes must be >= 1")
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        #: key -> (host pytree, nbytes)
        self._store: "OrderedDict[Tuple[int, ...], Tuple[Any, int]]" = (
            OrderedDict()
        )
        #: prefix fingerprint -> keys sharing it. A usable reuse
        #: match shares at least MIN_REUSE == FP_TOKENS leading ids,
        #: i.e. the same fingerprint — so the per-request match scan
        #: compares only this bucket (a few collision candidates)
        #: instead of every spilled key, and stays O(device LRU)
        #: however large the host budget grows. Keys too short to
        #: fingerprint can never match >= MIN_REUSE and are not
        #: indexed (PrefixCache doesn't spill them).
        self._by_fp: Dict[int, Set[Tuple[int, ...]]] = {}
        self._bytes = 0
        self.stats = {
            "spilled": 0,       # entries accepted into the tier
            "readmitted": 0,    # entries handed back to the device
            "evicted": 0,       # entries dropped for budget
            "refused": 0,       # entries larger than the whole budget
            "misses": 0,        # take() of a key not (or no longer) here
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def keys(self) -> List[Tuple[int, ...]]:
        """Snapshot of spilled keys, for digest publication (keys are
        immutable tuples; the list is safe to scan lock-free)."""
        with self._lock:
            return list(self._store)

    def candidates(
        self, fp: Optional[int]
    ) -> List[Tuple[int, ...]]:
        """Spilled keys that could match a row with prefix
        fingerprint ``fp`` at >= MIN_REUSE tokens (same-fingerprint
        bucket; collisions cost one exact compare, never a wrong
        answer). None — a row too short to fingerprint — can't reach
        the reuse floor at all."""
        if fp is None:
            return []
        with self._lock:
            bucket = self._by_fp.get(fp)
            return list(bucket) if bucket else []

    def _index(self, key: Tuple[int, ...]) -> None:
        fp = prefix_fingerprint(key)
        if fp is not None:
            self._by_fp.setdefault(fp, set()).add(key)

    def _unindex(self, key: Tuple[int, ...]) -> None:
        fp = prefix_fingerprint(key)
        bucket = self._by_fp.get(fp)
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._by_fp[fp]

    def put(self, key: Tuple[int, ...], cache: Any) -> bool:
        """Spill one evicted entry. Returns True when it was
        accepted; False when it exceeds the whole budget (refused)."""
        import jax

        # device -> host OUTSIDE the lock: a multi-ms transfer must
        # not block concurrent match scans
        host = jax.device_get(cache)
        nbytes = _tree_nbytes(host)
        if nbytes > self.max_bytes:
            self.stats["refused"] += 1
            return False
        with self._lock:
            old = self._store.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            else:
                self._index(key)
            self._store[key] = (host, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._store:
                evicted, (_, dropped) = self._store.popitem(last=False)
                self._unindex(evicted)
                self._bytes -= dropped
                self.stats["evicted"] += 1
        self.stats["spilled"] += 1
        return True

    def put_host(self, key: Tuple[int, ...], host_tree: Any) -> int:
        """Insert an entry that is ALREADY host-side (a handed-off KV
        prefix rebuilt from the wire — kvtier/handoff.py) without any
        device round-trip. Returns the bytes stored, 0 when refused
        for budget. The entry then readmits through the exact
        ``take``/``reuse_admission`` path a locally-spilled one
        takes, which is what makes handoff byte-parity hold by
        construction."""
        nbytes = _tree_nbytes(host_tree)
        if nbytes > self.max_bytes:
            self.stats["refused"] += 1
            return 0
        with self._lock:
            old = self._store.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            else:
                self._index(key)
            self._store[key] = (host_tree, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._store:
                evicted, (_, dropped) = self._store.popitem(last=False)
                self._unindex(evicted)
                self._bytes -= dropped
                self.stats["evicted"] += 1
        self.stats["spilled"] += 1
        return nbytes

    def peek(self, key: Tuple[int, ...]) -> Optional[Any]:
        """Non-destructive host-side read for EXPORT (the handoff
        send path): the stored host tree itself, no device ops, no
        LRU movement, the entry stays readmittable. Callers only
        serialize from it (leaves are effectively immutable)."""
        with self._lock:
            entry = self._store.get(key)
            return entry[0] if entry is not None else None

    def take(self, key: Tuple[int, ...]) -> Optional[Any]:
        """Pop one entry and readmit it to the device, or None when
        the key isn't spilled (evicted for budget, never spilled, or
        already taken by a concurrent readmit)."""
        import jax

        with self._lock:
            entry = self._store.pop(key, None)
            if entry is not None:
                self._bytes -= entry[1]
                self._unindex(key)
        if entry is None:
            self.stats["misses"] += 1
            return None
        self.stats["readmitted"] += 1
        # host -> device outside the lock, same rationale as put()
        return jax.device_put(entry[0])

    def snapshot(self) -> Dict[str, int]:
        """Stats + size for surfaces (``/v1/model``)."""
        with self._lock:
            return {
                "max_bytes": self.max_bytes,
                "bytes": self._bytes,
                "entries": len(self._store),
                **self.stats,
            }
