"""Prefix digests: how a replica tells the fleet what it has cached.

The gateway's cache-aware routing needs to know, per replica, WHICH
prompt prefixes are warm — without shipping token tuples around. The
answer is a fingerprint set:

- ``prefix_fingerprint(tokens)`` hashes the first ``FP_TOKENS`` ids
  of a prompt to a stable 32-bit value. ``FP_TOKENS`` equals the
  prefix cache's ``MIN_REUSE``: anything shorter can never be reused,
  so it never needs advertising. The hash is blake2b, NOT Python's
  ``hash()`` — it must agree across processes and runs.
- ``encode_fingerprints(version, fps)`` packs a set of fingerprints
  into ``v<version>:<8-hex each, sorted>``, truncated to
  ``DIGEST_MAX_BYTES`` so a huge cache can't balloon heartbeat notes
  or ``/v1/model`` responses. The version lets readers tell a fresh
  digest from a stale re-read.
- ``parse_digest(raw)`` is the tolerant reader: any malformed input
  (hostile peer, torn note) decodes to ``(None, frozenset())``, never
  an exception on the routing path.

Digests travel the way occupancy already does — as ``key=value``
fields in the TTL heartbeat's check output (``ok occ=0.50
kv=... pd=v3:...``), parsed with ``parse_kv_note`` — and verbatim in
``/v1/model``'s ``prefix_digest`` field.

A fingerprint match is a HINT, not a promise: the entry may have been
evicted (even from the spill tier) by the time the request lands, or
two distinct prefixes may collide in 32 bits (~1 in 4e9). Both cost
one wasted preference, never a wrong answer — the replica simply
prefills cold, exactly as an unhinted request would.
"""
from __future__ import annotations

import hashlib
from typing import Dict, FrozenSet, Iterable, Optional, Sequence, Tuple

#: prompt ids hashed into one fingerprint; equals serve_prefix's
#: MIN_REUSE (shorter prefixes are never reusable, so never
#: advertised) — keep the two in lockstep
FP_TOKENS = 16

#: byte bound on one encoded digest: it rides every TTL heartbeat's
#: check output, so it must stay note-sized (~128 fingerprints)
DIGEST_MAX_BYTES = 1024

_HEADER = "v"


def prefix_fingerprint(tokens: Sequence[int]) -> Optional[int]:
    """Stable 32-bit fingerprint of a prompt's first ``FP_TOKENS``
    ids, or None when the prompt is too short to ever be reused."""
    if len(tokens) < FP_TOKENS:
        return None
    raw = b"".join(
        int(t).to_bytes(4, "little", signed=True)
        for t in tokens[:FP_TOKENS]
    )
    return int.from_bytes(
        hashlib.blake2b(raw, digest_size=4).digest(), "big"
    )


def encode_fingerprints(
    version: int,
    fps: Iterable[int],
    max_bytes: int = DIGEST_MAX_BYTES,
) -> str:
    """``v<version>:<hex8 hex8 ...>`` (no separators), size-bounded.
    Sorted so equal sets encode identically; truncation keeps the
    lexicographically-smallest fingerprints, which is arbitrary but
    deterministic — a bounded digest is a sample, not a census."""
    header = f"{_HEADER}{int(version)}:"
    budget = max(0, max_bytes - len(header))
    body = "".join(
        f"{fp & 0xFFFFFFFF:08x}" for fp in sorted(set(fps))
    )[: (budget // 8) * 8]
    return header + body


def parse_digest(raw: object) -> Tuple[Optional[int], FrozenSet[int]]:
    """Tolerant inverse of :func:`encode_fingerprints`. Garbage — a
    hostile note, a torn read, the wrong field — parses to
    ``(None, frozenset())``; the routing path never throws on it."""
    if not isinstance(raw, str) or not raw.startswith(_HEADER):
        return None, frozenset()
    head, sep, body = raw[len(_HEADER):].partition(":")
    if not sep or not head.isascii() or not head.isdigit():
        return None, frozenset()
    if len(body) % 8 != 0 or len(body) > DIGEST_MAX_BYTES:
        return None, frozenset()
    try:
        fps = frozenset(
            int(body[i:i + 8], 16) for i in range(0, len(body), 8)
        )
    except ValueError:
        return None, frozenset()
    return int(head), fps


def parse_kv_note(notes: object) -> Dict[str, str]:
    """Split a heartbeat check output (``ok occ=0.50 kv=1,2,3
    pd=v4:...``) into its ``key=value`` fields. Bare words (the
    leading ``ok``) are dropped; duplicate keys keep the last."""
    out: Dict[str, str] = {}
    if not isinstance(notes, str):
        return out
    for token in notes.split():
        key, sep, value = token.partition("=")
        if sep and key:
            out[key] = value
    return out


#: migration-note counter names, wire order (all cumulative over the
#: replica's life; ``active`` is a 0/1 flag, not a counter)
MIGRATION_FIELDS = ("done", "total", "failed", "timeout", "active")


def encode_migration_note(
    done: int,
    total: int,
    failed: int,
    timeout: int,
    active: bool,
    landed: Iterable[Tuple[int, str]] = (),
    max_bytes: int = DIGEST_MAX_BYTES,
) -> str:
    """Encode a drain-migration progress report for the ``mg=``
    heartbeat-note field: ``done,total,failed,timeout,active`` plus
    zero or more ``;<fp hex8>:<target_id>`` landing segments — all
    non-whitespace, so :func:`parse_kv_note` carries it intact.
    Landings are size-bounded; callers pass them most-recent-first so
    truncation drops the repoints the gateway has already seen."""
    head = "%d,%d,%d,%d,%d" % (
        max(0, int(done)), max(0, int(total)), max(0, int(failed)),
        max(0, int(timeout)), 1 if active else 0,
    )
    out = [head]
    budget = max_bytes - len(head)
    for fp, target in landed:
        tid = "".join(
            ch for ch in str(target) if not ch.isspace() and ch != ";"
        )
        seg = f";{int(fp) & 0xFFFFFFFF:08x}:{tid}"
        if len(seg) > budget:
            break
        out.append(seg)
        budget -= len(seg)
    return "".join(out)


def parse_migration_note(
    raw: object,
) -> Tuple[Dict[str, int], Dict[int, str]]:
    """Tolerant inverse of :func:`encode_migration_note`. Returns
    ``(counters, landed)`` where counters zero-fill on short or torn
    input (same discipline as :func:`parse_kv_counters`: a half-
    written note must not zero a replica's migration state) and
    malformed landing segments are skipped, never thrown on."""
    out = {name: 0 for name in MIGRATION_FIELDS}
    landed: Dict[int, str] = {}
    if not isinstance(raw, str) or not raw:
        return out, landed
    head, _, tail = raw.partition(";")
    for name, part in zip(MIGRATION_FIELDS, head.split(",")):
        try:
            out[name] = max(0, int(part))
        except ValueError:
            break
    out["active"] = min(1, out["active"])
    for seg in tail.split(";") if tail else ():
        fp_hex, sep, target = seg.partition(":")
        if not sep or len(fp_hex) != 8 or not target:
            continue
        try:
            fp = int(fp_hex, 16)
        except ValueError:
            continue
        landed.setdefault(fp, target)
    return out, landed


def parse_kv_counters(raw: object) -> Dict[str, int]:
    """Decode the ``kv=`` note field: five comma-separated ints
    (hits, misses, tokens_reused, spilled, readmitted). Short or
    malformed values yield the fields that did parse, zero-filled —
    a half-written note must not zero a replica's routing state."""
    names = ("hits", "misses", "tokens_reused", "spilled", "readmitted")
    out = {name: 0 for name in names}
    if not isinstance(raw, str) or not raw:
        return out
    for name, part in zip(names, raw.split(",")):
        try:
            out[name] = max(0, int(part))
        except ValueError:
            break
    return out
