"""Live KV handoff: ship one prefix-cache entry replica -> replica.

The disaggregated prefill/decode fleet (fleet/gateway.py) runs a
prompt through a *prefill* replica's slot-engine admission, then moves
the resulting KV prefix to the pinned *decode* replica so its decode
rounds never pay a cold prefill. This module is the wire for that
move, deliberately the SAME discipline as PR 13's peer weight
transfer (fleet/standby.py):

    u64 manifest_len | manifest JSON | chunk bytes back-to-back

served by ``POST /v1/kv`` (workload/serve.py) as one close-delimited
cp-mux/1 stream, with ``?chunk=K`` resuming at the first unverified
chunk and ONE transparent redial on connection death. Every chunk
carries a blake2b-8 digest; a mismatch is corruption, not a transport
problem, so it fails the transfer immediately and the receiver
returns None — the decode replica then prefills locally, exactly as
an unhinted request would. Handoff is an accelerator, never a new
failure mode.

Unlike the weight manifest (whose treedef comes from the fetcher's
own ``like`` tree), a KV entry's structure is not known to the
receiver in advance, so the manifest here is **self-describing**: a
JSON skeleton mirrors the pytree's dict/list/tuple structure with
leaf indices at the arrays, and ``rebuild_kv`` reassembles the host
tree from skeleton + leaf table + verified chunks with no template.

Byte parity holds by construction: the receiver injects the rebuilt
host tree into its spill tier (``HostSpillTier.put_host``), and the
next request readmits it through the SAME ``reuse_admission``
protocol a locally-spilled entry takes — device_get/device_put
round-trips are bit-exact, so a handed-off conversation decodes
token-for-token like a local one.

Import-light like the rest of the package: jax and the fleet
transport load inside functions, so the gateway can import the codec
without an accelerator stack (and without an import cycle — fleet
imports kvtier at module scope).
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("containerpilot.kvtier")

__all__ = [
    "KVTransferError",
    "KV_CHUNK",
    "KV_PATH",
    "KV_PULL_PATH",
    "MIGRATE_PATH",
    "encode_kv_manifest",
    "fetch_kv",
    "kv_transfer_plan",
    "plan_migration",
    "push_kv",
    "rebuild_kv",
]

#: path a replica serves (and pulls) prefix-cache entries on
KV_PATH = "/v1/kv"

#: path a replica adopts a peer's entry on ({"tokens", "from"}) — the
#: same verb the gateway's disaggregated handoff POSTs, and the one a
#: DRAINING replica drives in reverse to evacuate its sessions
KV_PULL_PATH = "/v1/kv/pull"

#: path a replica reports (and takes) migration instructions on
MIGRATE_PATH = "/v1/migrate"

#: bytes per chunk — the weight stream's economics apply unchanged
#: (amortize the per-chunk digest, keep resume re-ship small)
KV_CHUNK = 256 * 1024

#: sanity cap on a KV manifest (skeleton + tables; entries are a few
#: hundred leaves at most, nothing like a weight manifest)
_MANIFEST_CAP = 8 * 1024 * 1024

_MANIFEST_LEN_BYTES = 8


class KVTransferError(RuntimeError):
    """The handoff failed in a way a redial cannot fix (digest
    mismatch, manifest drift, malformed skeleton): the receiver
    falls back to a local prefill, it does not retry the peer."""


def _chunk_digest(data: bytes) -> str:
    import hashlib

    return hashlib.blake2b(data, digest_size=8).hexdigest()


# -- the self-describing tree codec ------------------------------------


def _flatten(node: Any, leaves: List[Any]) -> Any:
    """Walk a host pytree into a JSON skeleton; every non-container
    node becomes ``{"x": i}`` pointing into ``leaves``. Dict keys
    must be strings (a KV cache's are) — anything else cannot
    round-trip JSON and refuses the transfer."""
    if isinstance(node, dict):
        if any(not isinstance(k, str) for k in node):
            raise KVTransferError(
                "KV tree has non-string dict keys; not transferable"
            )
        return {"d": {k: _flatten(v, leaves) for k, v in node.items()}}
    if isinstance(node, (list, tuple)):
        kind = "l" if isinstance(node, list) else "t"
        return {kind: [_flatten(v, leaves) for v in node]}
    leaves.append(node)
    return {"x": len(leaves) - 1}


def _unflatten(skeleton: Any, leaves: List[Any]) -> Any:
    if not isinstance(skeleton, dict) or len(skeleton) != 1:
        raise KVTransferError("malformed KV skeleton node")
    (kind, value), = skeleton.items()
    if kind == "d":
        if not isinstance(value, dict):
            raise KVTransferError("malformed KV skeleton dict")
        return {k: _unflatten(v, leaves) for k, v in value.items()}
    if kind in ("l", "t"):
        if not isinstance(value, list):
            raise KVTransferError("malformed KV skeleton sequence")
        seq = [_unflatten(v, leaves) for v in value]
        return seq if kind == "l" else tuple(seq)
    if kind == "x":
        if not isinstance(value, int) or not 0 <= value < len(leaves):
            raise KVTransferError("KV skeleton leaf index out of range")
        return leaves[value]
    raise KVTransferError(f"unknown KV skeleton node kind {kind!r}")


def kv_transfer_plan(
    host_tree: Any, chunk_bytes: int = KV_CHUNK
) -> Tuple[Dict[str, Any], List[bytes]]:
    """(manifest, per-leaf byte blobs) for one host-side KV entry.
    Blocking-ish (numpy ``tobytes`` per leaf): executor-wrap it.
    Deterministic for the same entry, so a resumed stream's digests
    match the first attempt's manifest."""
    import numpy as np

    raw_leaves: List[Any] = []
    skeleton = _flatten(host_tree, raw_leaves)
    leaves: List[Dict[str, Any]] = []
    blobs: List[bytes] = []
    chunks: List[Dict[str, Any]] = []
    for index, leaf in enumerate(raw_leaves):
        arr = np.asarray(leaf)
        data = arr.tobytes()
        leaves.append(
            {
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "bytes": len(data),
            }
        )
        blobs.append(data)
        for offset in range(0, len(data) or 1, chunk_bytes):
            piece = data[offset:offset + chunk_bytes]
            chunks.append(
                {
                    "leaf": index,
                    "offset": offset,
                    "len": len(piece),
                    "digest": _chunk_digest(piece),
                }
            )
    manifest = {
        "version": 1,
        "skeleton": skeleton,
        "total_bytes": sum(entry["bytes"] for entry in leaves),
        "leaves": leaves,
        "chunks": chunks,
    }
    return manifest, blobs


def encode_kv_manifest(manifest: Dict[str, Any]) -> bytes:
    """Length-prefixed manifest blob — the stream's first bytes
    (the weight stream's framing, verbatim)."""
    body = json.dumps(manifest, sort_keys=True).encode()
    return len(body).to_bytes(_MANIFEST_LEN_BYTES, "big") + body


def rebuild_kv(
    manifest: Dict[str, Any], chunks: List[bytes]
) -> Any:
    """Reassemble the host KV tree from a verified chunk list — no
    template needed, the manifest's skeleton IS the treedef. Raises
    KVTransferError on any structural disagreement."""
    import numpy as np

    specs = manifest.get("leaves")
    chunk_specs = manifest.get("chunks")
    skeleton = manifest.get("skeleton")
    if not isinstance(specs, list) or not isinstance(chunk_specs, list):
        raise KVTransferError("KV manifest missing its tables")
    if len(chunks) != len(chunk_specs):
        raise KVTransferError(
            f"{len(chunks)} chunks received, manifest names "
            f"{len(chunk_specs)}"
        )
    by_leaf: List[List[bytes]] = [[] for _ in specs]
    for spec, data in zip(chunk_specs, chunks):
        leaf = spec.get("leaf")
        if not isinstance(leaf, int) or not 0 <= leaf < len(specs):
            raise KVTransferError("KV chunk names a leaf out of range")
        by_leaf[leaf].append(data)
    leaves: List[Any] = []
    for spec, pieces in zip(specs, by_leaf):
        data = b"".join(pieces)
        if len(data) != int(spec["bytes"]):
            raise KVTransferError(
                f"leaf byte count {len(data)} != manifest "
                f"{spec['bytes']}"
            )
        try:
            arr = np.frombuffer(
                data, dtype=np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
        except (TypeError, ValueError) as exc:
            raise KVTransferError(
                f"leaf does not reassemble: {exc}"
            ) from None
        leaves.append(arr)
    return _unflatten(skeleton, leaves)


# -- the fetch client (decode-replica side) ----------------------------


async def _read_kv_manifest(reader: Any) -> Dict[str, Any]:
    from ..fleet.pool import UpstreamError

    raw_len = await reader.read_exact(_MANIFEST_LEN_BYTES)
    length = int.from_bytes(raw_len, "big")
    if not 0 < length <= _MANIFEST_CAP:
        raise UpstreamError(f"implausible KV manifest length {length}")
    try:
        manifest = json.loads((await reader.read_exact(length)).decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise UpstreamError(f"malformed KV manifest: {exc}") from None
    if not isinstance(manifest, dict) or not isinstance(
        manifest.get("chunks"), list
    ):
        raise UpstreamError("KV manifest missing its chunk table")
    return manifest


async def fetch_kv_chunks(
    address: str,
    port: int,
    tokens: List[int],
    *,
    connect_timeout: float = 5.0,
    read_timeout: float = 30.0,
) -> Tuple[Dict[str, Any], List[bytes]]:
    """Fetch one prompt's KV entry from a peer over cp-mux/1:
    (manifest, verified chunks). The weight transfer's exact
    resume/redial discipline — ONE transparent redial on connection
    death resuming at the first unverified chunk, digest mismatches
    and manifest drift raising KVTransferError immediately (a redial
    cannot fix corruption)."""
    from ..fleet.pool import ConnectionPool, UpstreamError
    from ..fleet.standby import _ChunkedReader, _Peer

    pool = ConnectionPool(mux=True)
    peer = _Peer(address, port)
    # one row in the token-matrix shape every serve endpoint parses
    body = json.dumps({"tokens": [list(tokens)]}).encode()
    got: List[bytes] = []
    manifest: Optional[Dict[str, Any]] = None
    redialed = False
    try:
        while True:
            try:
                conn = await pool.acquire_mux(peer, connect_timeout)
                if conn is None:
                    raise UpstreamError(
                        f"{peer.authority} declined the cp-mux/1 "
                        f"upgrade"
                    )
                stream = await conn.open_stream(
                    "POST", f"{KV_PATH}?chunk={len(got)}", body=body
                )
                status, _headers = await stream.response_head(
                    read_timeout
                )
                if status != 200:
                    raise UpstreamError(
                        f"KV fetch answered {status}"
                    )
                reader = _ChunkedReader(stream, read_timeout)
                fresh = await _read_kv_manifest(reader)
                if manifest is None:
                    manifest = fresh
                elif fresh != manifest:
                    # the peer's entry changed between attempts
                    # (evicted and recomputed): the already-verified
                    # prefix belongs to a different serialization
                    raise KVTransferError(
                        "peer KV manifest changed across the redial"
                    )
                specs = manifest["chunks"]
                while len(got) < len(specs):
                    spec = specs[len(got)]
                    data = await reader.read_exact(int(spec["len"]))
                    if _chunk_digest(data) != spec["digest"]:
                        raise KVTransferError(
                            f"KV chunk {len(got)} digest mismatch"
                        )
                    got.append(data)
                return manifest, got
            except KVTransferError:
                raise
            except UpstreamError:
                if redialed:
                    raise
                redialed = True
                # drop the dead shared connection so the next acquire
                # dials fresh; fully-verified chunks stay counted
                pool.close_all()
                log.warning(
                    "kv handoff: peer stream died at chunk %d; "
                    "redialing once to resume", len(got),
                )
    finally:
        pool.close_all()


async def fetch_kv(
    address: str,
    port: int,
    tokens: List[int],
    *,
    connect_timeout: float = 5.0,
    read_timeout: float = 30.0,
) -> Optional[Tuple[Any, int]]:
    """Fetch + reassemble one prompt's KV entry from a peer:
    ``(host_tree, total_bytes)`` on success, None on ANY failure —
    poisoned chunk, declined upgrade, 404, second connection death —
    so the caller falls back to a local prefill and corrupt KV is
    never served. Assembly (numpy) runs on an executor; no device
    ops happen here at all — injection stays host-side until the
    inference thread readmits through ``reuse_admission``."""
    from ..fleet.pool import UpstreamError

    try:
        manifest, chunks = await fetch_kv_chunks(
            address, port, tokens,
            connect_timeout=connect_timeout,
            read_timeout=read_timeout,
        )
    except (KVTransferError, UpstreamError, OSError) as exc:
        log.warning(
            "kv handoff: fetch from %s:%d failed (%s); falling back "
            "to local prefill", address, port, exc,
        )
        return None
    loop = asyncio.get_event_loop()
    try:
        host_tree = await loop.run_in_executor(
            None, rebuild_kv, manifest, chunks
        )
    except (KVTransferError, ValueError, TypeError) as exc:
        log.warning(
            "kv handoff: fetched entry does not reassemble (%s); "
            "falling back to local prefill", exc,
        )
        return None
    return host_tree, int(manifest.get("total_bytes", 0))


# -- drain migration: the same wire, driven in reverse ------------------


def plan_migration(
    keys: Any, targets: List[Tuple[str, Any]]
) -> List[Dict[str, Any]]:
    """Deterministic reverse-push plan for a draining replica: which
    cached prefix goes to which survivor.

    ``keys`` are the drainer's cached prompt keys (token tuples,
    device + spill tiers); ``targets`` are ``(target_id,
    fingerprint_set)`` pairs — each survivor's advertised ``pd=``
    digest, parsed. The plan is a list of ``{"key", "fp", "target",
    "warm"}`` entries, one per migratable key:

    - keys under the fingerprint floor are dropped (they can never be
      reused, so there is nothing worth moving);
    - a fingerprint already warm on a survivor is recorded as landed
      there with ``warm=True`` — zero bytes move, but the landing
      still repoints the gateway's pin;
    - every key sharing a fingerprint goes to ONE survivor (a
      conversation's turns share their first-FP_TOKENS ids, and
      splitting the family would strand its longest prefixes);
    - cold fingerprints go to the digest-coldest target (fewest
      advertised + already-planned fingerprints), ties broken by id.

    Pure and deterministic — same keys + same targets produce the
    same plan regardless of input order, so a resumed or re-driven
    drain pushes the same assignments (tests pin this).
    """
    from .digest import prefix_fingerprint

    plan: List[Dict[str, Any]] = []
    if not targets:
        return plan
    warmth: Dict[str, Any] = {
        tid: frozenset(fps) for tid, fps in targets
    }
    ids = sorted(warmth)
    # longest prefixes first: they carry the most recompute, and the
    # family placement they decide is the one the shorter turns join
    ordered = sorted(
        {tuple(k) for k in keys}, key=lambda k: (-len(k), k)
    )
    assigned: Dict[str, int] = {tid: 0 for tid in ids}
    placed: Dict[int, str] = {}  # fp -> survivor chosen this plan
    for key in ordered:
        fp = prefix_fingerprint(list(key))
        if fp is None:
            continue
        tid = placed.get(fp)
        if tid is None:
            warm_ids = [t for t in ids if fp in warmth[t]]
            tid = warm_ids[0] if warm_ids else min(
                ids,
                key=lambda t: (len(warmth[t]) + assigned[t], t),
            )
            placed[fp] = tid
        warm = fp in warmth[tid]
        if not warm:
            assigned[tid] += 1
        plan.append(
            {"key": key, "fp": fp, "target": tid, "warm": warm}
        )
    return plan


async def push_kv(
    address: str,
    port: int,
    tokens: List[int],
    source: str,
    *,
    connect_timeout: float = 5.0,
    read_timeout: float = 30.0,
) -> Optional[int]:
    """POST a pull instruction at a survivor: ask ``address:port`` to
    ``fetch_kv`` this prompt's entry from ``source`` (the draining
    replica's advertised ``host:port``) and adopt it into its spill
    tier — the existing handoff wire driven in reverse, so byte
    parity holds by the same construction the prefill->decode hop
    relies on. Returns the adopted byte count on success, None on ANY
    failure (declined upgrade, non-200, transport death after the one
    redial): the drainer counts it and moves on — a failed push is a
    fallback to today's re-prefill behavior, never a new error."""
    from ..fleet.pool import ConnectionPool, UpstreamError
    from ..fleet.standby import _Peer

    pool = ConnectionPool(mux=True)
    peer = _Peer(address, port)
    body = json.dumps(
        {"tokens": [list(tokens)], "from": source, "migrate": True}
    ).encode()
    redialed = False
    try:
        while True:
            try:
                conn = await pool.acquire_mux(peer, connect_timeout)
                if conn is None:
                    raise UpstreamError(
                        f"{peer.authority} declined the cp-mux/1 "
                        f"upgrade"
                    )
                stream = await conn.open_stream(
                    "POST", KV_PULL_PATH, body=body
                )
                status, _headers = await stream.response_head(
                    read_timeout
                )
                payload = await stream.read_body(
                    read_timeout, _MANIFEST_CAP
                )
                if status != 200:
                    log.warning(
                        "kv migrate: %s refused the push (%d)",
                        peer.authority, status,
                    )
                    return None
                try:
                    return int(
                        json.loads(payload.decode()).get("bytes", 0)
                    )
                except (ValueError, AttributeError,
                        UnicodeDecodeError):
                    return 0
            except UpstreamError as exc:
                if redialed:
                    log.warning(
                        "kv migrate: push to %s failed (%s)",
                        peer.authority, exc,
                    )
                    return None
                redialed = True
                pool.close_all()
                log.warning(
                    "kv migrate: peer stream died (%s); redialing "
                    "once", exc,
                )
    except OSError as exc:
        log.warning(
            "kv migrate: push to %s failed (%s)", peer.authority, exc
        )
        return None
    finally:
        pool.close_all()
