"""Service discovery: catalog backends and per-job registration state
(reference: discovery/ package)."""
from .backend import (
    Backend,
    DiscoveryError,
    ServiceInstance,
    ServiceRegistration,
)
from .noop import NoopBackend
from .service import ServiceDefinition

__all__ = [
    "Backend",
    "DiscoveryError",
    "ServiceInstance",
    "ServiceRegistration",
    "ServiceDefinition",
    "NoopBackend",
]
