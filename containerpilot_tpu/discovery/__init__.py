"""Service discovery: catalog backends and per-job registration state
(reference: discovery/ package)."""
from .backend import (
    Backend,
    DiscoveryError,
    ServiceInstance,
    ServiceRegistration,
)
from .consul import ConsulBackend
from .factory import DiscoveryConfigError, new_backend
from .filecatalog import FileCatalogBackend
from .noop import NoopBackend
from .service import ServiceDefinition

__all__ = [
    "Backend",
    "ConsulBackend",
    "DiscoveryConfigError",
    "DiscoveryError",
    "FileCatalogBackend",
    "NoopBackend",
    "ServiceDefinition",
    "ServiceInstance",
    "ServiceRegistration",
    "new_backend",
]
