"""cp-catalogd: a Consul-API-compatible catalog server for TPU pods.

The reference delegates cross-host coordination entirely to an external
Consul cluster (reference: discovery/consul.go). TPU pods usually don't
run one — so this framework ships its own catalog daemon speaking the
same agent-API subset the supervisor (and anything else using that API)
needs:

    PUT /v1/agent/service/register          body: AgentServiceRegistration
    PUT /v1/agent/service/deregister/<id>
    PUT /v1/agent/check/update/<check-id>   body: {Status, Output}
    GET /v1/health/service/<name>?passing=1[&tag=][&dc=]

One host in the pod (or a CPU VM) runs:

    python -m containerpilot_tpu -catalog-server 0.0.0.0:8500

and every host's supervisor points ``consul: "<leader>:8500"`` at it
over DCN. TTL semantics match Consul: a check that misses its TTL goes
critical and drops out of passing health queries;
``DeregisterCriticalServiceAfter`` reaps long-critical services.

State is in-memory; with ``-catalog-snapshot`` it is also journaled to disk
(atomic JSON snapshot, written when dirty) and reloaded on start, so a
supervised catalog daemon that crashes and restarts serves its last
known registrations immediately instead of returning an empty catalog
until every supervisor's next heartbeat. Restored TTLs are re-armed
for one fresh TTL window (the entry was passing when snapshotted; its
owner gets one round to heartbeat before it goes critical).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import time
import urllib.parse
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from ..config.timing import DurationError, parse_duration
from ..utils.http import HTTPServer, Request, Response
from ..utils.tasks import spawn

log = logging.getLogger("containerpilot.catalog")


@dataclass
class _Entry:
    id: str
    name: str
    address: str
    port: int
    tags: List[str]
    ttl: float
    status: str = "critical"
    expires: float = 0.0  # 0 = never passed yet
    dereg_after: float = 0.0  # seconds critical before reaping; 0 = never
    critical_since: float = 0.0
    enable_tag_override: bool = False

    def effective_status(self, now: float) -> str:
        if self.status == "passing" and self.ttl > 0 and now > self.expires:
            return "critical"
        return self.status


class CatalogServer:
    """In-memory Consul-compatible catalog."""

    def __init__(
        self, host: str = "0.0.0.0", port: int = 8500, dc: str = "dc1",
        snapshot_path: str = "", snapshot_every: float = 2.0,
    ) -> None:
        self.host = host
        self.port = port
        self.dc = dc  # health queries for another dc return empty
        self.snapshot_path = snapshot_path
        self.snapshot_every = snapshot_every
        self._dirty = False
        self._entries: Dict[str, _Entry] = {}  # by instance id
        self._server = HTTPServer()
        self._reaper: Optional["asyncio.Task[None]"] = None
        # routes with path params are matched manually
        self._server.route(
            "PUT", "/v1/agent/service/register", self._register
        )
        self._server.fallback = self._dispatch_dynamic

    # -- lifecycle --------------------------------------------------------

    async def run(self) -> None:
        if self.snapshot_path:
            # disk read off-loop: nothing is serving yet, but a slow
            # volume must not delay sibling tasks on this loop
            await asyncio.get_running_loop().run_in_executor(
                None, self._load_snapshot
            )
        await self._server.start_tcp(self.host, self.port)
        self._reaper = spawn(self._reap_loop(), name="catalog-reaper")
        log.info("catalog: serving Consul-compatible API on %s:%d",
                 self.host, self.port)

    async def stop(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
        await self._server.stop()
        # final write AFTER the listener is down: a mutation handled
        # during shutdown was acknowledged, so it must be journaled
        if self.snapshot_path:
            await self._journal()

    # -- durability -------------------------------------------------------

    def _load_snapshot(self) -> None:
        try:
            with open(self.snapshot_path) as fh:
                raw = json.load(fh)
        except FileNotFoundError:
            return
        except (OSError, ValueError) as exc:
            log.warning("catalog: unreadable snapshot %s (%s); starting "
                        "empty", self.snapshot_path, exc)
            return
        now = time.time()
        saved_at = float(raw.get("saved_at") or now)
        for item in raw.get("entries", []):
            try:
                entry = _Entry(**item)
            except TypeError:
                log.warning("catalog: skipping malformed snapshot entry")
                continue
            if entry.status == "passing" and entry.ttl > 0:
                if entry.expires >= saved_at:
                    # it was genuinely passing when journaled: one
                    # fresh TTL window to heartbeat before critical
                    entry.expires = now + entry.ttl
                else:
                    # its TTL had already lapsed pre-snapshot (expiry
                    # is computed at query time, never written back) —
                    # don't resurrect a dead service as healthy
                    entry.status = "critical"
            entry.critical_since = 0.0
            self._entries[entry.id] = entry
        if self._entries:
            log.info("catalog: restored %d entries from %s",
                     len(self._entries), self.snapshot_path)

    def _snapshot_payload(self) -> dict:
        """Freeze entry state for journaling. Runs ON the event loop —
        the write happens off-loop, and ``_entries`` must not be
        iterated there while request handlers keep mutating it."""
        return {
            "saved_at": time.time(),
            "entries": [asdict(e) for e in
                        sorted(self._entries.values(),
                               key=lambda e: e.id)],
        }

    def _write_snapshot(self, payload: Optional[dict] = None) -> bool:
        """Blocking file write; async callers go through _journal."""
        if payload is None:
            payload = self._snapshot_payload()
        tmp = f"{self.snapshot_path}.tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.snapshot_path)  # atomic on POSIX
            return True
        except OSError as exc:
            log.warning("catalog: snapshot write failed: %s", exc)
            return False

    async def _journal(self) -> None:
        """Snapshot to disk without stalling the loop: capture the
        payload here, hand the file I/O to the default executor."""
        payload = self._snapshot_payload()
        # clear BEFORE the write so mutations landing during it
        # re-dirty the journal and get picked up next cadence
        self._dirty = False
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._write_snapshot, payload
        )
        if not ok:
            self._dirty = True

    async def _reap_loop(self) -> None:
        """Reap services critical longer than DeregisterCriticalServiceAfter;
        journal dirty state to the snapshot file on the same cadence."""
        last_snapshot = 0.0
        try:
            while True:
                await asyncio.sleep(
                    min(1.0, self.snapshot_every) if self.snapshot_path
                    else 1.0
                )
                if (
                    self.snapshot_path and self._dirty
                    and time.time() - last_snapshot >= self.snapshot_every
                ):
                    await self._journal()
                    last_snapshot = time.time()
                now = time.time()
                for entry in list(self._entries.values()):
                    status = entry.effective_status(now)
                    if status == "critical":
                        if entry.critical_since == 0.0:
                            entry.critical_since = now
                            # journal the transition: a later hard
                            # crash must not restore this entry from a
                            # stale passing-era snapshot (the rewrite
                            # moves saved_at past its expires)
                            self._dirty = True
                        elif (
                            entry.dereg_after > 0
                            and now - entry.critical_since > entry.dereg_after
                        ):
                            log.info(
                                "catalog: reaping %s (critical > %.0fs)",
                                entry.id,
                                entry.dereg_after,
                            )
                            self._entries.pop(entry.id, None)
                            self._dirty = True
                    else:
                        entry.critical_since = 0.0
        except asyncio.CancelledError:
            pass

    # -- handlers -----------------------------------------------------------

    async def _register(self, req: Request) -> Response:
        try:
            body = json.loads(req.body.decode() or "{}")
        except ValueError:
            return Response(400, b"bad json\n")
        check = body.get("Check") or {}
        ttl = 0.0
        if check.get("TTL"):
            try:
                ttl = parse_duration(check["TTL"])
            except DurationError:
                return Response(400, b"bad TTL\n")
        dereg_after = 0.0
        if check.get("DeregisterCriticalServiceAfter"):
            try:
                dereg_after = parse_duration(
                    check["DeregisterCriticalServiceAfter"]
                )
            except DurationError:
                return Response(400, b"bad DeregisterCriticalServiceAfter\n")
        try:
            port = int(body.get("Port") or 0)
        except (TypeError, ValueError):
            return Response(400, b"bad Port\n")
        entry = _Entry(
            id=body.get("ID") or body.get("Name", ""),
            name=body.get("Name", ""),
            address=body.get("Address", ""),
            port=port,
            tags=list(body.get("Tags") or []),
            ttl=ttl,
            status=check.get("Status") or "critical",
            dereg_after=dereg_after,
            enable_tag_override=bool(body.get("EnableTagOverride", False)),
        )
        if not entry.id or not entry.name:
            return Response(400, b"service needs ID and Name\n")
        if entry.status == "passing" and entry.ttl > 0:
            entry.expires = time.time() + entry.ttl
        self._entries[entry.id] = entry
        self._dirty = True
        log.debug("catalog: registered %s (%s)", entry.id, entry.status)
        return Response(200, b"")

    async def _dispatch_dynamic(self, req: Request) -> Optional[Response]:
        if req.method == "PUT" and req.path.startswith(
            "/v1/agent/service/deregister/"
        ):
            service_id = urllib.parse.unquote(req.path.rsplit("/", 1)[-1])
            if self._entries.pop(service_id, None) is not None:
                self._dirty = True
            log.debug("catalog: deregistered %s", service_id)
            return Response(200, b"")
        if req.method == "PUT" and req.path.startswith(
            "/v1/agent/check/update/"
        ):
            check_id = urllib.parse.unquote(req.path.rsplit("/", 1)[-1])
            # check ids are "service:<instance-id>"
            instance_id = check_id.split(":", 1)[-1]
            entry = self._entries.get(instance_id)
            if entry is None:
                return Response(404, b"unknown check\n")
            try:
                body = json.loads(req.body.decode() or "{}")
            except ValueError:
                return Response(400, b"bad json\n")
            status = body.get("Status", "passing")
            new_status = "passing" if status in ("pass", "passing") else (
                "warning" if status in ("warn", "warning") else "critical"
            )
            if new_status != entry.status:
                # TTL refreshes alone don't dirty the snapshot (expires
                # is re-armed on restore); status transitions do
                self._dirty = True
            entry.status = new_status
            if entry.status == "passing" and entry.ttl > 0:
                entry.expires = time.time() + entry.ttl
            return Response(200, b"")
        if req.method == "GET" and req.path == "/metrics":
            # prometheus exposition for the catalog daemon itself, so a
            # supervised cp-catalogd is scrapeable like everything else
            now = time.time()
            by_status: Dict[str, int] = {}
            for entry in self._entries.values():
                status = entry.effective_status(now)
                by_status[status] = by_status.get(status, 0) + 1
            # one labeled family only: the total is sum(by status),
            # so an unlabeled twin would double-count aggregations
            lines = ["# TYPE cp_catalog_services gauge"]
            for status in ("passing", "warning", "critical"):
                lines.append(
                    f'cp_catalog_services{{status="{status}"}} '
                    f"{by_status.get(status, 0)}"
                )
            lines.append("# TYPE cp_catalog_snapshot_enabled gauge")
            lines.append(
                f"cp_catalog_snapshot_enabled "
                f"{1 if self.snapshot_path else 0}"
            )
            return Response(
                200, ("\n".join(lines) + "\n").encode(),
                content_type="text/plain; version=0.0.4",
            )
        if req.method == "GET" and req.path.startswith("/v1/health/service/"):
            name = urllib.parse.unquote(req.path.rsplit("/", 1)[-1])
            passing_only = req.query.get("passing", ["0"])[0] not in ("0", "")
            tag = req.query.get("tag", [""])[0]
            dc = req.query.get("dc", [""])[0]
            if dc and dc != self.dc:
                # this catalog serves exactly one datacenter
                return Response(
                    200, b"[]", content_type="application/json"
                )
            now = time.time()
            out: List[Dict[str, Any]] = []
            for entry in sorted(self._entries.values(), key=lambda e: e.id):
                if entry.name != name:
                    continue
                status = entry.effective_status(now)
                if passing_only and status != "passing":
                    continue
                if tag and tag not in entry.tags:
                    continue
                out.append(
                    {
                        "Node": {"Node": "catalog", "Address": entry.address},
                        "Service": {
                            "ID": entry.id,
                            "Service": entry.name,
                            "Address": entry.address,
                            "Port": entry.port,
                            "Tags": entry.tags,
                        },
                        "Checks": [
                            {
                                "CheckID": f"service:{entry.id}",
                                "Status": status,
                            }
                        ],
                    }
                )
            return Response(
                200, json.dumps(out).encode(), content_type="application/json"
            )
        return None
