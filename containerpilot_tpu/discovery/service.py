"""ServiceDefinition: how one job talks to the discovery catalog.

Capability parity with the reference (reference: discovery/service.go):
lazy registration on first heartbeat, TTL refresh writes, initial-status
registration, deregistration on stop, and maintenance = deregister.
"""
from __future__ import annotations

import logging
from typing import Optional

from .backend import Backend, DiscoveryError, ServiceRegistration

log = logging.getLogger("containerpilot.discovery")

HEALTH_PASSING = "passing"
HEALTH_WARNING = "warning"
HEALTH_CRITICAL = "critical"


class ServiceDefinition:
    """A job's live registration state against a Backend."""

    def __init__(self, registration: ServiceRegistration, backend: Backend) -> None:
        self.registration = registration
        self.backend = backend
        self.was_registered = False

    @property
    def id(self) -> str:
        return self.registration.id

    @property
    def name(self) -> str:
        return self.registration.name

    @property
    def initial_status(self) -> str:
        return self.registration.initial_status

    def send_heartbeat(self) -> None:
        """Lazy-register then refresh the TTL check
        (reference: discovery/service.go:41-51)."""
        self._register(HEALTH_PASSING)
        check_id = f"service:{self.id}"
        try:
            self.backend.update_ttl(check_id, "ok", "pass")
        except DiscoveryError as exc:
            log.warning("service update TTL failed: %s", exc)

    def register_with_initial_status(self) -> None:
        """Register once with the configured initial status
        (reference: discovery/service.go:54-76)."""
        if self.was_registered:
            return
        status = {
            "passing": HEALTH_PASSING,
            "warning": HEALTH_WARNING,
            "critical": HEALTH_CRITICAL,
        }.get(self.initial_status, "")
        log.info(
            "registering service %s with initial status %r", self.name, status
        )
        self._register(status)

    def _register(self, status: str) -> None:
        if self.was_registered:
            return
        try:
            self.backend.service_register(self.registration, status)
        except DiscoveryError as exc:
            log.warning("service registration failed: %s", exc)
            return
        log.info("service registered: %s", self.name)
        self.was_registered = True

    def deregister(self) -> None:
        """Remove from the catalog (reference: discovery/service.go:28-33).

        Deviation from the reference: ``was_registered`` resets here so
        the next heartbeat lazily re-registers. The reference leaves the
        flag set, so a service that exits maintenance mode keeps writing
        TTL updates against a check it deleted — it never reappears in
        the catalog until a config reload.
        """
        log.debug("deregistering: %s", self.id)
        try:
            self.backend.service_deregister(self.id)
        except DiscoveryError as exc:
            log.info("deregistering failed: %s", exc)
        finally:
            self.was_registered = False

    def mark_for_maintenance(self) -> None:
        """Maintenance mode = drop out of the catalog
        (reference: discovery/service.go:36-38)."""
        self.deregister()
