"""ServiceDefinition: how one job talks to the discovery catalog.

Capability parity with the reference (reference: discovery/service.go):
lazy registration on first heartbeat, TTL refresh writes, initial-status
registration, deregistration on stop, and maintenance = deregister.

Catalog I/O runs on a small shared thread pool, never on the
supervisor's event loop: the reference runs each actor in its own
goroutine so a slow Consul call only stalls that actor — here a
blocking HTTP call on the single asyncio loop would stall *every*
actor's timers and the control socket. Per-service operations execute
in strict submission (FIFO) order through a private drain queue, so a
heartbeat submitted before a deregister can never re-register the
service afterwards, regardless of pool scheduling. Heartbeats dedup
against a non-empty queue (a hung catalog can't build a backlog);
``deregister`` always enqueues and returns a future that async callers
(job cleanup) await so the stopped event still orders after
deregistration.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Deque, Optional, Tuple

from .backend import Backend, DiscoveryError, ServiceRegistration

log = logging.getLogger("containerpilot.discovery")

HEALTH_PASSING = "passing"
HEALTH_WARNING = "warning"
HEALTH_CRITICAL = "critical"

# shared across all services; catalog calls are tiny and infrequent
_EXECUTOR = ThreadPoolExecutor(max_workers=2, thread_name_prefix="discovery")


class ServiceDefinition:
    """A job's live registration state against a Backend."""

    def __init__(self, registration: ServiceRegistration, backend: Backend) -> None:
        self.registration = registration
        self.backend = backend
        self.was_registered = False
        self._lock = threading.Lock()
        self._pending: Deque[Tuple[Callable[[], None], Future]] = deque()
        self._draining = False

    @property
    def id(self) -> str:
        return self.registration.id

    @property
    def name(self) -> str:
        return self.registration.name

    @property
    def initial_status(self) -> str:
        return self.registration.initial_status

    # -- FIFO off-loop execution ------------------------------------------

    def _enqueue(
        self, fn: Callable[[], None], *, dedup: bool
    ) -> Optional[Future]:
        """Queue a catalog op; per-service ops run in submission order.

        ``dedup=True`` skips the submit when ops are already queued or
        running (heartbeats must not pile up behind a hung catalog).
        """
        with self._lock:
            if dedup and (self._pending or self._draining):
                log.debug("%s: catalog op in flight, skipping", self.id)
                return None
            future: Future = Future()
            self._pending.append((fn, future))
            if not self._draining:
                self._draining = True
                _EXECUTOR.submit(self._drain)
        return future

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    self._draining = False
                    return
                fn, future = self._pending.popleft()
            try:
                fn()
                future.set_result(None)
            except Exception as exc:  # noqa: BLE001 - surfaced via future
                log.warning("%s: catalog op failed: %s", self.id, exc)
                future.set_exception(exc)

    # -- operations --------------------------------------------------------

    def send_heartbeat(self, output: str = "ok") -> Optional[Future]:
        """Lazy-register then refresh the TTL check
        (reference: discovery/service.go:41-51). ``output`` rides the
        check record (consul's check Output field; the file catalog's
        ``notes``) — fleet members put slot occupancy there."""

        def work() -> None:
            self._register_sync(HEALTH_PASSING)
            try:
                self.backend.update_ttl(
                    f"service:{self.id}", output, "pass"
                )
            except DiscoveryError as exc:
                log.warning("service update TTL failed: %s", exc)
                # self-heal from catalog state loss (restarted agent,
                # wiped store): assume our registration is gone and
                # lazily re-register on the next heartbeat. The
                # reference warns forever and never recovers.
                self.was_registered = False

        return self._enqueue(work, dedup=True)

    def register_with_initial_status(self) -> Optional[Future]:
        """Register once with the configured initial status
        (reference: discovery/service.go:54-76)."""
        if self.was_registered:
            return None
        status = {
            "passing": HEALTH_PASSING,
            "warning": HEALTH_WARNING,
            "critical": HEALTH_CRITICAL,
        }.get(self.initial_status, "")

        def work() -> None:
            log.info(
                "registering service %s with initial status %r",
                self.name,
                status,
            )
            self._register_sync(status)

        return self._enqueue(work, dedup=True)

    def _register_sync(self, status: str) -> None:
        if self.was_registered:
            return
        try:
            self.backend.service_register(self.registration, status)
        except DiscoveryError as exc:
            log.warning("service registration failed: %s", exc)
            return
        log.info("service registered: %s", self.name)
        self.was_registered = True

    def deregister(self) -> Optional[Future]:
        """Remove from the catalog (reference: discovery/service.go:28-33).

        Deviation from the reference: ``was_registered`` resets so the
        next heartbeat lazily re-registers — the reference leaves the
        flag set, so a service exiting maintenance mode keeps writing
        TTL updates against a check it deleted and never reappears in
        the catalog until a config reload.
        """

        def work() -> None:
            self.was_registered = False
            log.debug("deregistering: %s", self.id)
            try:
                self.backend.service_deregister(self.id)
            except DiscoveryError as exc:
                log.info("deregistering failed: %s", exc)

        # never dedup-skipped: cleanup must always deregister
        return self._enqueue(work, dedup=False)

    def mark_for_maintenance(self) -> None:
        """Maintenance mode = drop out of the catalog
        (reference: discovery/service.go:36-38)."""
        self.deregister()
