"""ServiceDefinition: how one job talks to the discovery catalog.

Capability parity with the reference (reference: discovery/service.go):
lazy registration on first heartbeat, TTL refresh writes, initial-status
registration, deregistration on stop, and maintenance = deregister.

Catalog I/O runs on a small shared thread pool, never on the
supervisor's event loop: the reference runs each actor in its own
goroutine so a slow Consul call only stalls that actor — here a
blocking HTTP call on the single asyncio loop would stall *every*
actor's timers and the control socket, so backend calls are submitted
to the pool (with in-flight dedup so a hung catalog can't queue an
unbounded backlog). ``deregister`` returns a future; async callers
(job cleanup) await it so the stopped event still orders after
deregistration.
"""
from __future__ import annotations

import logging
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

from .backend import Backend, DiscoveryError, ServiceRegistration

log = logging.getLogger("containerpilot.discovery")

HEALTH_PASSING = "passing"
HEALTH_WARNING = "warning"
HEALTH_CRITICAL = "critical"

# shared across all services; catalog calls are tiny and infrequent
_EXECUTOR = ThreadPoolExecutor(max_workers=2, thread_name_prefix="discovery")


class ServiceDefinition:
    """A job's live registration state against a Backend."""

    def __init__(self, registration: ServiceRegistration, backend: Backend) -> None:
        self.registration = registration
        self.backend = backend
        self.was_registered = False
        self._inflight: Optional[Future] = None

    @property
    def id(self) -> str:
        return self.registration.id

    @property
    def name(self) -> str:
        return self.registration.name

    @property
    def initial_status(self) -> str:
        return self.registration.initial_status

    # -- threading plumbing ----------------------------------------------

    def _submit(self, fn: Callable[[], None]) -> Optional[Future]:
        """Run a catalog call off-loop; skip if the previous one is
        still in flight (a hung catalog must not queue a backlog)."""
        if self._inflight is not None and not self._inflight.done():
            log.debug("%s: catalog call still in flight, skipping", self.id)
            return None
        future = _EXECUTOR.submit(fn)
        self._inflight = future
        return future

    # -- operations --------------------------------------------------------

    def send_heartbeat(self) -> Optional[Future]:
        """Lazy-register then refresh the TTL check, off-loop
        (reference: discovery/service.go:41-51)."""

        def work() -> None:
            self._register_sync(HEALTH_PASSING)
            try:
                self.backend.update_ttl(f"service:{self.id}", "ok", "pass")
            except DiscoveryError as exc:
                log.warning("service update TTL failed: %s", exc)

        return self._submit(work)

    def register_with_initial_status(self) -> Optional[Future]:
        """Register once with the configured initial status
        (reference: discovery/service.go:54-76)."""
        if self.was_registered:
            return None
        status = {
            "passing": HEALTH_PASSING,
            "warning": HEALTH_WARNING,
            "critical": HEALTH_CRITICAL,
        }.get(self.initial_status, "")

        def work() -> None:
            log.info(
                "registering service %s with initial status %r",
                self.name,
                status,
            )
            self._register_sync(status)

        return self._submit(work)

    def _register_sync(self, status: str) -> None:
        if self.was_registered:
            return
        try:
            self.backend.service_register(self.registration, status)
        except DiscoveryError as exc:
            log.warning("service registration failed: %s", exc)
            return
        log.info("service registered: %s", self.name)
        self.was_registered = True

    def deregister(self) -> Optional[Future]:
        """Remove from the catalog (reference: discovery/service.go:28-33).

        Deviation from the reference: ``was_registered`` resets so the
        next heartbeat lazily re-registers — the reference leaves the
        flag set, so a service exiting maintenance mode keeps writing
        TTL updates against a check it deleted and never reappears in
        the catalog until a config reload.
        """
        # flip the flag immediately so a concurrently-queued heartbeat
        # can't observe stale registration state
        self.was_registered = False

        def work() -> None:
            log.debug("deregistering: %s", self.id)
            try:
                self.backend.service_deregister(self.id)
            except DiscoveryError as exc:
                log.info("deregistering failed: %s", exc)

        # never dedup-skipped: cleanup must always deregister, even if
        # a heartbeat is mid-flight
        future = _EXECUTOR.submit(work)
        self._inflight = future
        return future

    def mark_for_maintenance(self) -> None:
        """Maintenance mode = drop out of the catalog
        (reference: discovery/service.go:36-38)."""
        self.deregister()
