"""Discovery backend construction from config.

Capability parity with the reference's discovery config
(reference: discovery/config.go:29-61 — URI or map forms, CONSUL_*
environment overrides), extended with TPU-pod-friendly backends:

    consul: "consul:8500"                  -> ConsulBackend
    consul: {address: ..., scheme: ...}    -> ConsulBackend
    consul: "file:/shared/catalog"         -> FileCatalogBackend
    consul: "none"                         -> NoopBackend (catalog-free)
    (section absent)                       -> no discovery (None)
"""
from __future__ import annotations

import os
from typing import Any, Optional

from .backend import Backend
from .consul import ConsulBackend
from .filecatalog import FileCatalogBackend
from .noop import NoopBackend


class DiscoveryConfigError(ValueError):
    pass


def new_backend(raw: Any) -> Optional[Backend]:
    if raw is None:
        return None
    if isinstance(raw, str):
        value = raw.strip()
        if value == "none":
            return NoopBackend()
        if value.startswith("file:"):
            return FileCatalogBackend(value[len("file:"):])
        return ConsulBackend.from_uri(value)
    if isinstance(raw, dict):
        return ConsulBackend.from_map(raw)
    raise DiscoveryConfigError(f"unparseable 'consul' config: {raw!r}")
