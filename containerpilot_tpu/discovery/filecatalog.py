"""File-based service catalog for TPU-VM pods.

TPU-native analog of the reference's Consul backend for deployments
without a catalog server: hosts in a TPU pod slice (or any fleet with a
shared filesystem — NFS, GCS-fuse, or a local dir for single-host) use
a directory as the catalog. Each registered service instance is one
JSON file carrying address/port/TTL state; TTL expiry marks instances
critical exactly like Consul's TTL checks
(reference behavior: discovery/consul.go, discovery/service.go:93-110).

Layout:  <root>/services/<service-name>/<instance-id>.json

Change detection mirrors the reference's compare-and-swap of the
last-seen instance list (reference: discovery/consul.go:102-125).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from .backend import (
    Backend,
    DiscoveryError,
    ServiceInstance,
    ServiceRegistration,
)


class FileCatalogBackend(Backend):
    def __init__(self, root: str) -> None:
        self.root = root
        self._services_dir = os.path.join(root, "services")
        os.makedirs(self._services_dir, exist_ok=True)
        # last-seen healthy instance set per watched service
        self._last_seen: Dict[str, List[ServiceInstance]] = {}

    # -- paths ----------------------------------------------------------

    def _service_dir(self, name: str) -> str:
        return os.path.join(self._services_dir, name)

    def _instance_path(self, name: str, instance_id: str) -> str:
        return os.path.join(self._service_dir(name), f"{instance_id}.json")

    def _find_instance_file(self, instance_id: str) -> Optional[str]:
        try:
            names = os.listdir(self._services_dir)
        except OSError as exc:
            raise DiscoveryError(str(exc)) from None
        for name in names:
            path = self._instance_path(name, instance_id)
            if os.path.exists(path):
                return path
        return None

    # -- Backend interface ----------------------------------------------

    def service_register(
        self, registration: ServiceRegistration, status: str = ""
    ) -> None:
        record = {
            "id": registration.id,
            "name": registration.name,
            "address": registration.address,
            "port": registration.port,
            "tags": registration.tags,
            "ttl": registration.ttl,
            "status": status or "critical",
            # an empty status registers as unchecked-but-present; TTL
            # expiry is what flips healthy -> critical
            "expires": time.time() + registration.ttl
            if status == "passing"
            else 0.0,
        }
        sdir = self._service_dir(registration.name)
        try:
            os.makedirs(sdir, exist_ok=True)
            tmp = self._instance_path(registration.name, registration.id) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f)
            os.replace(tmp, self._instance_path(registration.name, registration.id))
        except OSError as exc:
            raise DiscoveryError(str(exc)) from None

    def service_deregister(self, service_id: str) -> None:
        path = self._find_instance_file(service_id)
        if path is None:
            return
        try:
            os.unlink(path)
        except OSError as exc:
            raise DiscoveryError(str(exc)) from None

    def update_ttl(self, check_id: str, output: str, status: str) -> None:
        # check ids look like "service:<instance-id>" (reference:
        # discovery/service.go:45)
        instance_id = check_id.split(":", 1)[-1]
        path = self._find_instance_file(instance_id)
        if path is None:
            raise DiscoveryError(f"unknown check {check_id!r}")
        try:
            with open(path, "r", encoding="utf-8") as f:
                record = json.load(f)
            record["status"] = "passing" if status == "pass" else status
            record["expires"] = time.time() + float(record.get("ttl") or 0)
            # the TTL check's output (e.g. "ok occ=0.50" from fleet
            # members): a coarse load signal readers can surface
            record["notes"] = output
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f)
            os.replace(tmp, path)
        except (OSError, ValueError) as exc:
            raise DiscoveryError(str(exc)) from None

    def _healthy_instances(self, service_name: str, tag: str) -> List[ServiceInstance]:
        sdir = self._service_dir(service_name)
        if not os.path.isdir(sdir):
            return []
        now = time.time()
        out: List[ServiceInstance] = []
        for fname in sorted(os.listdir(sdir)):
            # only settled records. This also skips writer scratch
            # files (`<id>.json.tmp`, left behind by a crash between
            # write and os.replace): they don't end in ".json"
            if not fname.endswith(".json"):
                continue
            # a torn/partial write (concurrent writer on NFS, killed
            # host) or a malformed record is CRITICAL — skipped from
            # the healthy set — never an exception that kills the
            # whole listing for every healthy peer next to it
            try:
                with open(os.path.join(sdir, fname), encoding="utf-8") as f:
                    record = json.load(f)
                if not isinstance(record, dict):
                    continue
                instance = ServiceInstance(
                    id=record["id"],
                    name=record["name"],
                    address=str(record.get("address") or ""),
                    port=int(record.get("port") or 0),
                    notes=str(record.get("notes") or ""),
                )
                healthy = (
                    record.get("status") == "passing"
                    and float(record.get("expires") or 0) >= now
                )
                tags = record.get("tags") or []
            except (OSError, ValueError, KeyError, TypeError):
                continue
            if not healthy:
                continue
            if tag and (not isinstance(tags, list) or tag not in tags):
                continue
            out.append(instance)
        return out

    def check_for_upstream_changes(
        self, service_name: str, tag: str = "", dc: str = ""
    ) -> Tuple[bool, bool]:
        instances = self._healthy_instances(service_name, tag)
        last = self._last_seen.get(service_name)
        did_change = last is not None and last != instances
        if last is None and instances:
            did_change = True  # first sighting of a healthy upstream
        self._last_seen[service_name] = instances
        return did_change, bool(instances)

    def instances(self, service_name: str, tag: str = "") -> List[ServiceInstance]:
        return self._healthy_instances(service_name, tag)
