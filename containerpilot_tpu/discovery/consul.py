"""Consul discovery backend over the raw HTTP API.

Capability parity with the reference's Consul backend
(reference: discovery/consul.go, discovery/config.go) without the
vendored client library: the four agent/health endpoints the supervisor
needs, URI/map config with ``CONSUL_HTTP_ADDR`` / ``CONSUL_HTTP_SSL`` /
``CONSUL_HTTP_TOKEN`` environment overrides
(reference: discovery/config.go:29-61), per-watch caching of the
last-seen instance list with compare-for-change
(reference: discovery/consul.go:102-125), and a Prometheus gauge of
watched instance counts (reference: discovery/consul.go:16-22).

Catalog calls ride PERSISTENT keep-alive connections, one per thread
(heartbeats run on the discovery FIFO thread, watch/gateway polls on a
small poll executor — each keeps its own warm connection to the
agent): TTL refreshes every ttl/2 seconds and membership polls every
interval no longer dial per call. A connection the agent closed while
idle is detected before any response byte and redialed transparently
once; agents that answer ``Connection: close`` (or any non-keep-alive
proxy in front of one) degrade gracefully to dial-per-call.
"""
from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from ..utils.httpclient import keepalive_request
from .backend import (
    Backend,
    DiscoveryError,
    ServiceInstance,
    ServiceRegistration,
)

log = logging.getLogger("containerpilot.discovery")

try:
    from prometheus_client import Gauge, REGISTRY

    def _make_gauge() -> Optional["Gauge"]:
        try:
            return Gauge(
                "containerpilot_watch_instances",
                "Count of instances seen for each watched service",
                ["service"],
            )
        except ValueError:
            return REGISTRY._names_to_collectors.get(  # noqa: SLF001
                "containerpilot_watch_instances"
            )

    _INSTANCE_GAUGE = _make_gauge()
except Exception:  # pragma: no cover
    _INSTANCE_GAUGE = None


class ConsulBackend(Backend):
    def __init__(
        self,
        address: str = "localhost:8500",
        scheme: str = "http",
        token: str = "",
        timeout: float = 10.0,
    ) -> None:
        self.address = address
        self.scheme = scheme
        self.token = token
        self.timeout = timeout
        self._last_seen: Dict[str, List[ServiceInstance]] = {}
        # one persistent agent connection PER THREAD:
        # http.client.HTTPConnection is not thread-safe, and catalog
        # traffic comes from a handful of long-lived threads (the
        # discovery FIFO drain, the poll executor) that each get to
        # keep their own warm connection
        self._local = threading.local()

    # -- construction ---------------------------------------------------

    @classmethod
    def from_uri(cls, uri: str) -> "ConsulBackend":
        scheme = "http"
        address = uri
        if "://" in uri:
            scheme, address = uri.split("://", 1)
        return cls._with_env_overrides(address=address, scheme=scheme)

    @classmethod
    def from_map(cls, raw: Dict[str, Any]) -> "ConsulBackend":
        return cls._with_env_overrides(
            address=str(raw.get("address", "localhost:8500")),
            scheme=str(raw.get("scheme", "http")),
            token=str(raw.get("token", "")),
        )

    @classmethod
    def _with_env_overrides(
        cls, address: str, scheme: str, token: str = ""
    ) -> "ConsulBackend":
        address = os.environ.get("CONSUL_HTTP_ADDR", address)
        if os.environ.get("CONSUL_HTTP_SSL", "").lower() in ("1", "true"):
            scheme = "https"
        token = os.environ.get("CONSUL_HTTP_TOKEN", token)
        if "://" in address:
            scheme, address = address.split("://", 1)
        return cls(address=address, scheme=scheme, token=token)

    # -- HTTP plumbing --------------------------------------------------

    def _take_conn(self) -> Optional[http.client.HTTPConnection]:
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        return conn

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        self._local.conn = conn

    def _new_conn(self) -> http.client.HTTPConnection:
        cls = (
            http.client.HTTPSConnection
            if self.scheme == "https"
            else http.client.HTTPConnection
        )
        # http.client parses a "host:port" string itself
        return cls(self.address, timeout=self.timeout)

    def _request(
        self, method: str, path: str, body: Optional[Dict[str, Any]] = None
    ) -> Any:
        """One agent round trip over this thread's kept connection
        (utils/httpclient.py owns the redial discipline: a kept
        connection the agent reaped while idle fails before any
        response byte and is resent once on a fresh dial)."""
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Consul-Token"] = self.token
        try:
            status, payload = keepalive_request(
                self._take_conn, self._put_conn, self._new_conn,
                method, path, body=data, headers=headers,
            )
        except (OSError, http.client.HTTPException) as exc:
            raise DiscoveryError(
                f"consul {method} {path}: {exc}"
            ) from None
        if status >= 400:
            raise DiscoveryError(
                f"consul {method} {path}: {status} {payload[:200]!r}"
            )
        if not payload:
            return None
        try:
            return json.loads(payload)
        except ValueError:
            return None

    # -- Backend interface ----------------------------------------------

    def service_register(
        self, registration: ServiceRegistration, status: str = ""
    ) -> None:
        body: Dict[str, Any] = {
            "ID": registration.id,
            "Name": registration.name,
            "Tags": registration.tags,
            "Port": registration.port,
            "Address": registration.address,
            "EnableTagOverride": registration.enable_tag_override,
            "Check": {
                "TTL": f"{registration.ttl}s",
                "Notes": f"TTL for {registration.name} set by containerpilot",
            },
        }
        if status:
            body["Check"]["Status"] = status
        if registration.deregister_critical_service_after:
            body["Check"]["DeregisterCriticalServiceAfter"] = (
                registration.deregister_critical_service_after
            )
        self._request("PUT", "/v1/agent/service/register", body)

    def service_deregister(self, service_id: str) -> None:
        self._request(
            "PUT",
            "/v1/agent/service/deregister/"
            + urllib.parse.quote(service_id, safe=":"),
        )

    def update_ttl(self, check_id: str, output: str, status: str) -> None:
        # ":" stays raw — it is legal in a path segment and check ids are
        # "service:<id>" (the reference's client sends them unescaped)
        self._request(
            "PUT",
            "/v1/agent/check/update/" + urllib.parse.quote(check_id, safe=":"),
            {"Output": output, "Status": "passing" if status == "pass" else status},
        )

    def _health_service(
        self, service_name: str, tag: str, dc: str
    ) -> List[ServiceInstance]:
        query: List[Tuple[str, str]] = [("passing", "1")]
        if tag:
            query.append(("tag", tag))
        if dc:
            query.append(("dc", dc))
        path = (
            "/v1/health/service/"
            + urllib.parse.quote(service_name, safe=":")
            + "?"
            + urllib.parse.urlencode(query)
        )
        entries = self._request("GET", path) or []
        out: List[ServiceInstance] = []
        for entry in entries:
            svc = entry.get("Service", {})
            node = entry.get("Node", {})
            out.append(
                ServiceInstance(
                    id=svc.get("ID", ""),
                    name=svc.get("Service", service_name),
                    address=svc.get("Address") or node.get("Address", ""),
                    port=int(svc.get("Port") or 0),
                )
            )
        out.sort(key=lambda i: (i.id, i.address, i.port))
        return out

    def check_for_upstream_changes(
        self, service_name: str, tag: str = "", dc: str = ""
    ) -> Tuple[bool, bool]:
        """Poll + compare-for-change (reference: discovery/consul.go:87-125)."""
        try:
            instances = self._health_service(service_name, tag, dc)
        except DiscoveryError as exc:
            log.warning("failed to query %s: %s", service_name, exc)
            return False, False
        if _INSTANCE_GAUGE is not None:
            try:
                _INSTANCE_GAUGE.labels(service=service_name).set(len(instances))
            except Exception:  # pragma: no cover — cpcheck: disable=CP-SWALLOW metrics must never break the poll
                pass
        last = self._last_seen.get(service_name)
        did_change = (last is not None and last != instances) or (
            last is None and bool(instances)
        )
        self._last_seen[service_name] = instances
        return did_change, bool(instances)

    def instances(self, service_name: str, tag: str = "") -> List[ServiceInstance]:
        try:
            return self._health_service(service_name, tag, "")
        except DiscoveryError:
            return []
