"""No-op discovery backend: a test double with a settable change signal.

Capability parity with the reference's mock backend
(reference: tests/mocks/discovery.go:6-41): ``val`` drives what
``check_for_upstream_changes`` reports, and a compare-against-last-seen
mimics real change detection. Shipped in the package (not just tests)
so the supervisor can run catalog-free ("consul: none" deployments).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .backend import Backend, ServiceInstance, ServiceRegistration


class NoopBackend(Backend):
    def __init__(self) -> None:
        self.val = False  # "is the upstream healthy right now?"
        self._last_val = False
        self.registered: Dict[str, ServiceRegistration] = {}
        self.ttl_updates: List[str] = []

    def check_for_upstream_changes(
        self, service_name: str, tag: str = "", dc: str = ""
    ) -> Tuple[bool, bool]:
        did_change = self.val != self._last_val
        self._last_val = self.val
        return did_change, self.val

    def update_ttl(self, check_id: str, output: str, status: str) -> None:
        self.ttl_updates.append(check_id)

    def service_register(
        self, registration: ServiceRegistration, status: str = ""
    ) -> None:
        self.registered[registration.id] = registration

    def service_deregister(self, service_id: str) -> None:
        self.registered.pop(service_id, None)

    def instances(self, service_name: str, tag: str = "") -> List[ServiceInstance]:
        return [
            ServiceInstance(r.id, r.name, r.address, r.port)
            for r in self.registered.values()
            if r.name == service_name
        ]
