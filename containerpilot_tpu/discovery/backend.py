"""Service-discovery backend interface.

Capability parity with the reference's Backend interface
(reference: discovery/discovery.go:8-14) — five methods: upstream
change detection, TTL check updates, and service register/deregister.

Backends provided in-tree:

- ``ConsulBackend`` (consul.py): the Consul HTTP API, for deployments
  with a real catalog.
- ``FileCatalogBackend`` (filecatalog.py): a shared-filesystem catalog
  for TPU-VM pods, where hosts in a pod slice see a common NFS/GCS-fuse
  mount and no Consul is available.
- ``NoopBackend`` (noop.py): test double with a settable change signal.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class ServiceRegistration:
    """Everything a backend needs to advertise one service instance
    (reference: consul api.AgentServiceRegistration usage,
    discovery/service.go:93-110)."""

    id: str
    name: str
    port: int = 0
    ttl: int = 0
    tags: List[str] = field(default_factory=list)
    address: str = ""
    initial_status: str = ""
    enable_tag_override: bool = False
    deregister_critical_service_after: str = ""


@dataclass(frozen=True)
class ServiceInstance:
    """One healthy instance of an upstream service as seen in the
    catalog (reference: consul api.ServiceEntry subset used by
    discovery/consul.go:102-125)."""

    id: str
    name: str
    address: str
    port: int
    #: last TTL-check output ("ok occ=0.50" from fleet members):
    #: a coarse, TTL-fresh load hint; empty when the backend doesn't
    #: surface check output
    notes: str = ""


class Backend(abc.ABC):
    """The discovery catalog interface (reference: discovery/discovery.go:8-14)."""

    @abc.abstractmethod
    def check_for_upstream_changes(
        self, service_name: str, tag: str = "", dc: str = ""
    ) -> Tuple[bool, bool]:
        """Poll the catalog for healthy instances of ``service_name``.

        Returns (did_change, is_healthy): whether membership changed
        since the last poll, and whether at least one healthy instance
        exists (reference: discovery/consul.go:87-110).
        """

    @abc.abstractmethod
    def update_ttl(self, check_id: str, output: str, status: str) -> None:
        """Refresh a TTL health check (reference: discovery/consul.go)."""

    @abc.abstractmethod
    def service_register(
        self, registration: ServiceRegistration, status: str = ""
    ) -> None:
        """Register a service instance plus its TTL check."""

    @abc.abstractmethod
    def service_deregister(self, service_id: str) -> None:
        """Remove a service instance from the catalog."""

    def instances(self, service_name: str, tag: str = "") -> List[ServiceInstance]:
        """Current healthy instances (used by /status and templating)."""
        return []


class DiscoveryError(RuntimeError):
    """A backend operation failed (network, catalog rejection, ...)."""
