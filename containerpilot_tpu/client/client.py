"""HTTP client for the control plane's unix socket.

Capability parity with the reference (reference: client/client.go):
one verb per control endpoint, used by the CLI subcommands and usable
as an SDK by supervised workloads (e.g. a JAX training loop POSTing
step-rate metrics).
"""
from __future__ import annotations

import errno
import http.client
import json
import socket
import time
from typing import Any, Dict, Optional


class ControlClientError(RuntimeError):
    pass


# connect-phase failures worth retrying briefly: the socket file does
# not exist yet (supervisor still booting), nothing is accepting on it
# yet, or the kernel pushed back transiently. All three happen on the
# FIRST control call after `containerpilot start` and nothing has been
# sent when they fire, so a retry cannot double-apply a request.
_TRANSIENT_ERRNOS = frozenset(
    {errno.ECONNREFUSED, errno.EAGAIN, errno.ENOENT, errno.EALREADY}
)


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 10.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class ControlClient:
    def __init__(
        self,
        socket_path: str,
        timeout: float = 10.0,
        retries: int = 3,
        retry_delay: float = 0.05,
    ) -> None:
        self.socket_path = socket_path
        self.timeout = timeout
        # >= 0 so _request's loop always makes at least one attempt
        # (its last iteration always returns or raises)
        self.retries = max(retries, 0)
        self.retry_delay = retry_delay

    def _request(
        self, method: str, path: str, body: Optional[Dict[str, Any]] = None
    ) -> str:
        """One control-plane round trip. Transient connect-phase
        socket errors (ECONNREFUSED/EAGAIN/ENOENT while the supervisor
        is still binding its socket) retry with short exponential
        backoff instead of failing the first control call after
        start; anything else surfaces immediately."""
        delay = self.retry_delay
        for attempt in range(self.retries + 1):
            conn = _UnixHTTPConnection(self.socket_path, self.timeout)
            try:
                payload = json.dumps(body) if body is not None else None
                headers = (
                    {"Content-Type": "application/json"} if payload else {}
                )
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = resp.read().decode("utf-8", "replace")
                if resp.status != 200:
                    raise ControlClientError(
                        f"{method} {path}: HTTP {resp.status}: {data.strip()}"
                    )
                return data
            except (OSError, http.client.HTTPException) as exc:
                transient = (
                    isinstance(exc, OSError)
                    and exc.errno in _TRANSIENT_ERRNOS
                )
                if transient and attempt < self.retries:
                    time.sleep(delay)
                    delay = min(delay * 2, 0.5)
                    continue
                raise ControlClientError(f"{method} {path}: {exc}") from None
            finally:
                conn.close()

    def reload(self) -> None:
        """POST /v3/reload (reference: client.go:45-52)."""
        self._request("POST", "/v3/reload")

    def set_maintenance(self, enable: bool) -> None:
        """POST /v3/maintenance/{enable,disable} (reference: client.go:56-68)."""
        verb = "enable" if enable else "disable"
        self._request("POST", f"/v3/maintenance/{verb}")

    def put_env(self, env: Dict[str, str]) -> None:
        """POST /v3/environ (reference: client.go:72-84)."""
        self._request("POST", "/v3/environ", env)

    def put_metric(self, metrics: Dict[str, Any]) -> None:
        """POST /v3/metric (reference: client.go:88-100)."""
        self._request("POST", "/v3/metric", metrics)

    def get_ping(self) -> bool:
        """GET /v3/ping (reference: client.go:104-115)."""
        self._request("GET", "/v3/ping")
        return True

    def get_maintenance_status(self) -> bool:
        """GET /v3/maintenance/status: whether the supervisor is in
        maintenance mode right now (an extension over the reference's
        write-only maintenance verbs — drain runbooks need to confirm
        the flip actually landed)."""
        data = json.loads(self._request("GET", "/v3/maintenance/status"))
        return bool(data.get("maintenance"))

    def get_events(self) -> list:
        """GET /v3/events: the supervisor's recent-event ring (an
        observability extension over the reference's control API)."""
        return json.loads(self._request("GET", "/v3/events"))

    def get_tasks(self) -> list:
        """GET /v3/tasks: the live actor/timer/exec task table."""
        return json.loads(self._request("GET", "/v3/tasks"))
