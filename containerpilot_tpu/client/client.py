"""HTTP client for the control plane's unix socket.

Capability parity with the reference (reference: client/client.go):
one verb per control endpoint, used by the CLI subcommands and usable
as an SDK by supervised workloads (e.g. a JAX training loop POSTing
step-rate metrics).

The client keeps ONE unix-socket connection across verbs (the control
server speaks HTTP/1.1 keep-alive): an SDK posting a metric every
step no longer pays a dial per call. If the server reaped the idle
connection (restart, idle timeout), the next verb sees the close
before any response byte and transparently redials once — the server
answered nothing, so nothing was applied. ``close()`` drops the kept
connection; ``keep_alive=False`` restores dial-per-verb.
"""
from __future__ import annotations

import errno
import http.client
import json
import socket
import threading
import time
from typing import Any, Dict, Optional

from ..utils.httpclient import keepalive_request


class ControlClientError(RuntimeError):
    pass


# connect-phase failures worth retrying briefly: the socket file does
# not exist yet (supervisor still booting), nothing is accepting on it
# yet, or the kernel pushed back transiently. All three happen on the
# FIRST control call after `containerpilot start` and nothing has been
# sent when they fire, so a retry cannot double-apply a request.
_TRANSIENT_ERRNOS = frozenset(
    {errno.ECONNREFUSED, errno.EAGAIN, errno.ENOENT, errno.EALREADY}
)


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 10.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class ControlClient:
    def __init__(
        self,
        socket_path: str,
        timeout: float = 10.0,
        retries: int = 3,
        retry_delay: float = 0.05,
        keep_alive: bool = True,
    ) -> None:
        self.socket_path = socket_path
        self.timeout = timeout
        # >= 0 so _request's loop always makes at least one attempt
        # (its last iteration always returns or raises)
        self.retries = max(retries, 0)
        self.retry_delay = retry_delay
        self.keep_alive = keep_alive
        # the kept connection is taken/put under a lock so the client
        # stays thread-safe (each verb previously built a private
        # connection); concurrent verbs simply dial extra connections
        # and only one is kept
        self._conn: Optional[_UnixHTTPConnection] = None
        self._conn_lock = threading.Lock()

    def _take_conn(self) -> Optional[_UnixHTTPConnection]:
        with self._conn_lock:
            conn, self._conn = self._conn, None
        return conn

    def _put_conn(self, conn: _UnixHTTPConnection) -> None:
        with self._conn_lock:
            if self._conn is None:
                self._conn = conn
                return
        conn.close()

    def close(self) -> None:
        """Drop the kept connection (idempotent; the next verb
        redials)."""
        conn = self._take_conn()
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def __enter__(self) -> "ControlClient":
        return self

    def __exit__(self, *_exc) -> None:
        self.close()

    def _request(
        self, method: str, path: str, body: Optional[Dict[str, Any]] = None
    ) -> str:
        """One control-plane round trip over the kept connection
        (utils/httpclient.py owns the redial discipline: a kept
        connection that failed before any response byte is resent
        once on a fresh dial; anything after response bytes is NOT —
        the server may have processed the verb).

        Transient connect-phase socket errors (ECONNREFUSED/EAGAIN/
        ENOENT while the supervisor is still binding its socket) retry
        with short exponential backoff instead of failing the first
        control call after start; anything else surfaces
        immediately."""
        payload = json.dumps(body) if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        put = self._put_conn if self.keep_alive else (
            lambda conn: conn.close()
        )
        delay = self.retry_delay
        for attempt in range(self.retries + 1):
            try:
                status, data = keepalive_request(
                    self._take_conn,
                    put,
                    lambda: _UnixHTTPConnection(
                        self.socket_path, self.timeout
                    ),
                    method, path, body=payload, headers=headers,
                )
            except (OSError, http.client.HTTPException) as exc:
                transient = (
                    isinstance(exc, OSError)
                    and exc.errno in _TRANSIENT_ERRNOS
                )
                if transient and attempt < self.retries:
                    time.sleep(delay)
                    delay = min(delay * 2, 0.5)
                    continue
                raise ControlClientError(f"{method} {path}: {exc}") from None
            text = data.decode("utf-8", "replace")
            if status != 200:
                raise ControlClientError(
                    f"{method} {path}: HTTP {status}: {text.strip()}"
                )
            return text

    def reload(self) -> None:
        """POST /v3/reload (reference: client.go:45-52)."""
        self._request("POST", "/v3/reload")

    def set_maintenance(self, enable: bool) -> None:
        """POST /v3/maintenance/{enable,disable} (reference: client.go:56-68)."""
        verb = "enable" if enable else "disable"
        self._request("POST", f"/v3/maintenance/{verb}")

    def put_env(self, env: Dict[str, str]) -> None:
        """POST /v3/environ (reference: client.go:72-84)."""
        self._request("POST", "/v3/environ", env)

    def put_metric(self, metrics: Dict[str, Any]) -> None:
        """POST /v3/metric (reference: client.go:88-100)."""
        self._request("POST", "/v3/metric", metrics)

    def get_ping(self) -> bool:
        """GET /v3/ping (reference: client.go:104-115)."""
        self._request("GET", "/v3/ping")
        return True

    def get_maintenance_status(self) -> bool:
        """GET /v3/maintenance/status: whether the supervisor is in
        maintenance mode right now (an extension over the reference's
        write-only maintenance verbs — drain runbooks need to confirm
        the flip actually landed)."""
        data = json.loads(self._request("GET", "/v3/maintenance/status"))
        return bool(data.get("maintenance"))

    def get_events(self) -> list:
        """GET /v3/events: the supervisor's recent-event ring (an
        observability extension over the reference's control API)."""
        return json.loads(self._request("GET", "/v3/events"))

    def get_tasks(self) -> list:
        """GET /v3/tasks: the live actor/timer/exec task table."""
        return json.loads(self._request("GET", "/v3/tasks"))
