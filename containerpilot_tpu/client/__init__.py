"""Control-plane client SDK (reference: client/ package)."""
from .client import ControlClient, ControlClientError

__all__ = ["ControlClient", "ControlClientError"]
