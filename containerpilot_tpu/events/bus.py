"""The in-process event bus: synchronous fan-out pub/sub.

Capability parity with the reference supervisor's bus
(reference: events/bus.go). Semantics preserved:

- ``publish`` fans an event out to every subscriber synchronously,
  under a lock, in subscription order (reference: events/bus.go:125-140).
- Actors ``register`` before starting their loop and ``unregister`` when
  done; the app's lifetime is ``await bus.wait()``, which completes when
  the registered-actor count drops to zero and returns the reload flag
  (reference: events/bus.go:97-122,150-170).
- A small ring buffer of recent events supports event-sequence
  assertions in tests (reference: events/bus.go:34-54,75).
- ``shutdown`` publishes GLOBAL_SHUTDOWN; ``set_reload_flag`` marks the
  next ``wait`` return as a reload rather than a stop.

Design note (TPU-host idiom): the supervisor runs a single asyncio event
loop — the analogue of the reference pinning itself to one OS thread so
it never contends with the supervised JAX workload for host cores.
Fan-out delivers into per-actor ``asyncio.Queue`` mailboxes, which are
NOT thread-safe off the loop, so ``publish`` from a foreign thread is
routed onto the bus's home loop via ``call_soon_threadsafe`` (the home
loop is remembered the first time subscribe/register/publish runs on a
loop thread). In-tree publishers are all loop-resident; the routing
exists for embedding scenarios.
"""
from __future__ import annotations

import asyncio
import logging
import threading
from collections import deque
from typing import TYPE_CHECKING, Deque, List, Optional

from .events import GLOBAL_SHUTDOWN, Event

if TYPE_CHECKING:  # pragma: no cover
    from .subscriber import Subscriber

log = logging.getLogger("containerpilot.events")

# Ring-buffer size for DebugEvents-style assertions
# (reference: events/bus.go:75).
DEBUG_RING_SIZE = 10

try:  # metrics are optional at import time so the bus has no hard deps
    from prometheus_client import Counter, REGISTRY

    def _make_event_counter() -> Optional["Counter"]:
        try:
            return Counter(
                "containerpilot_events",
                "Total events published to the supervisor bus",
                ["code", "source"],
            )
        except ValueError:  # re-registration in the same process (reloads)
            collector = REGISTRY._names_to_collectors.get(  # noqa: SLF001
                "containerpilot_events"
            )
            return collector  # type: ignore[return-value]

    _EVENT_COUNTER = _make_event_counter()
except Exception:  # pragma: no cover - prometheus always present in-tree
    _EVENT_COUNTER = None


class EventBus:
    """Synchronous fan-out pub/sub with actor-lifetime tracking."""

    def __init__(self, ring_size: int = DEBUG_RING_SIZE) -> None:
        self._lock = threading.RLock()
        # Serializes fan-out WITHOUT coupling it to the state lock:
        # delivery-only, reentrant (a subscriber may publish from its
        # receive callback on the same thread), taken by no other code
        # path — so it cannot participate in a lock-order cycle with
        # application locks. It matters only on the direct off-loop
        # publish path (no home loop yet, or the loop already closed):
        # two foreign threads publishing concurrently must not
        # interleave unsynchronized mailbox puts.
        self._fanout_lock = threading.RLock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._subscribers: List["Subscriber"] = []
        self._registered: int = 0
        self._done = asyncio.Event()
        self._done.set()  # nothing registered yet
        self._reload_flag = False
        self._shutdown = False
        self._ring: Deque[Event] = deque(maxlen=ring_size)

    # -- subscription ---------------------------------------------------

    def _remember_home_loop(self) -> None:
        """Record the loop whose thread this call runs on, if any."""
        if self._loop is None:
            try:
                self._loop = asyncio.get_running_loop()
            except RuntimeError:
                pass

    def subscribe(self, subscriber: "Subscriber") -> None:
        with self._lock:
            self._remember_home_loop()
            self._subscribers.append(subscriber)

    def unsubscribe(self, subscriber: "Subscriber") -> None:
        with self._lock:
            try:
                self._subscribers.remove(subscriber)
            except ValueError:
                pass

    # -- actor lifetime (the WaitGroup analogue) ------------------------

    def register(self, _actor: object = None) -> None:
        """Count an actor into this bus generation's lifetime."""
        with self._lock:
            self._remember_home_loop()
            self._registered += 1
            self._done.clear()

    def unregister(self, _actor: object = None) -> None:
        with self._lock:
            self._registered -= 1
            if self._registered <= 0:
                self._registered = 0
                self._done.set()

    async def wait(self) -> bool:
        """Block until every registered actor has unregistered.

        Returns True when the generation ended because of a reload
        request, False for a plain shutdown
        (reference: events/bus.go:164-170 + core/app.go:146).
        """
        await self._done.wait()
        with self._lock:
            return self._reload_flag

    # -- publishing -----------------------------------------------------

    def publish(self, event: Event) -> None:
        """Fan the event out to all subscribers, synchronously, in order.

        A subscriber with a full mailbox gets the event dropped with an
        error log and a ``containerpilot_events_dropped`` counter bump
        rather than wedging the entire bus (the reference blocks in that
        case, which is a documented deadlock hazard —
        reference: events/bus.go:125-140, jobs/jobs.go:23).

        Calls from a thread other than the bus's home loop thread are
        re-routed onto the home loop: mailbox delivery touches
        ``asyncio.Queue`` internals that are not thread-safe off-loop.
        """
        home = self._loop
        if home is not None and not home.is_closed():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not home:
                home.call_soon_threadsafe(self._publish_on_loop, event)
                return
        self._publish_on_loop(event)

    def _publish_on_loop(self, event: Event) -> None:
        # Bookkeeping under the STATE lock, fan-out outside it:
        # delivering into subscriber mailboxes while holding the lock
        # that register/unregister/wait also take is the reference's
        # classic deadlock shape (a subscriber callback that touches
        # the bus re-enters it) — cpcheck's CP-LOCKPUB exists to keep
        # it out of this codebase, starting here. The snapshot keeps
        # subscription order; the delivery-only _fanout_lock keeps
        # concurrent direct publishes (off-loop fallback path) from
        # interleaving mailbox puts, as the old state lock did.
        with self._fanout_lock:
            with self._lock:
                self._remember_home_loop()
                log.debug("event: %s", event)
                self._ring.append(event)
                subscribers = list(self._subscribers)
            if _EVENT_COUNTER is not None:
                try:
                    _EVENT_COUNTER.labels(
                        code=event.code.value, source=event.source
                    ).inc()
                except Exception:  # pragma: no cover — cpcheck: disable=CP-SWALLOW metrics must never break publish
                    pass
            for sub in subscribers:
                sub.receive(event)  # cpcheck: disable=CP-LOCKPUB delivery-only reentrant lock, taken by no other code path

    def shutdown(self) -> None:
        """Broadcast GLOBAL_SHUTDOWN (reference: events/bus.go:156-160)."""
        with self._lock:
            self._shutdown = True
        self.publish(GLOBAL_SHUTDOWN)

    # -- reload flag ----------------------------------------------------

    def set_reload_flag(self) -> None:
        with self._lock:
            self._reload_flag = True

    def get_reload_flag(self) -> bool:
        with self._lock:
            return self._reload_flag

    # -- test/debug support ---------------------------------------------

    def debug_events(self) -> List[Event]:
        """Most-recent events, oldest first (reference: events/bus.go:34-54)."""
        with self._lock:
            return list(self._ring)
