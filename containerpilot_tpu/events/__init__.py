"""Event system: codes, bus, mailboxes, timers.

The supervisor is a set of actors on one in-process event bus; this
package is the keystone every other package builds on
(reference layer map: SURVEY.md §1, events/ row).
"""
from .events import (
    Event,
    EventCode,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_EXIT_MAINTENANCE,
    GLOBAL_SHUTDOWN,
    GLOBAL_STARTUP,
    NON_EVENT,
    QUIT_BY_CLOSE,
    QUIT_BY_TEST,
    code_from_string,
)
from .bus import DEBUG_RING_SIZE, EventBus
from .subscriber import MAILBOX_CAPACITY, EventHandler, Publisher, Subscriber
from .timer import cancel_timer, event_timeout, event_timer

__all__ = [
    "Event",
    "EventCode",
    "EventBus",
    "EventHandler",
    "Publisher",
    "Subscriber",
    "GLOBAL_STARTUP",
    "GLOBAL_SHUTDOWN",
    "GLOBAL_ENTER_MAINTENANCE",
    "GLOBAL_EXIT_MAINTENANCE",
    "NON_EVENT",
    "QUIT_BY_CLOSE",
    "QUIT_BY_TEST",
    "code_from_string",
    "event_timeout",
    "event_timer",
    "cancel_timer",
    "DEBUG_RING_SIZE",
    "MAILBOX_CAPACITY",
]
