"""Event codes and the Event value type.

Capability parity with the reference supervisor's event enum
(reference: events/events.go:10-54): sixteen event codes plus the
sentinel, value-semantics Event{code, source} pairs, and the well-known
global events used to kick off and tear down an actor generation.

Events are immutable value objects: two events with the same code and
source compare equal, which is what the job state machine's dispatch
switch relies on.
"""
from __future__ import annotations

import enum
from typing import NamedTuple


class EventCode(enum.Enum):
    """All event codes a supervisor actor can publish or receive."""

    NONE = "none"
    EXIT_SUCCESS = "exitSuccess"
    EXIT_FAILED = "exitFailed"
    STOPPING = "stopping"
    STOPPED = "stopped"
    STATUS_HEALTHY = "statusHealthy"
    STATUS_UNHEALTHY = "statusUnhealthy"
    STATUS_CHANGED = "statusChanged"
    TIMER_EXPIRED = "timerExpired"
    ENTER_MAINTENANCE = "enterMaintenance"
    EXIT_MAINTENANCE = "exitMaintenance"
    ERROR = "error"
    QUIT = "quit"
    METRIC = "metric"
    STARTUP = "startup"
    SHUTDOWN = "shutdown"
    SIGNAL = "signal"

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value


_CODE_BY_NAME = {c.value: c for c in EventCode}
# Accept the enum's symbolic names too (e.g. "EXIT_SUCCESS").
_CODE_BY_NAME.update({c.name: c for c in EventCode})
# Config-facing aliases (reference: events/events.go:52-84 — FromString
# maps "healthy"/"unhealthy"/"changed" onto the status codes).
_CODE_BY_NAME.update(
    {
        "healthy": EventCode.STATUS_HEALTHY,
        "unhealthy": EventCode.STATUS_UNHEALTHY,
        "changed": EventCode.STATUS_CHANGED,
    }
)


def code_from_string(name: str) -> EventCode:
    """Parse an event-code string (config files use the camelCase form).

    Reference behavior: unknown names are an error
    (reference: events/events.go:52-58).
    """
    try:
        return _CODE_BY_NAME[name]
    except KeyError:
        raise ValueError(f"invalid event code: {name!r}") from None


class Event(NamedTuple):
    """An immutable (code, source) pair flowing through the bus."""

    code: EventCode
    source: str

    def __str__(self) -> str:  # pragma: no cover - trivial
        return f"{self.code.value}:{self.source}"


# Well-known events (reference: events/events.go:41-50).
GLOBAL_STARTUP = Event(EventCode.STARTUP, "global")
GLOBAL_SHUTDOWN = Event(EventCode.SHUTDOWN, "global")
NON_EVENT = Event(EventCode.NONE, "")
QUIT_BY_CLOSE = Event(EventCode.QUIT, "closed")
# Test hook: lets unit tests stop actor loops without a global shutdown
# (reference: events/events.go:48).
QUIT_BY_TEST = Event(EventCode.QUIT, "test")
GLOBAL_ENTER_MAINTENANCE = Event(EventCode.ENTER_MAINTENANCE, "global")
GLOBAL_EXIT_MAINTENANCE = Event(EventCode.EXIT_MAINTENANCE, "global")
