"""Timers that inject TIMER_EXPIRED events onto the bus.

Capability parity with the reference's timer helpers
(reference: events/timer.go):

- ``event_timeout``: one-shot — after ``delay`` seconds publish
  ``{TIMER_EXPIRED, name}`` once (reference: events/timer.go:12-34).
- ``event_timer``: ticker — publish ``{TIMER_EXPIRED, name}`` every
  ``interval`` seconds until cancelled (reference: events/timer.go:40-68).

Both are asyncio tasks bound to a context; cancelling the context (or
the returned task) stops them. Publishing after the bus generation has
torn down is harmless — the reference handles the analogous
send-on-closed-channel race with a recover() (events/timer.go:26-30,49-54);
here a cancelled task simply stops ticking.

The reference silences debug logging for the internal heartbeat timer
(GH-556); we keep that behavior via the logger's level only.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from .bus import EventBus
from .events import Event, EventCode


def event_timeout(
    bus: EventBus, delay: float, name: str
) -> "asyncio.Task[None]":
    """One-shot timer: publish {TIMER_EXPIRED, name} after delay seconds."""

    async def _fire() -> None:
        try:
            await asyncio.sleep(delay)
            bus.publish(Event(EventCode.TIMER_EXPIRED, name))
        except asyncio.CancelledError:
            pass

    return asyncio.get_event_loop().create_task(_fire(), name=f"timeout:{name}")


def event_timer(
    bus: EventBus, interval: float, name: str, *, immediate: bool = False
) -> "asyncio.Task[None]":
    """Ticker: publish {TIMER_EXPIRED, name} every interval seconds.

    ``immediate=True`` fires once right away before settling into the
    interval cadence (used by watches so the first poll isn't delayed).
    """

    async def _tick() -> None:
        try:
            if immediate:
                bus.publish(Event(EventCode.TIMER_EXPIRED, name))
            while True:
                await asyncio.sleep(interval)
                bus.publish(Event(EventCode.TIMER_EXPIRED, name))
        except asyncio.CancelledError:
            pass

    return asyncio.get_event_loop().create_task(_tick(), name=f"timer:{name}")


def cancel_timer(task: Optional["asyncio.Task[None]"]) -> None:
    if task is not None and not task.done():
        task.cancel()
