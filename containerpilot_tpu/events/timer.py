"""Timers that inject TIMER_EXPIRED events.

Capability parity with the reference's timer helpers
(reference: events/timer.go):

- ``event_timeout``: one-shot — after ``delay`` seconds emit
  ``{TIMER_EXPIRED, name}`` once (reference: events/timer.go:12-34).
- ``event_timer``: ticker — emit ``{TIMER_EXPIRED, name}`` every
  ``interval`` seconds until cancelled (reference: events/timer.go:40-68).

Timers emit either onto the global bus or directly into one actor's
private mailbox — the reference's job-private timers write to the job's
own channel (reference: jobs/jobs.go:147-158), so the sink here is any
object with ``publish`` (EventBus) or ``receive`` (Subscriber mailbox),
or a bare callable.

Both are asyncio tasks; cancelling the returned task stops them.
Emitting after the generation tears down is harmless — the reference
handles the analogous send-on-closed-channel race with a recover()
(events/timer.go:26-30,49-54); here a cancelled task simply stops.
"""
from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from ..utils.tasks import spawn
from .events import Event, EventCode

EmitFn = Callable[[Event], None]


def _as_emit(sink: Any) -> EmitFn:
    # publish/receive take priority over bare callability so that
    # bus-like objects which also happen to be callable route through
    # their documented interface
    if hasattr(sink, "publish"):
        return sink.publish
    if hasattr(sink, "receive"):
        return sink.receive
    if callable(sink):
        return sink
    raise TypeError(f"not a timer sink: {sink!r}")


def _emit_safe(emit: EmitFn, event: Event, name: str) -> None:
    # one bad emit must not kill the cadence — the reference guards the
    # analogous send-on-closed-channel with recover()
    # (reference: events/timer.go:26-30,49-54)
    try:
        emit(event)
    except Exception:  # noqa: BLE001
        import logging

        logging.getLogger("containerpilot.events").exception(
            "timer %s: emit failed", name
        )


def event_timeout(sink: Any, delay: float, name: str) -> "asyncio.Task[None]":
    """One-shot timer: emit {TIMER_EXPIRED, name} after delay seconds."""
    emit = _as_emit(sink)

    async def _fire() -> None:
        try:
            await asyncio.sleep(delay)
            _emit_safe(emit, Event(EventCode.TIMER_EXPIRED, name), name)
        except asyncio.CancelledError:
            pass

    return spawn(_fire(), name=f"timeout:{name}")


def event_timer(
    sink: Any, interval: float, name: str, *, immediate: bool = False
) -> "asyncio.Task[None]":
    """Ticker: emit {TIMER_EXPIRED, name} every interval seconds.

    ``immediate=True`` fires once right away before settling into the
    interval cadence (used by watches so the first poll isn't delayed).
    """
    emit = _as_emit(sink)

    async def _tick() -> None:
        try:
            if immediate:
                _emit_safe(emit, Event(EventCode.TIMER_EXPIRED, name), name)
            while True:
                await asyncio.sleep(interval)
                _emit_safe(emit, Event(EventCode.TIMER_EXPIRED, name), name)
        except asyncio.CancelledError:
            pass

    return spawn(_tick(), name=f"timer:{name}")


def cancel_timer(task: Optional["asyncio.Task[None]"]) -> None:
    if task is not None and not task.done():
        task.cancel()
