"""Actor mailbox plumbing: Subscriber and Publisher mixins.

Capability parity with the reference's subscriber/publisher structs
(reference: events/subscriber.go, events/publisher.go). Every supervisor
actor (job, watch, metric collector, control server) embeds these:

- ``Subscriber``: a bounded mailbox (``rx``) the bus fans events into,
  plus subscribe/unsubscribe bookkeeping.
- ``Publisher``: register/unregister against the bus's actor-lifetime
  count plus a publish passthrough.

The mailbox is bounded at 1000 events, matching the reference's
per-actor channel capacity (reference: jobs/jobs.go:23).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Optional

from .bus import EventBus
from .events import Event

log = logging.getLogger("containerpilot.events")

MAILBOX_CAPACITY = 1000

try:  # mirror the bus's optional-metrics posture
    from prometheus_client import Counter, REGISTRY

    def _make_drop_counter() -> Optional["Counter"]:
        try:
            return Counter(
                "containerpilot_events_dropped",
                "Events dropped because an actor's mailbox overflowed",
                ["code", "source"],
            )
        except ValueError:  # re-registration in the same process (reloads)
            collector = REGISTRY._names_to_collectors.get(  # noqa: SLF001
                "containerpilot_events_dropped"
            )
            return collector  # type: ignore[return-value]

    _DROP_COUNTER = _make_drop_counter()
except Exception:  # pragma: no cover - prometheus always present in-tree
    _DROP_COUNTER = None


class Publisher:
    """Gives an actor a handle to publish onto the bus and be counted
    in the bus generation's lifetime."""

    def __init__(self) -> None:
        self.bus: Optional[EventBus] = None

    def register(self, bus: EventBus) -> None:
        self.bus = bus
        bus.register(self)

    def unregister(self) -> None:
        if self.bus is not None:
            self.bus.unregister(self)

    def publish(self, event: Event) -> None:
        if self.bus is not None:
            self.bus.publish(event)


class Subscriber(Publisher):
    """An actor with a bounded mailbox the bus delivers into."""

    def __init__(self) -> None:
        super().__init__()
        self.rx: asyncio.Queue[Event] = asyncio.Queue(maxsize=MAILBOX_CAPACITY)
        self._subscribed = False

    def subscribe(self, bus: EventBus) -> None:
        self.bus = bus
        bus.subscribe(self)
        self._subscribed = True

    def unsubscribe(self) -> None:
        if self.bus is not None and self._subscribed:
            self.bus.unsubscribe(self)
            self._subscribed = False

    def receive(self, event: Event) -> None:
        """Called by the bus, synchronously, during publish fan-out."""
        try:
            self.rx.put_nowait(event)
        except asyncio.QueueFull:
            # The reference would block the whole bus here; dropping with
            # a loud error + a counter is the safer failure mode for a
            # supervisor, and the counter makes the deviation observable
            # in /metrics.
            log.error(
                "mailbox full (%d): dropping %s for %r",
                MAILBOX_CAPACITY,
                event,
                self,
            )
            if _DROP_COUNTER is not None:
                try:
                    _DROP_COUNTER.labels(
                        code=event.code.value, source=event.source
                    ).inc()
                except Exception:  # pragma: no cover — cpcheck: disable=CP-SWALLOW metrics must never break fan-out
                    pass

    async def next_event(self) -> Event:
        return await self.rx.get()


class EventHandler(Subscriber):
    """Convenience base for actors that both subscribe and publish
    (every domain actor in practice)."""
