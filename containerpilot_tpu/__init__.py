"""containerpilot_tpu — a TPU-host-native application lifecycle supervisor.

A ground-up re-implementation of the capability set of a container
init/supervisor (reference: TritonDataCenter/containerpilot v3.6.x, Go)
re-designed for TPU VM pods: it supervises per-host JAX training and
serving processes, reaps zombies as PID 1, runs health checks, registers
services in a discovery catalog (Consul or a TPU-pod file catalog),
watches the catalog for upstream changes, exposes Prometheus telemetry,
and serves an HTTP control plane on a unix socket.

Layer map (bottom-up; see SURVEY.md §1 for the reference layout):

    sup/         PID-1 zombie reaper + signal passthrough (C++ native, Python fallback)
    commands/    process execution with process groups and timeouts
    events/      in-process actor event bus, timers
    discovery/   service catalog backends (Consul HTTP, TPU-pod file catalog, noop)
    jobs/        the job state machine (when/restarts/health/stop-dependencies)
    watches/     upstream-change pollers
    telemetry/   Prometheus /metrics + /status server
    control/     unix-socket HTTP control plane; client/ is its SDK
    config/      JSON5 + template config pipeline
    core/        the App generation loop, signals, CLI flags
    fleet/       inference fleet: replica registration/drain (FleetMember)
                 + discovery-driven routing gateway (FleetGateway)
    models/ ops/ parallel/ workload/   the TPU workload half: a JAX/pjit
                 training harness (flagship transformer, sharding rules,
                 pallas-ready op library) run *under* the supervisor.
"""
from .version import GIT_HASH, VERSION

__version__ = VERSION
__all__ = ["VERSION", "GIT_HASH", "__version__"]
