"""PID-1 mode: fork the real supervisor, forward signals, reap orphans.

Capability parity with the reference's sup package (reference:
sup/sup.go): when the supervisor finds itself as PID 1 inside a
container it must behave as init — fork the actual worker process
(re-exec of ourselves), pass SIGINT/SIGTERM/SIGHUP/SIGUSR1/SIGUSR2
through to the worker, and reap any orphans reparented onto PID 1 via a
``waitpid(-1)`` loop on SIGCHLD, *without* stealing the worker's own
waits (reference: sup/sup.go:61-92).

Two implementations, same behavior:

- the C++ binary ``native/cpsup`` (preferred as the container
  entrypoint — a single static-ish native init, like the reference's
  Go binary; see native/sup.cpp), and
- this Python fallback, used when ``python -m containerpilot_tpu`` is
  itself PID 1.
"""
from __future__ import annotations

import errno
import os
import signal
import sys
from typing import List, Optional

PASS_THROUGH_SIGNALS = (
    signal.SIGINT,
    signal.SIGTERM,
    signal.SIGHUP,
    signal.SIGUSR1,
    signal.SIGUSR2,
)


PR_SET_CHILD_SUBREAPER = 36  # linux/prctl.h


def claim_subreaper() -> bool:
    """Mark this process a child subreaper (ctypes twin of
    native/sup.cpp's prctl call): orphans of our descendants reparent
    to US, not to PID 1, so the waitpid(-1) loop actually collects
    them even when we are not literal PID 1 (systemd on a TPU VM, a
    test harness, a PID namespace with a shim at 1). Best-effort:
    returns False on kernels/platforms without the prctl."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        return libc.prctl(PR_SET_CHILD_SUBREAPER, 1, 0, 0, 0) == 0
    except (OSError, AttributeError):
        return False


def run(argv: Optional[List[str]] = None) -> int:
    """Fork the worker and babysit it as PID 1; returns the worker's
    exit code (reference: sup/sup.go:15-30)."""
    argv = argv if argv is not None else sys.argv
    claim_subreaper()
    worker_pid = os.fork()
    if worker_pid == 0:
        # child: become the real supervisor process
        os.execv(sys.executable, [sys.executable, "-m", "containerpilot_tpu"]
                 + argv[1:])
        return 127  # pragma: no cover - execv doesn't return

    exit_code = 0

    def forward(signum: int, _frame: object) -> None:
        try:
            os.kill(worker_pid, signum)
        except ProcessLookupError:
            pass

    for sig in PASS_THROUGH_SIGNALS:
        signal.signal(sig, forward)

    # reap until our worker exits (reference: sup/sup.go:61-92); the
    # blocking wait on -1 reaps any orphan that gets reparented to us
    while True:
        try:
            pid, status = os.waitpid(-1, 0)
        except InterruptedError:
            continue
        except ChildProcessError:
            break
        if pid == worker_pid:
            if os.WIFEXITED(status):
                exit_code = os.WEXITSTATUS(status)
            elif os.WIFSIGNALED(status):
                exit_code = 128 + os.WTERMSIG(status)
            break
    # final non-blocking sweep for any remaining zombies
    while True:
        try:
            pid, _status = os.waitpid(-1, os.WNOHANG)
        except (ChildProcessError, OSError):
            break
        if pid == 0:
            break
    return exit_code
