"""PID-1 supervisor: fork the worker, pass signals, reap zombies
(reference: sup/ package)."""
from .sup import run as run_sup

__all__ = ["run_sup"]
