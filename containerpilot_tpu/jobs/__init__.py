"""Jobs: the supervisor's core domain actor (reference: jobs/ package)."""
from .config import UNLIMITED, JobConfig, JobConfigError, new_job_configs
from .jobs import Job, from_configs
from .status import JobStatus

__all__ = [
    "Job",
    "JobConfig",
    "JobConfigError",
    "JobStatus",
    "UNLIMITED",
    "from_configs",
    "new_job_configs",
]
