"""The Job actor: the supervisor's core state machine.

Capability parity with the reference's job runtime
(reference: jobs/jobs.go). Each job runs an event-loop task over its
bounded mailbox and dispatches on (code, source) pairs through eleven
handlers (reference: jobs/jobs.go:187-376):

- private ``run-every``/``heartbeat`` tickers and ``wait-timeout``
  one-shot feed the job's own mailbox, not the global bus
  (reference: jobs/jobs.go:147-161);
- health-check execs publish ``check.<name>`` exit events on the global
  bus, which the job maps to healthy/unhealthy status plus a catalog
  TTL heartbeat (reference: jobs/jobs.go:278-293);
- restarts decrement a budget; start events respect the
  once/each/unlimited starts limit (reference: jobs/jobs.go:333-383);
- pre-stop/post-stop jobs (started by another job's ``stopping`` /
  ``stopped`` events) get one more run during global shutdown
  (reference: jobs/jobs.go:295-312);
- cleanup publishes ``{STOPPING, name}``, waits for the configured
  stop-dependency's ``{STOPPED, dep}`` with a timeout, deregisters from
  the catalog, then publishes ``{STOPPED, name}``
  (reference: jobs/jobs.go:388-416).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Callable, List, Optional

from ..events import (
    Event,
    EventBus,
    EventCode,
    EventHandler,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_EXIT_MAINTENANCE,
    GLOBAL_SHUTDOWN,
    NON_EVENT,
    QUIT_BY_TEST,
    cancel_timer,
    event_timeout,
    event_timer,
)
from ..utils.tasks import spawn
from .config import UNLIMITED, JobConfig
from .status import JobStatus

log = logging.getLogger("containerpilot.jobs")

_HALT = True
_CONTINUE = False


class Job(EventHandler):
    """One supervised job: exec + health + discovery + lifecycle."""

    def __init__(self, cfg: JobConfig) -> None:
        super().__init__()
        self.name = cfg.name
        self.exec = cfg.exec
        self.status = JobStatus.IDLE
        self.service = cfg.service_definition
        self.health_check_exec = cfg.health_check_exec
        self.start_event = cfg.when_event
        self.start_timeout = cfg.when_timeout
        self.starts_remain = cfg.when_starts_limit
        self.start_timeout_event: Event = NON_EVENT
        self.stopping_wait_event = cfg.stopping_wait_event
        self.stopping_timeout = cfg.stopping_timeout
        self.heartbeat = cfg.heartbeat_interval
        self.restart_limit = cfg.restart_limit
        self.restarts_remain = cfg.restart_limit
        self.frequency = cfg.freq_interval
        self.is_complete = False
        self._timers: List["asyncio.Task[None]"] = []
        self._task: Optional["asyncio.Task[None]"] = None
        if self.name == "containerpilot":
            # the telemetry service advertises itself as always-healthy
            # (reference: jobs/jobs.go:82-87)
            self.status = JobStatus.ALWAYS_HEALTHY

    # -- status ---------------------------------------------------------

    def get_status(self) -> JobStatus:
        return self.status

    def set_status(self, status: JobStatus) -> None:
        if self.status is not JobStatus.ALWAYS_HEALTHY:
            self.status = status

    def send_heartbeat(self) -> None:
        if self.service is not None:
            self.service.send_heartbeat()

    def check_registration(self) -> None:
        """Retry initial-status registration every loop iteration so a
        flaky catalog heals (reference: jobs/jobs.go:108-113,168-171)."""
        if self.service is not None and self.service.initial_status:
            self.service.register_with_initial_status()

    def kill(self) -> None:
        if self.exec is not None:
            self.exec.kill()

    # -- run loop -------------------------------------------------------

    def run(
        self, on_complete: Optional[Callable[["Job"], None]] = None
    ) -> "asyncio.Task[None]":
        """Start timers and the event-loop task
        (reference: jobs/jobs.go:144-185)."""
        if self.frequency > 0:
            self._timers.append(
                event_timer(self.receive, self.frequency, f"{self.name}.run-every")
            )
        if self.heartbeat > 0:
            self._timers.append(
                event_timer(self.receive, self.heartbeat, f"{self.name}.heartbeat")
            )
        if self.start_timeout > 0:
            timeout_name = f"{self.name}.wait-timeout"
            self._timers.append(
                event_timeout(self.receive, self.start_timeout, timeout_name)
            )
            self.start_timeout_event = Event(EventCode.TIMER_EXPIRED, timeout_name)
        else:
            self.start_timeout_event = NON_EVENT
        self._task = spawn(
            self._loop(on_complete), name=f"job:{self.name}"
        )
        return self._task

    async def _loop(self, on_complete: Optional[Callable[["Job"], None]]) -> None:
        try:
            while True:
                self.check_registration()
                event = await self.next_event()
                if event == QUIT_BY_TEST:
                    return
                if self._process_event(event) == _HALT:
                    return
        except asyncio.CancelledError:
            pass  # hard teardown: skip the stopping handshake
        finally:
            await self._cleanup()
            if on_complete is not None:
                on_complete(self)

    # -- dispatch (reference: jobs/jobs.go:187-234) ---------------------

    def _process_event(self, event: Event) -> bool:
        run_every_source = f"{self.name}.run-every"
        heartbeat_source = f"{self.name}.heartbeat"
        health_check_name = (
            self.health_check_exec.name
            if self.health_check_exec is not None
            else f"check.{self.name}"
        )

        if event == Event(EventCode.TIMER_EXPIRED, heartbeat_source):
            return self._on_heartbeat_timer_expired()
        if event == self.start_timeout_event:
            return self._on_start_timeout_expired()
        if event == Event(EventCode.TIMER_EXPIRED, run_every_source):
            return self._on_run_every_timer_expired()
        if event == Event(EventCode.EXIT_FAILED, health_check_name):
            return self._on_health_check_failed()
        if event == Event(EventCode.EXIT_SUCCESS, health_check_name):
            return self._on_health_check_passed()
        if event in (Event(EventCode.QUIT, self.name), GLOBAL_SHUTDOWN):
            return self._on_quit()
        if event == GLOBAL_ENTER_MAINTENANCE:
            return self._on_enter_maintenance()
        if event == GLOBAL_EXIT_MAINTENANCE:
            return self._on_exit_maintenance()
        if event in (
            Event(EventCode.EXIT_SUCCESS, self.name),
            Event(EventCode.EXIT_FAILED, self.name),
        ):
            return self._on_exec_exit()
        if event in (
            Event(EventCode.SIGNAL, "SIGHUP"),
            Event(EventCode.SIGNAL, "SIGUSR2"),
        ):
            return self._on_signal_event(event.source)
        if event == self.start_event:
            return self._on_start_event()
        return _CONTINUE

    # -- handlers (reference: jobs/jobs.go:245-383) ---------------------

    def _start_job_exec(self) -> None:
        self.start_timeout_event = NON_EVENT
        self.set_status(JobStatus.UNKNOWN)
        if self.exec is not None and self.bus is not None:
            self.exec.run(self.bus)

    def _on_heartbeat_timer_expired(self) -> bool:
        status = self.get_status()
        if status not in (JobStatus.MAINTENANCE, JobStatus.IDLE):
            if self.health_check_exec is not None and self.bus is not None:
                self.health_check_exec.run(self.bus)
            elif self.service is not None:
                # advertised but uncheck-ed services (e.g. telemetry)
                self.send_heartbeat()
        return _CONTINUE

    def _on_start_timeout_expired(self) -> bool:
        self.publish(Event(EventCode.TIMER_EXPIRED, self.name))
        self.receive(Event(EventCode.QUIT, self.name))
        return _CONTINUE

    def _on_run_every_timer_expired(self) -> bool:
        if not self._restart_permitted():
            log.debug("interval expired but restart not permitted: %s", self.name)
            self.start_event = NON_EVENT
            return _HALT
        self.restarts_remain -= 1
        self._start_job_exec()
        return _CONTINUE

    def _on_health_check_failed(self) -> bool:
        if self.get_status() is not JobStatus.MAINTENANCE:
            self.set_status(JobStatus.UNHEALTHY)
            self.publish(Event(EventCode.STATUS_UNHEALTHY, self.name))
        return _CONTINUE

    def _on_health_check_passed(self) -> bool:
        if self.get_status() is not JobStatus.MAINTENANCE:
            self.set_status(JobStatus.HEALTHY)
            self.publish(Event(EventCode.STATUS_HEALTHY, self.name))
            self.send_heartbeat()
        return _CONTINUE

    def _on_quit(self) -> bool:
        self.restarts_remain = 0
        if (
            self.start_event.code in (EventCode.STOPPING, EventCode.STOPPED)
            and self.exec is not None
        ):
            # pre-stop/post-stop jobs ride out the global shutdown and
            # halt on their own exec exit; the app's stopTimeout then
            # SIGKILL bounds them (reference: jobs/jobs.go:297-308)
            if self.starts_remain == UNLIMITED:
                self.starts_remain = 1
            return _CONTINUE
        self.starts_remain = 0
        self.start_event = NON_EVENT
        return _HALT

    def _on_enter_maintenance(self) -> bool:
        self.set_status(JobStatus.MAINTENANCE)
        if self.service is not None:
            self.service.mark_for_maintenance()
        if self.start_event == GLOBAL_ENTER_MAINTENANCE:
            return self._on_start_event()
        return _CONTINUE

    def _on_exit_maintenance(self) -> bool:
        self.set_status(JobStatus.UNKNOWN)
        if self.start_event == GLOBAL_EXIT_MAINTENANCE:
            return self._on_start_event()
        return _CONTINUE

    def _on_exec_exit(self) -> bool:
        if self.frequency > 0:
            return _CONTINUE  # periodic jobs ignore their exits
        if self._restart_permitted():
            self.restarts_remain -= 1
            self._start_job_exec()
            return _CONTINUE
        if self.starts_remain != 0:
            return _CONTINUE
        log.debug("job exited but restart not permitted: %s", self.name)
        self.start_event = NON_EVENT
        self.set_status(JobStatus.UNKNOWN)
        return _HALT

    def _on_signal_event(self, sig: str) -> bool:
        if (
            self.start_event.code == EventCode.SIGNAL
            and self.start_event.source == sig
        ):
            self._start_job_exec()
        return _CONTINUE

    def _on_start_event(self) -> bool:
        if self.starts_remain == 0:
            self.start_event = NON_EVENT
            return _HALT
        if self.starts_remain != UNLIMITED:
            self.starts_remain -= 1
            if self.starts_remain == 0 or self.restarts_remain == 0:
                # don't receive the start event again while running
                self.start_event = NON_EVENT
        self._start_job_exec()
        return _CONTINUE

    def _restart_permitted(self) -> bool:
        return self.restart_limit == UNLIMITED or self.restarts_remain > 0

    # -- cleanup (reference: jobs/jobs.go:388-416) ----------------------

    async def _cleanup(self) -> None:
        stopping_timeout_name = f"{self.name}.stopping-timeout"
        self.publish(Event(EventCode.STOPPING, self.name))
        if self.stopping_wait_event != NON_EVENT:
            if self.stopping_timeout > 0:
                self._timers.append(
                    event_timeout(
                        self.receive, self.stopping_timeout, stopping_timeout_name
                    )
                )
            while True:
                event = await self.next_event()
                if event == self.stopping_wait_event:
                    break
                if event == Event(EventCode.TIMER_EXPIRED, stopping_timeout_name):
                    break
        for timer in self._timers:
            cancel_timer(timer)
        self._timers = []
        # the reference cancels the job-scoped context here, which
        # SIGTERMs any still-running exec/health-check process groups
        # (reference: jobs/jobs.go:408 + commands/commands.go:114-121);
        # the app's stopTimeout then bounds stragglers with SIGKILL
        if self.exec is not None:
            self.exec.term()
        if self.health_check_exec is not None:
            self.health_check_exec.term()
        if self.service is not None:
            future = self.service.deregister()
            if future is not None:
                # keep ordering: our stopped event follows deregistration.
                # shield so a timeout gives up *waiting* without
                # cancelling the queued deregister itself — it must
                # still run once the catalog unwedges
                try:
                    await asyncio.wait_for(
                        asyncio.shield(asyncio.wrap_future(future)),
                        timeout=10.0,
                    )
                except Exception:  # noqa: BLE001 — cpcheck: disable=CP-SWALLOW cleanup never raises; deregister failure already logged by the service queue
                    pass
        self.unsubscribe()
        self.unregister()
        self.is_complete = True
        self.status = JobStatus.COMPLETED
        self.publish(Event(EventCode.STOPPED, self.name))

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"jobs.Job[{self.name}]"


def from_configs(configs: List[JobConfig]) -> List[Job]:
    """Build Jobs from validated configs (reference: jobs/jobs.go:92-99)."""
    return [Job(cfg) for cfg in configs]
