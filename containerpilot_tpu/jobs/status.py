"""Job health status enum (reference: jobs/status.go)."""
from __future__ import annotations

import enum


class JobStatus(enum.Enum):
    IDLE = "idle"  # default before starting
    UNKNOWN = "unknown"
    HEALTHY = "healthy"
    UNHEALTHY = "unhealthy"
    MAINTENANCE = "maintenance"
    ALWAYS_HEALTHY = "alwaysHealthy"  # hardcoded-healthy jobs (telemetry)
    COMPLETED = "completed"

    def __str__(self) -> str:
        """Serialized form for /status (reference: jobs/status.go:17-34):
        idle and unknown both render as "unknown", alwaysHealthy as
        "healthy"."""
        if self in (JobStatus.IDLE, JobStatus.UNKNOWN):
            return "unknown"
        if self is JobStatus.ALWAYS_HEALTHY:
            return "healthy"
        return self.value
