"""Job configuration: parsing and validation.

Capability parity with the reference's job config
(reference: jobs/config.go). The validation surface preserved:

- ``when`` defaults to GLOBAL_STARTUP with one start
  (reference: config.go:178-186); ``interval``/``once``/``each`` are
  mutually exclusive (config.go:188-193); interval jobs must tick at
  >= 1ms (config.go:200-215); SIGHUP/SIGUSR2 sources become Signal
  events with unlimited starts (config.go:239-243).
- ``restarts`` accepts non-negative ints, "never", "unlimited";
  defaults: unlimited for interval jobs else 0; "unlimited" is
  forbidden with ``when.each`` (config.go:346-396).
- advertised jobs (``port`` set) require ``health`` with interval and
  ttl >= 1 (config.go:297-310); service names are validated and the
  advertised IP resolved from the interface DSL (config.go:139-160,
  400-440).
- exec timeouts >= 1ms; interval jobs default their exec timeout to
  the interval itself (config.go:259-277).
- jobs whose ``when.once/each: stopping`` of another job wire up the
  stop-dependency handshake on that *other* job
  (config.go:99-114,135-137).
"""
from __future__ import annotations

import os
import socket
from typing import Any, Dict, List, Optional

from ..commands import ArgsError, Command
from ..config.decode import coerce_int, coerce_number
from ..config.services import get_ip, validate_name
from ..config.timing import DurationError, get_timeout, parse_duration
from ..discovery import Backend, ServiceDefinition, ServiceRegistration
from ..events import (
    Event,
    EventCode,
    GLOBAL_STARTUP,
    NON_EVENT,
    code_from_string,
)

UNLIMITED = -1
TASK_MIN_DURATION = 0.001  # 1ms (reference: jobs/config.go:18)


class JobConfigError(ValueError):
    """A job config failed validation."""


class JobConfig:
    """One validated job definition."""

    def __init__(self, raw: Dict[str, Any]) -> None:
        if not isinstance(raw, dict):
            raise JobConfigError(f"job configuration must be a mapping: {raw!r}")
        known = {
            "name", "exec", "port", "initial_status", "initialStatus",
            "interfaces", "tags", "consul", "health", "timeout", "restarts",
            "stopTimeout", "when", "logging",
        }
        unknown = set(raw) - known
        if unknown:
            raise JobConfigError(
                f"job[{raw.get('name', '?')}]: unknown keys {sorted(unknown)}"
            )
        self.name: str = raw.get("name", "") or ""
        self.exec_raw = raw.get("exec")
        port = coerce_int(raw.get("port", 0) or 0)
        if port is None:
            raise JobConfigError(f"job[{self.name}].port must be an integer")
        self.port: int = port
        self.initial_status: str = (
            raw.get("initial_status") or raw.get("initialStatus") or ""
        )
        self.interfaces = raw.get("interfaces")
        tags = raw.get("tags") or []
        if not isinstance(tags, (list, tuple)):
            raise JobConfigError(f"job[{self.name}].tags must be a list")
        self.tags: List[str] = [str(t) for t in tags]
        # structured sections must be mappings (JSON5 objects)
        for key in ("consul", "health", "when", "logging"):
            value = raw.get(key)
            if value is not None and not isinstance(value, dict):
                raise JobConfigError(
                    f"job[{self.name}].{key} must be an object"
                )
        self.consul_extras: Optional[Dict[str, Any]] = raw.get("consul")
        self.health_raw: Optional[Dict[str, Any]] = raw.get("health")
        self.exec_timeout_raw = raw.get("timeout", "")
        self.restarts_raw = raw.get("restarts")
        self.stop_timeout_raw = raw.get("stopTimeout", "")
        self.when_raw: Optional[Dict[str, Any]] = raw.get("when")
        self.logging_raw: Optional[Dict[str, Any]] = raw.get("logging")

        # validated/derived state
        self.exec: Optional[Command] = None
        self.exec_timeout: float = 0.0
        self.freq_interval: float = 0.0
        self.when_event: Event = GLOBAL_STARTUP
        self.when_timeout: float = 0.0
        self.when_starts_limit: int = 1
        self.stopping_wait_event: Event = NON_EVENT
        self.stopping_timeout: float = 0.0
        self.restart_limit: int = 0
        self.heartbeat_interval: float = 0.0
        self.ttl: int = 0
        self.health_check_exec: Optional[Command] = None
        self.service_definition: Optional[ServiceDefinition] = None

    # -- validation pipeline (reference: jobs/config.go:118-133) --------

    def validate(self, disc: Optional[Backend]) -> "JobConfig":
        self._validate_discovery(disc)
        self._validate_when()
        self._validate_stopping_timeout()
        self._validate_restarts()
        self._validate_exec()
        return self

    def set_stopping(self, dependent_name: str) -> None:
        """Wire the stop-dependency handshake: this job's cleanup waits
        for {STOPPED, dependent} (reference: jobs/config.go:135-137)."""
        self.stopping_wait_event = Event(EventCode.STOPPED, dependent_name)

    # -- discovery ------------------------------------------------------

    def _validate_discovery(self, disc: Optional[Backend]) -> None:
        self._validate_health_check()
        if (self.port == 0 or disc is None) and self.name != "":
            return  # not an advertised service
        if self.port == 0:
            return
        self._validate_initial_status()
        try:
            validate_name(self.name)
        except ValueError as exc:
            raise JobConfigError(str(exc)) from None
        self._add_discovery_config(disc)

    def _validate_initial_status(self) -> None:
        if self.initial_status and self.initial_status not in (
            "passing", "warning", "critical",
        ):
            raise JobConfigError(
                f"job[{self.name}].initialStatus must be one of 'passing', "
                "'warning' or 'critical'"
            )

    def _validate_health_check(self) -> None:
        if self.port != 0 and self.health_raw is None and self.name != "containerpilot":
            raise JobConfigError(
                f"job[{self.name}].health must be set if 'port' is set"
            )
        if self.health_raw is None:
            return
        heartbeat = coerce_number(self.health_raw.get("interval", 0))
        ttl = coerce_number(self.health_raw.get("ttl", 0))
        if not isinstance(heartbeat, (int, float)) or heartbeat < 1:
            raise JobConfigError(f"job[{self.name}].health.interval must be > 0")
        if not isinstance(ttl, (int, float)) or ttl < 1:
            raise JobConfigError(f"job[{self.name}].health.ttl must be > 0")
        self.ttl = int(ttl)
        self.heartbeat_interval = float(heartbeat)
        try:
            check_timeout = get_timeout(self.health_raw.get("timeout", ""))
        except DurationError as exc:
            raise JobConfigError(
                f"could not parse job[{self.name}].health.timeout: {exc}"
            ) from None
        if not check_timeout:
            check_timeout = self.heartbeat_interval
        check_exec = self.health_raw.get("exec")
        if check_exec is not None:
            check_name = f"check.{self.name}"
            fields: Optional[Dict[str, Any]] = {"check": check_name}
            health_logging = self.health_raw.get("logging") or {}
            if not isinstance(health_logging, dict):
                raise JobConfigError(
                    f"job[{self.name}].health.logging must be an object"
                )
            if health_logging.get("raw"):
                fields = None
            try:
                self.health_check_exec = Command.from_config(
                    check_exec, timeout=check_timeout, fields=fields,
                    name=check_name,
                )
            except ArgsError as exc:
                raise JobConfigError(
                    f"unable to create job[{self.name}].health.exec: {exc}"
                ) from None

    def _add_discovery_config(self, disc: Backend) -> None:
        interfaces = self.interfaces
        if isinstance(interfaces, str):
            interfaces = [interfaces]
        try:
            ip_address = get_ip(interfaces)
        except ValueError as exc:
            raise JobConfigError(str(exc)) from None
        hostname = socket.gethostname()
        dereg_after = ""
        enable_tag_override = False
        if self.consul_extras:
            dereg_after = self.consul_extras.get(
                "deregisterCriticalServiceAfter", ""
            )
            if dereg_after:
                try:
                    parse_duration(dereg_after)
                except DurationError as exc:
                    raise JobConfigError(
                        f"unable to parse job[{self.name}].consul."
                        f"deregisterCriticalServiceAfter: {exc}"
                    ) from None
            enable_tag_override = bool(
                self.consul_extras.get("enableTagOverride", False)
            )
        registration = ServiceRegistration(
            id=f"{self.name}-{hostname}",
            name=self.name,
            port=self.port,
            ttl=self.ttl,
            tags=self.tags,
            address=ip_address,
            initial_status=self.initial_status,
            enable_tag_override=enable_tag_override,
            deregister_critical_service_after=dereg_after,
        )
        self.service_definition = ServiceDefinition(registration, disc)

    # -- when -----------------------------------------------------------

    def _validate_when(self) -> None:
        when = self.when_raw
        if when is None:
            self.when_event = GLOBAL_STARTUP
            self.when_timeout = 0.0
            self.when_starts_limit = 1
            return
        freq = when.get("interval", "")
        once = when.get("once", "")
        each = when.get("each", "")
        if (freq and once) or (freq and each) or (once and each):
            raise JobConfigError(
                f"job[{self.name}].when can have only one of 'interval', "
                "'once', or 'each'"
            )
        if freq:
            self._validate_frequency(freq)
            return
        self._validate_when_event(when, once, each)

    def _validate_frequency(self, freq_raw: Any) -> None:
        try:
            freq = parse_duration(freq_raw)
        except DurationError as exc:
            raise JobConfigError(
                f"unable to parse job[{self.name}].when.interval "
                f"{freq_raw!r}: {exc}"
            ) from None
        if freq < TASK_MIN_DURATION:
            raise JobConfigError(
                f"job[{self.name}].when.interval {freq_raw!r} cannot be "
                f"less than {TASK_MIN_DURATION}s"
            )
        self.freq_interval = freq
        self.when_timeout = 0.0
        self.when_event = GLOBAL_STARTUP
        self.when_starts_limit = 1

    def _validate_when_event(
        self, when: Dict[str, Any], once: str, each: str
    ) -> None:
        try:
            self.when_timeout = get_timeout(when.get("timeout", ""))
        except DurationError as exc:
            raise JobConfigError(
                f"unable to parse job[{self.name}].when.timeout: {exc}"
            ) from None
        source = when.get("source", "")
        code = EventCode.NONE
        try:
            if once:
                code = code_from_string(once)
                self.when_starts_limit = 1
            elif each:
                code = code_from_string(each)
                self.when_starts_limit = UNLIMITED
        except ValueError as exc:
            raise JobConfigError(
                f"unable to parse job[{self.name}].when.event: {exc}"
            ) from None
        if source in ("SIGHUP", "SIGUSR2"):
            code = EventCode.SIGNAL
            self.when_starts_limit = UNLIMITED
        self.when_event = Event(code, source)

    # -- stopping / restarts / exec -------------------------------------

    def _validate_stopping_timeout(self) -> None:
        try:
            self.stopping_timeout = get_timeout(self.stop_timeout_raw)
        except DurationError as exc:
            raise JobConfigError(
                f"unable to parse job[{self.name}].stopTimeout "
                f"{self.stop_timeout_raw!r}: {exc}"
            ) from None
        self.stopping_wait_event = NON_EVENT

    def _validate_restarts(self) -> None:
        raw = self.restarts_raw
        if raw is None:
            self.restart_limit = UNLIMITED if self.freq_interval else 0
            return
        msg = f"job[{self.name}].restarts field {raw!r} invalid"
        when_each = bool(self.when_raw and self.when_raw.get("each"))
        if isinstance(raw, str):
            if raw == "unlimited":
                if when_each:
                    raise JobConfigError(
                        f"{msg}: may not be used when 'job.when.each' is set "
                        "because it may result in infinite processes"
                    )
                self.restart_limit = UNLIMITED
            elif raw == "never":
                self.restart_limit = 0
            elif raw.isdigit():
                self.restart_limit = int(raw)
            else:
                raise JobConfigError(
                    f'{msg}: accepts positive integers, "unlimited", or "never"'
                )
        elif isinstance(raw, bool):
            raise JobConfigError(
                f'{msg}: accepts positive integers, "unlimited", or "never"'
            )
        elif isinstance(raw, (int, float)):
            if raw < 0:
                raise JobConfigError(f"{msg}: number must be positive integer")
            self.restart_limit = int(raw)
        else:
            raise JobConfigError(
                f'{msg}: accepts positive integers, "unlimited", or "never"'
            )

    def _validate_exec(self) -> None:
        if not self.exec_timeout_raw and self.freq_interval:
            # periodic tasks require a timeout (reference: config.go:261-264)
            self.exec_timeout = self.freq_interval
        if self.exec_timeout_raw:
            try:
                timeout = get_timeout(self.exec_timeout_raw)
            except DurationError as exc:
                raise JobConfigError(
                    f"unable to parse job[{self.name}].timeout "
                    f"{self.exec_timeout_raw!r}: {exc}"
                ) from None
            if timeout < TASK_MIN_DURATION:
                raise JobConfigError(
                    f"job[{self.name}].timeout {self.exec_timeout_raw!r} "
                    "cannot be less than 1ms"
                )
            self.exec_timeout = timeout
        if self.exec_raw is not None:
            fields: Optional[Dict[str, Any]] = {"job": self.name}
            if self.logging_raw and self.logging_raw.get("raw"):
                fields = None
            try:
                cmd = Command.from_config(
                    self.exec_raw, timeout=self.exec_timeout, fields=fields
                )
            except ArgsError as exc:
                raise JobConfigError(
                    f"unable to create job[{self.name}].exec: {exc}"
                ) from None
            if not self.name:
                self.name = cmd.exec
            cmd.name = self.name
            if fields is not None:
                cmd.fields = {"job": self.name}
            self.exec = cmd


def new_job_configs(
    raw: Optional[List[Dict[str, Any]]], disc: Optional[Backend]
) -> List[JobConfig]:
    """Parse and validate a list of raw job configs, wiring up
    stop-dependencies (reference: jobs/config.go:91-115)."""
    if raw is None:
        return []
    if not isinstance(raw, list):
        raise JobConfigError("job configuration must be a list")
    configs = [JobConfig(item) for item in raw]
    stop_dependencies: Dict[str, str] = {}
    for cfg in configs:
        cfg.validate(disc)
        if cfg.when_event.code == EventCode.STOPPING:
            stop_dependencies[cfg.when_event.source] = cfg.name
    for cfg in configs:
        if cfg.name in stop_dependencies:
            cfg.set_stopping(stop_dependencies[cfg.name])
    return configs
