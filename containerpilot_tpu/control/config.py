"""Control-plane config: the unix socket path
(reference: control/config.go — default /var/run/containerpilot.socket)."""
from __future__ import annotations

from typing import Any, Dict, Optional

DEFAULT_SOCKET = "/var/run/containerpilot.socket"


class ControlConfigError(ValueError):
    pass


class ControlConfig:
    def __init__(self, raw: Optional[Dict[str, Any]] = None) -> None:
        raw = raw or {}
        if not isinstance(raw, dict):
            raise ControlConfigError(f"control configuration must be a mapping")
        unknown = set(raw) - {"socket"}
        if unknown:
            raise ControlConfigError(f"control: unknown keys {sorted(unknown)}")
        self.socket: str = raw.get("socket") or DEFAULT_SOCKET
