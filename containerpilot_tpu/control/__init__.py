"""Control plane: unix-socket HTTP server (reference: control/ package)."""
from .config import ControlConfig, ControlConfigError, DEFAULT_SOCKET
from .control import ControlServer

__all__ = ["ControlConfig", "ControlConfigError", "ControlServer", "DEFAULT_SOCKET"]
