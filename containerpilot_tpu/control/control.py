"""The control plane: HTTP over a unix domain socket.

Capability parity with the reference (reference: control/control.go,
control/endpoints.go). Endpoints (all under /v3):

- ``POST /v3/environ``              set env vars for future execs/reloads
- ``POST /v3/reload``               set reload flag + shut down generation
- ``POST /v3/metric``               publish {METRIC, "name|value"} events
- ``POST /v3/maintenance/enable``   publish GlobalEnterMaintenance
- ``POST /v3/maintenance/disable``  publish GlobalExitMaintenance
- ``GET  /v3/maintenance/status``   {"maintenance": bool} (extension:
  drain runbooks confirm the flip landed)
- ``GET  /v3/ping``                 liveness of the socket

Binding retries while a prior generation's socket file lingers
(reference: control/control.go:125-140). A Prometheus counter tracks
request statuses (reference: control/control.go:27-33).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Optional

from ..events import (
    Event,
    EventBus,
    EventCode,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_EXIT_MAINTENANCE,
)
from ..utils.http import HTTPServer, Request, Response
from .config import ControlConfig

log = logging.getLogger("containerpilot.control")

BIND_RETRIES = 10
BIND_RETRY_DELAY = 1.0  # reference: control/control.go:130-137

try:
    from prometheus_client import Counter, REGISTRY

    def _make_counter() -> Optional["Counter"]:
        try:
            return Counter(
                "containerpilot_control_http_requests",
                "Control-plane HTTP requests by status and path",
                ["status", "path"],
            )
        except ValueError:
            return REGISTRY._names_to_collectors.get(  # noqa: SLF001
                "containerpilot_control_http_requests"
            )

    _REQUEST_COUNTER = _make_counter()
except Exception:  # pragma: no cover
    _REQUEST_COUNTER = None


class ControlServer:
    """One generation's control server (reference: control/control.go:36-93)."""

    def __init__(self, cfg: ControlConfig) -> None:
        self.cfg = cfg
        self.bus: Optional[EventBus] = None
        # the last maintenance verb posted through THIS generation's
        # socket; /v3/maintenance/status reads it back so operators
        # (and fleet drain runbooks) can confirm the flip landed
        self.maintenance = False
        self._server = HTTPServer()
        self._server.route("GET", "/v3/ping", self._ping)
        self._server.route("POST", "/v3/environ", self._put_environ)
        self._server.route("POST", "/v3/reload", self._post_reload)
        self._server.route("POST", "/v3/metric", self._post_metric)
        self._server.route(
            "POST", "/v3/maintenance/enable", self._post_maintenance_enable
        )
        self._server.route(
            "POST", "/v3/maintenance/disable", self._post_maintenance_disable
        )
        self._server.route(
            "GET", "/v3/maintenance/status", self._get_maintenance_status
        )
        # observability beyond the reference: the bus's recent-event
        # ring and the live actor-task table, for debugging live
        # supervisors
        self._server.route("GET", "/v3/events", self._get_events)
        self._server.route("GET", "/v3/tasks", self._get_tasks)

    # -- lifecycle ------------------------------------------------------

    async def run(self, bus: EventBus) -> None:
        self.bus = bus
        await self._listen_with_retry()

    async def _listen_with_retry(self) -> None:
        for attempt in range(BIND_RETRIES):
            try:
                self._try_unlink_stale_socket()
                await self._server.start_unix(self.cfg.socket)
                os.chmod(self.cfg.socket, 0o660)
                log.debug("control: serving at %s", self.cfg.socket)
                return
            except OSError as exc:
                if attempt == BIND_RETRIES - 1:
                    raise
                log.warning(
                    "control: error listening to socket at %s: %s",
                    self.cfg.socket,
                    exc,
                )
                await asyncio.sleep(BIND_RETRY_DELAY)

    def _try_unlink_stale_socket(self) -> None:
        """A previous generation (or crashed supervisor) may have left
        the socket file behind; a fresh bind needs it gone
        (reference: control/control.go:125-140)."""
        if os.path.exists(self.cfg.socket):
            try:
                os.unlink(self.cfg.socket)
            except OSError:
                pass

    async def stop(self) -> None:
        await self._server.stop()
        self._try_unlink_stale_socket()

    # -- endpoint helpers -----------------------------------------------

    def _count(self, status: int, path: str) -> None:
        if _REQUEST_COUNTER is not None:
            try:
                _REQUEST_COUNTER.labels(status=str(status), path=path).inc()
            except Exception:  # pragma: no cover — cpcheck: disable=CP-SWALLOW metrics must never break the handler
                pass

    def _respond(
        self,
        status: int,
        path: str,
        body: bytes = b"\n",
        content_type: str = "text/plain; charset=utf-8",
    ) -> Response:
        self._count(status, path)
        return Response(status, body, content_type=content_type)

    # -- endpoints ------------------------------------------------------

    async def _ping(self, req: Request) -> Response:
        return self._respond(200, req.path)

    async def _put_environ(self, req: Request) -> Response:
        """Set env vars in the supervisor process so reloads and future
        execs observe them (reference: endpoints.go:57-72); '-putenv'
        persistence across reloads comes from this process surviving
        generations."""
        try:
            env = json.loads(req.body.decode() or "null")
            if not isinstance(env, dict):
                raise ValueError("not an object")
            for key, value in env.items():
                os.environ[str(key)] = str(value)
        except (ValueError, UnicodeDecodeError):
            return self._respond(422, req.path)
        return self._respond(200, req.path)

    async def _post_reload(self, req: Request) -> Response:
        log.debug("control: reloading app via control plane")
        assert self.bus is not None
        self.bus.set_reload_flag()
        self.bus.shutdown()
        return self._respond(200, req.path)

    async def _post_metric(self, req: Request) -> Response:
        assert self.bus is not None
        try:
            metrics = json.loads(req.body.decode() or "null")
            if not isinstance(metrics, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            return self._respond(422, req.path)
        for key, value in metrics.items():
            self.bus.publish(Event(EventCode.METRIC, f"{key}|{value}"))
        return self._respond(200, req.path)

    async def _get_events(self, req: Request) -> Response:
        assert self.bus is not None
        body = json.dumps(
            [
                {"code": e.code.value, "source": e.source}
                for e in self.bus.debug_events()
            ]
        ).encode()
        return self._respond(200, req.path, body, "application/json")

    async def _get_tasks(self, req: Request) -> Response:
        """Live asyncio task table — which actors/timers/execs exist
        right now (the single-event-loop analog of a thread dump)."""
        tasks = sorted(
            t.get_name()
            for t in asyncio.all_tasks()
            if not t.done()
        )
        body = json.dumps(tasks).encode()
        return self._respond(200, req.path, body, "application/json")

    async def _post_maintenance_enable(self, req: Request) -> Response:
        assert self.bus is not None
        self.maintenance = True
        self.bus.publish(GLOBAL_ENTER_MAINTENANCE)
        return self._respond(200, req.path)

    async def _post_maintenance_disable(self, req: Request) -> Response:
        assert self.bus is not None
        self.maintenance = False
        self.bus.publish(GLOBAL_EXIT_MAINTENANCE)
        return self._respond(200, req.path)

    async def _get_maintenance_status(self, req: Request) -> Response:
        body = json.dumps({"maintenance": self.maintenance}).encode()
        return self._respond(200, req.path, body, "application/json")
