"""Model zoo for the supervised TPU workload. Flagship: a decoder-only
transformer designed around the MXU (bf16 matmuls, static shapes,
scan-friendly layers, tensor-parallel head/hidden sharding)."""
from .transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
)

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn"]
