"""Mixture-of-Experts: switch-style routing with expert parallelism.

TPU-first formulation: top-1 (switch) routing expressed entirely as
one-hot einsums — dispatch and combine are batched matmuls the MXU
eats, no gathers/scatters, fully static shapes. Routing is per-token
and drop-free (see moe_layer). Expert weights carry a leading expert
axis sharded over the mesh's ``model`` axis (expert parallelism); XLA
inserts the all-to-alls at the dispatch and combine einsums.

Aux load-balancing loss is the standard switch formulation: E *
sum_e(fraction_of_tokens_e * mean_router_prob_e), minimized at uniform
routing.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def _route(x: jax.Array, router_w: jax.Array):
    """Top-1 switch routing shared by the drop-free and capacity
    layers: returns (probs, gate, onehot, aux_loss)."""
    n_experts = router_w.shape[-1]
    router_logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), router_w.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [b,s,E]
    expert_idx = jnp.argmax(probs, axis=-1)  # [b,s]
    gate = jnp.max(probs, axis=-1)  # [b,s]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)
    fraction = jnp.mean(onehot, axis=(0, 1))
    router_mean = jnp.mean(probs, axis=(0, 1))
    aux_loss = n_experts * jnp.sum(fraction * router_mean)
    return probs, gate, onehot, aux_loss


def moe_layer(
    x: jax.Array,
    router_w: jax.Array,  # [d_model, n_experts]
    w_in: jax.Array,      # [n_experts, d_model, d_ff]
    w_out: jax.Array,     # [n_experts, d_ff, d_model]
) -> Tuple[jax.Array, jax.Array]:
    """Returns (output [b,s,d], aux_loss scalar). x in compute dtype.

    Routing is per-token and drop-free (no capacity bound), so the
    result for any token depends only on that token's features — which
    is what makes incremental decoding bit-identical to the full
    forward. The cost is dense dispatch (each expert processes the full
    masked sequence). For bounded expert compute during training use
    ``moe_layer_capacity``; decoding always uses this drop-free layer
    (models/decode.py rejects capacity configs).
    """
    _probs, gate, onehot, aux_loss = _route(x, router_w)

    # note: no preferred_element_type=f32 on the batched expert einsums
    # — the TPU MXU accumulates bf16 inputs in f32 internally, and the
    # CPU backend's batched dot lacks the bf16->f32 widening variant
    dt = x.dtype
    expert_in = jnp.einsum("bse,bsd->besd", onehot.astype(dt), x)
    hidden = jnp.einsum("besd,edf->besf", expert_in, w_in.astype(dt))
    hidden = jax.nn.gelu(hidden.astype(jnp.float32)).astype(dt)
    expert_out = jnp.einsum("besf,efd->besd", hidden, w_out.astype(dt))
    combine = (onehot * gate[..., None]).astype(dt)
    out = jnp.einsum("bse,besd->bsd", combine, expert_out)
    return out, aux_loss


def moe_layer_capacity(
    x: jax.Array,
    router_w: jax.Array,  # [d_model, n_experts]
    w_in: jax.Array,      # [n_experts, d_model, d_ff]
    w_out: jax.Array,     # [n_experts, d_ff, d_model]
    capacity_factor: float,
) -> Tuple[jax.Array, jax.Array]:
    """Capacity-bounded switch MoE: each expert processes at most
    ``ceil(capacity_factor * s / E)`` tokens per batch row; overflow
    tokens drop to the residual (standard switch training).

    Dispatch is **sparse**: every token knows its queue position within
    its expert (a cumsum over the routing one-hot), so tokens scatter
    straight into static-shape ``[E, capacity, d]`` blocks and results
    gather back by the same slot index. Expert compute AND
    dispatch/combine are O(E*capacity*d) / O(s*d) — no ``[b,s,E,C]``
    one-hot dispatch tensor, no O(s*E*C*d) dispatch einsums. Shapes are
    fully static, so XLA tiles the expert GEMMs on the MXU and (with
    the expert axis sharded over ``model``) inserts all-to-alls at the
    scatter/gather boundaries.

    Inference must use the drop-free ``moe_layer`` (capacity depends on
    sequence length, so this routing cannot match incremental decode —
    models/decode.py enforces that).
    """
    import math

    b, s, d = x.shape
    n_experts = router_w.shape[-1]
    capacity = max(1, math.ceil(capacity_factor * s / n_experts))

    probs, gate, onehot, aux_loss = _route(x, router_w)
    expert_idx = jnp.argmax(probs, axis=-1)  # [b,s]

    # queue position of each token within its expert, per batch row
    pos = jnp.sum(
        (jnp.cumsum(onehot, axis=1) - 1.0) * onehot, axis=-1
    ).astype(jnp.int32)  # [b,s]
    keep = pos < capacity
    # flat slot in the [E*C] dispatch buffer; overflow tokens get an
    # out-of-range slot, which the scatter drops and the gather fills 0
    slot = jnp.where(keep, expert_idx * capacity + pos, n_experts * capacity)

    dt = x.dtype

    def dispatch_row(x_row: jax.Array, slot_row: jax.Array) -> jax.Array:
        buf = jnp.zeros((n_experts * capacity, d), dt)
        return buf.at[slot_row].set(x_row, mode="drop")

    expert_in = jax.vmap(dispatch_row)(x, slot).reshape(
        b, n_experts, capacity, d
    )
    hidden = jnp.einsum("becd,edf->becf", expert_in, w_in.astype(dt))
    hidden = jax.nn.gelu(hidden.astype(jnp.float32)).astype(dt)
    expert_out = jnp.einsum("becf,efd->becd", hidden, w_out.astype(dt))

    def gather_row(flat_row: jax.Array, slot_row: jax.Array) -> jax.Array:
        return jnp.take(
            flat_row, slot_row, axis=0, mode="fill", fill_value=0
        )

    out = jax.vmap(gather_row)(
        expert_out.reshape(b, n_experts * capacity, d), slot
    )
    out = out * (gate * keep).astype(dt)[..., None]
    return out, aux_loss
