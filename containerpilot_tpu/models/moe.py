"""Mixture-of-Experts: switch-style routing with expert parallelism.

TPU-first formulation: top-1 (switch) routing expressed entirely as
one-hot einsums — dispatch and combine are batched matmuls the MXU
eats, no gathers/scatters, fully static shapes. Routing is per-token
and drop-free (see moe_layer). Expert weights carry a leading expert
axis sharded over the mesh's ``model`` axis (expert parallelism); XLA
inserts the all-to-alls at the dispatch and combine einsums.

Aux load-balancing loss is the standard switch formulation: E *
sum_e(fraction_of_tokens_e * mean_router_prob_e), minimized at uniform
routing.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def moe_layer(
    x: jax.Array,
    router_w: jax.Array,  # [d_model, n_experts]
    w_in: jax.Array,      # [n_experts, d_model, d_ff]
    w_out: jax.Array,     # [n_experts, d_ff, d_model]
) -> Tuple[jax.Array, jax.Array]:
    """Returns (output [b,s,d], aux_loss scalar). x in compute dtype.

    Routing is per-token and drop-free (no capacity bound), so the
    result for any token depends only on that token's features — which
    is what makes incremental decoding bit-identical to the full
    forward. The cost is dense dispatch (each expert processes the full
    masked sequence); a capacity-bounded sparse dispatch is a
    throughput optimization for a later round and must thread its drop
    state through the KV cache to keep decode parity.
    """
    b, s, d = x.shape
    n_experts = router_w.shape[-1]

    router_logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), router_w.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [b,s,E]
    expert_idx = jnp.argmax(probs, axis=-1)  # [b,s]
    gate = jnp.max(probs, axis=-1)  # [b,s]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)

    # note: no preferred_element_type=f32 on the batched expert einsums
    # — the TPU MXU accumulates bf16 inputs in f32 internally, and the
    # CPU backend's batched dot lacks the bf16->f32 widening variant
    dt = x.dtype
    expert_in = jnp.einsum("bse,bsd->besd", onehot.astype(dt), x)
    hidden = jnp.einsum("besd,edf->besf", expert_in, w_in.astype(dt))
    hidden = jax.nn.gelu(hidden.astype(jnp.float32)).astype(dt)
    expert_out = jnp.einsum("besf,efd->besd", hidden, w_out.astype(dt))
    combine = (onehot * gate[..., None]).astype(dt)
    out = jnp.einsum("bse,besd->bsd", combine, expert_out)

    # switch load-balancing loss
    fraction = jnp.mean(onehot, axis=(0, 1))          # tokens per expert
    router_mean = jnp.mean(probs, axis=(0, 1))        # mean prob per expert
    aux_loss = n_experts * jnp.sum(fraction * router_mean)
    return out, aux_loss
