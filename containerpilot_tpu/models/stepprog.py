"""The step-program interface: one slot-engine driver for every
decode strategy.

The continuous-batching engine (workload/serve_slots.py) used to call
``decode_slots_chunk`` directly, which welded it to the plain
transformer; speculative decoding lived on a legacy one-shot path and
quantized weights composed only by accident. A **step program** is the
seam: it owns the device-resident decode state for a fixed pool of S
slots and exposes five verbs with STATIC shapes per
``(config, S, chunk, K)`` — one compiled program set, no recompiles
as traffic changes:

- ``admit(slot, req, logits, row_cache)`` — write one prefilled
  request into ``slot`` and return its first sampled token (a host
  int). The ENGINE computes the prefill and passes the result in:
  prefix-cache rewind+extend, cp-ring, chunked and plain prefill stay
  engine policy, shared identically by every program.
- ``dispatch(budgets, fused)`` — advance every live slot by up to
  ``rounds * chunk`` tokens (``fused=True``; one ``chunk`` otherwise)
  in ONE logical step, returning an opaque handle. Never syncs the
  host; ``dispatch_cost`` is the number of device dispatches one call
  ships (1 for the fused/plain programs, 2 for draft+verify).
  ``budgets`` is a [S] int array of remaining max_new allowances —
  the early-exit gate, never an emission mask.
- ``tokens(handle)`` — the round trip: fetch the handle's tokens (the
  one deliberate host sync per window) and return
  ``(toks [S, W], valid [S], rounds_run)`` where ``valid[i]`` bounds
  the tokens slot i actually produced (the engine appends
  ``toks[i, :valid[i]]`` through the shared ``append_chunk``
  convention, so eos/max_new capping stays in one place).
- ``retire(slot)`` — free one row (harvest or cancel); pads follow
  until re-admission.
- ``reset()`` — rebuild the device buffers after a failed dispatch
  (the failure died holding the donated pool/state).

``supports_lookahead`` says whether the engine may dispatch window
N+1 before fetching window N (true when the next dispatch does not
depend on host-side decisions about N's tokens — the plain programs;
false for draft/verify, whose next round needs the acceptance
result).

Implementations: :class:`PlainStepProgram` (models/slots.py's chunk +
fused-window programs), ``models.quantized.QuantizedStepProgram``
(the same programs over int8 weights — the forward dequantizes per
layer, so composition is structural) and
``models.speculative.SpeculativeStepProgram`` (draft/verify rounds:
multi-token emission per dispatch). ``make_step_program`` picks the
right default for a params pytree.
"""
from __future__ import annotations

import jax
import numpy as np

from .slots import (
    admit_slot_state,
    decode_slots_chunk,
    decode_slots_window,
    first_sample,
    init_slot_state,
    insert_row,
    retire_slot,
    slot_cache,
)
from .transformer import Params, TransformerConfig


class PlainStepProgram:
    """The plain transformer's step program: the slot pool + the
    device-resident sampling state, advanced by decode_slots_chunk
    (``fused=False``) or the K-round fused window
    (``decode_slots_window``, ``fused=True``) — one device dispatch
    either way. ``out_sharding`` pins output placement (the pod's
    mirror passes fully-replicated)."""

    supports_lookahead = True
    dispatch_cost = 1

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Params,
        max_len: int,
        slots: int,
        chunk: int,
        rounds: int = 1,
        out_sharding=None,
    ) -> None:
        if slots < 1 or chunk < 1 or rounds < 1:
            raise ValueError("slots, chunk and rounds must be >= 1")
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self.chunk = chunk
        self.rounds = rounds
        self.out_sharding = out_sharding
        self.reset()

    def reset(self) -> None:
        self._pool = slot_cache(self.cfg, self.slots, self.max_len)
        self._state = init_slot_state(self.cfg, self.slots)

    def admit(self, slot: int, req, logits, row_cache) -> int:
        """Sample token 0 with the server key convention (row 0 of
        ``req.seed``), write the prefilled row + the whole sampling
        state row in two dispatches, return the first token."""
        cfg = self.cfg
        row_key = jax.random.fold_in(
            jax.random.PRNGKey(req.seed), 0
        )
        first = first_sample(
            logits, row_key, req.temperature, req.top_k, req.top_p,
            cfg, eos_id=req.eos_id, min_new=req.min_new,
            bias_idx=req.bias_idx, bias_val=req.bias_val,
        )
        first_host = int(jax.device_get(first))
        self._pool = insert_row(
            self._pool, row_cache, slot, cfg, self.out_sharding
        )
        done = first_host == req.eos_id or req.max_new <= 1
        self._state = admit_slot_state(
            self._state, slot, cfg,
            last=first, key=row_key,
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, eos_id=req.eos_id, pad_id=req.pad_id,
            min_new=req.min_new, presence=req.presence,
            frequency=req.frequency, bias_idx=req.bias_idx,
            bias_val=req.bias_val, done=done,
            out_sharding=self.out_sharding,
        )
        return first_host

    def retire(self, slot: int) -> None:
        self._state = retire_slot(
            self._state, slot, self.out_sharding
        )

    # cpcheck: hotpath — the fused window dispatch: one device call,
    # zero host syncs (the budgets upload is async and per-window)
    def dispatch(self, budgets, fused: bool):
        if fused and self.rounds > 1:
            self._pool, self._state, toks, run = decode_slots_window(
                self.params, self._pool, self._state, self.cfg,
                self.chunk, self.rounds, budgets, self.out_sharding,
            )
            return toks, run
        self._pool, self._state, toks = decode_slots_chunk(
            self.params, self._pool, self._state, self.cfg,
            self.chunk, self.out_sharding,
        )
        return toks, None

    # cpcheck: hotpath — the one deliberate sync per window
    def tokens(self, handle):
        toks, run = handle
        if run is None:
            toks_host = np.asarray(jax.device_get(toks))  # cpcheck: disable=CP-HOTSYNC the per-window token fetch
            rounds_run = 1
        else:
            toks_host, run_host = jax.device_get((toks, run))  # cpcheck: disable=CP-HOTSYNC the per-window token fetch
            rounds_run = int(run_host)
            toks_host = toks_host[:, : rounds_run * self.chunk]
        valid = np.full(
            (self.slots,), rounds_run * self.chunk, np.int64
        )
        return toks_host, valid, rounds_run


def make_step_program(
    cfg: TransformerConfig,
    params: Params,
    max_len: int,
    slots: int,
    chunk: int,
    rounds: int = 1,
    out_sharding=None,
):
    """The default step program for a params pytree: quantized params
    get the quantized program (same device programs, the composition
    made explicit and validated), everything else the plain one."""
    from .quantized import QuantizedStepProgram, is_quantized

    kind = (
        QuantizedStepProgram if is_quantized(params)
        else PlainStepProgram
    )
    return kind(
        cfg, params, max_len, slots, chunk,
        rounds=rounds, out_sharding=out_sharding,
    )
