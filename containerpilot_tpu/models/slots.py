"""Slot-based continuous decode: the static-shape TPU analog of
in-flight batching.

``generate`` batches rows that start together; a serving system wants
rows that start WHENEVER — a new request should join the decode loop
at the next chunk boundary instead of queueing behind the current
batch's full generation. The XLA-friendly shape for that is a fixed
pool of S slots: every slot owns one cache row and its own position,
the decode step is the single-row ``decode_step`` vmapped over the
slot axis (XLA still batches the matmuls — weights stream from HBM
once per step for all slots), and admission/harvest happen between
fixed-size chunks on the host. All shapes are static: one compiled
chunk program per (config, S, K), no recompiles as traffic changes.

Sampling reproduces ``generate``'s schedule exactly: per-row key =
``jax.random.split(PRNGKey(seed), 1)[0]``, sample i uses
``fold_in(row_key, i)`` with sample 0 drawn from the prefill logits —
so a request's output is byte-identical to a solo ``generate`` call
no matter what it shared the pool with (tested).

Dead slots (finished rows not yet reused) keep decoding garbage —
static shapes — but their writes are harmless: a linear cache's
dynamic_update_slice clamps at the boundary, a sliding-window config's
ring cache (decode.py) wraps within its own row, and either way the
row is wholesale overwritten by the next admission (``insert_row``
replaces the full row INCLUDING its position, so a reused slot holds
nothing of its previous occupant — what makes windows compose with
the pool). Emitted tokens are masked to pad after eos, same as
``generate``.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import (
    BIAS_SLOTS_MAX,
    Cache,
    apply_logit_bias,
    apply_token_penalties,
    count_token,
    decode_step,
    init_cache,
    mask_eos_before_min,
    sample_logits,
)
from .transformer import Params, TransformerConfig


def append_chunk(emitted, toks, max_new: int, eos_id: int) -> bool:
    """The ONE chunk-append convention shared by the slot engine and
    the pod's streamed decode (their outputs are documented as
    byte-identical to generate, so the rules must live in one place):
    append ``toks`` into ``emitted`` capped at ``max_new``, stopping
    at eos inclusive. Returns whether the row ended."""
    for t in toks:
        if len(emitted) >= max_new:
            break
        emitted.append(int(t))
        if int(t) == eos_id:
            break
    return (
        len(emitted) >= max_new
        or (eos_id >= 0 and eos_id in emitted)
    )


def seed_counts(vocab_size: int, first: int, eos_id: int) -> jax.Array:
    """Fresh generated-token counts after sample 0: the just-drawn
    token counts unless it ended the row — matching generate's scan
    exactly (the other half of the shared convention)."""
    counts = jnp.zeros((vocab_size,), jnp.float32)
    if first != eos_id:
        counts = counts.at[first].set(1.0)
    return counts


def slot_cache(cfg: TransformerConfig, slots: int, max_len: int) -> Cache:
    """A pool of ``slots`` single-row caches, stacked on a leading
    slot axis (k/v: [S, layers, 1, length, kv_heads, head_dim];
    pos: [S])."""
    row = init_cache(cfg, 1, max_len)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(
            x[None], (slots,) + x.shape
        ).copy() if x.ndim else jnp.zeros((slots,), x.dtype),
        row,
    )


@functools.lru_cache(maxsize=8)
def _jitted_insert(cfg: TransformerConfig, out_sharding=None):
    """(pool, row_cache, slot) -> pool with the row written at slot.
    donate the pool: insertion must not copy S full cache rows.

    ``out_sharding`` (a NamedSharding, hashable) pins the output
    placement — multi-process serving passes fully-replicated so the
    pool NEVER drifts into whatever sharding GSPMD would pick for
    this program (a drifting pool re-enters the next donating program
    under a different layout; pinning keeps every process's copy
    bit-identical by construction)."""

    def insert(pool: Cache, row: Cache, slot: jax.Array) -> Cache:
        def put(big, small):
            if big.ndim == 1:  # pos: [S] <- scalar
                return lax.dynamic_update_slice(
                    big, small[None].astype(big.dtype), (slot,)
                )
            return lax.dynamic_update_slice(
                big, small[None].astype(big.dtype),
                (slot,) + (0,) * small.ndim,
            )

        return jax.tree.map(put, pool, row)

    return jax.jit(
        insert, donate_argnums=(0,), out_shardings=out_sharding
    )


def insert_row(pool: Cache, row: Cache, slot: int,
               cfg: TransformerConfig, out_sharding=None) -> Cache:
    """Write a freshly prefilled single-row cache into the pool.
    The pool buffer is donated (in-place update)."""
    return _jitted_insert(cfg, out_sharding)(
        pool, row, jnp.asarray(slot, jnp.int32)
    )


@functools.lru_cache(maxsize=8)
def _jitted_chunk(cfg: TransformerConfig, slots: int, chunk: int,
                  out_sharding=None):
    """One compiled program advancing every slot ``chunk`` tokens.

    Operands (all [S] unless noted): pool cache (donated), last
    sampled token, stacked row keys [S, 2], next sample index,
    temperature/top_k/top_p/eos/pad, done mask. Returns (pool, last,
    done, tokens [S, chunk]).
    """
    vstep = jax.vmap(
        lambda params, cache, token: decode_step(
            params, cache, token, cfg
        ),
        in_axes=(None, 0, 0),
    )

    def run(params, pool, last, row_keys, step_idx, temperature,
            top_k, top_p, eos_id, pad_id, min_new, presence,
            frequency, bias_idx, bias_val, counts, done):
        def body(carry, _):
            pool, tok, done, idx, counts = carry
            logits, pool = vstep(params, pool, tok[:, None])  # [S,1,V]
            keys = jax.vmap(jax.random.fold_in)(row_keys, idx)
            masked = apply_token_penalties(
                logits[:, 0, :], counts, presence, frequency
            )
            # always-on operand (the pool program is ONE compile):
            # idx -1 rows add exactly zero, bitwise-neutral
            masked = apply_logit_bias(masked, bias_idx, bias_val)
            masked = mask_eos_before_min(masked, idx, min_new, eos_id)
            nxt = sample_logits(
                masked, keys, temperature, top_k, top_p
            ).astype(jnp.int32)
            nxt = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
            counts = count_token(counts, nxt, ~done)
            return (pool, nxt, done, idx + 1, counts), nxt

        (pool, last, done, _, counts), toks = lax.scan(
            body, (pool, last, done, step_idx, counts), None,
            length=chunk,
        )
        return pool, last, done, counts, toks.T  # [S, chunk]

    return jax.jit(
        run, donate_argnums=(1, 15), out_shardings=out_sharding
    )


def decode_slots_chunk(
    params: Params,
    pool: Cache,
    last: jax.Array,
    row_keys: jax.Array,
    step_idx: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    eos_id: jax.Array,
    pad_id: jax.Array,
    min_new: jax.Array,
    presence: jax.Array,
    frequency: jax.Array,
    bias_idx: jax.Array,
    bias_val: jax.Array,
    counts: jax.Array,
    done: jax.Array,
    cfg: TransformerConfig,
    chunk: int,
    out_sharding=None,
):
    """Advance the whole pool ``chunk`` tokens; see _jitted_chunk.
    ``bias_idx``/``bias_val`` are [S, K] per-slot logit_bias operands
    (-1 = unused slot; serving uses K = BIAS_SLOTS_MAX so one program
    covers every legal request). Returns (pool, last, done, counts,
    tokens [S, chunk]); the pool AND the counts buffer are donated.
    ``out_sharding`` pins every output's placement (see
    _jitted_insert) — the pod passes fully-replicated."""
    slots = int(last.shape[0])
    return _jitted_chunk(cfg, slots, chunk, out_sharding)(
        params, pool, last, row_keys, step_idx, temperature, top_k,
        top_p, eos_id, pad_id, min_new, presence, frequency,
        bias_idx, bias_val, counts, done,
    )


@functools.lru_cache(maxsize=8)
def _jitted_first_sample(cfg: TransformerConfig):
    """Sample token 0 from prefill logits with generate's key
    schedule (fold_in(row_key, 0))."""

    def first(logits, row_key, temperature, top_k, top_p, eos_id,
              min_new, bias_idx, bias_val):
        # counts are empty at sample 0, so penalties are a no-op here
        # by construction — identical to generate's first sample.
        # logit_bias DOES apply at sample 0 (generate biases every
        # draw), hence the operands here.
        key = jax.random.fold_in(row_key, jnp.int32(0))
        masked = apply_logit_bias(
            logits, bias_idx[None], bias_val[None]
        )
        masked = mask_eos_before_min(
            masked, jnp.int32(0), min_new[None], eos_id[None]
        )
        return sample_logits(
            masked, key[None], temperature[None], top_k[None],
            top_p[None],
        )[0].astype(jnp.int32)

    return jax.jit(first)


def first_sample(logits, row_key, temperature, top_k, top_p,
                 cfg: TransformerConfig, eos_id: int = -1,
                 min_new: int = 0, bias_idx=None,
                 bias_val=None) -> jax.Array:
    """logits: [1, vocab] from prefill -> token 0 (scalar).
    ``bias_idx``/``bias_val``: a [K] logit_bias row (None = no bias;
    the default materializes at BIAS_SLOTS_MAX — the width serving
    always passes — so biased and plain callers share one compiled
    program)."""
    if bias_idx is None:
        bias_idx = jnp.full((BIAS_SLOTS_MAX,), -1, jnp.int32)
        bias_val = jnp.zeros((BIAS_SLOTS_MAX,), jnp.float32)
    return _jitted_first_sample(cfg)(
        logits, row_key,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        jnp.asarray(top_p, jnp.float32),
        jnp.asarray(eos_id, jnp.int32),
        jnp.asarray(min_new, jnp.int32),
        jnp.asarray(bias_idx, jnp.int32),
        jnp.asarray(bias_val, jnp.float32),
    )
