"""Slot-based continuous decode: the static-shape TPU analog of
in-flight batching.

``generate`` batches rows that start together; a serving system wants
rows that start WHENEVER — a new request should join the decode loop
at the next chunk boundary instead of queueing behind the current
batch's full generation. The XLA-friendly shape for that is a fixed
pool of S slots: every slot owns one cache row and its own position,
the decode step is the single-row ``decode_step`` vmapped over the
slot axis (XLA still batches the matmuls — weights stream from HBM
once per step for all slots), and admission/harvest happen between
fixed-size chunks on the host. All shapes are static: one compiled
chunk program per (config, S, chunk), plus one fused-window program
per (config, S, chunk, K) that loops K chunk-rounds on device with
early exit (``decode_slots_window``) so the host pays one dispatch
per K rounds — no recompiles as traffic changes.

Sampling reproduces ``generate``'s schedule exactly: per-row key =
``jax.random.split(PRNGKey(seed), 1)[0]``, sample i uses
``fold_in(row_key, i)`` with sample 0 drawn from the prefill logits —
so a request's output is byte-identical to a solo ``generate`` call
no matter what it shared the pool with (tested).

Dead slots (finished rows not yet reused) keep decoding garbage —
static shapes — but their writes are harmless: a linear cache's
dynamic_update_slice clamps at the boundary, a sliding-window config's
ring cache (decode.py) wraps within its own row, and either way the
row is wholesale overwritten by the next admission (``insert_row``
replaces the full row INCLUDING its position, so a reused slot holds
nothing of its previous occupant — what makes windows compose with
the pool). Emitted tokens are masked to pad after eos, same as
``generate``.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import (
    BIAS_SLOTS_MAX,
    Cache,
    apply_logit_bias,
    apply_token_penalties,
    count_token,
    decode_step,
    init_cache,
    mask_eos_before_min,
    sample_logits,
    seed_counts_row,
)
from .transformer import Params, TransformerConfig

# The device-resident per-slot sampling state both serving engines
# carry between chunk rounds (one dict = one donated jit operand):
# everything the chunk program reads besides params and the KV pool.
# It changes ONLY at admission (one row) and retirement (one done
# flag), so keeping it on device removes the ~12 host->device uploads
# the old loop paid per round AND the whole class of zero-copied-
# numpy-mutated-in-place hazards (there is no host buffer left to
# mutate). step_idx advances on device inside the chunk program for
# the same reason.
SLOT_STATE_KEYS = (
    "last", "keys", "step_idx", "temperature", "top_k", "top_p",
    "eos_id", "pad_id", "min_new", "presence", "frequency",
    "bias_idx", "bias_val", "counts", "done",
)


def append_chunk(emitted, toks, max_new: int, eos_id: int) -> bool:
    """The ONE chunk-append convention shared by the slot engine and
    the pod's streamed decode (their outputs are documented as
    byte-identical to generate, so the rules must live in one place):
    append ``toks`` into ``emitted`` capped at ``max_new``, stopping
    at eos inclusive. Returns whether the row ended."""
    for t in toks:
        if len(emitted) >= max_new:
            break
        emitted.append(int(t))
        if int(t) == eos_id:
            break
    return (
        len(emitted) >= max_new
        or (eos_id >= 0 and eos_id in emitted)
    )


def init_slot_state(cfg: TransformerConfig, slots: int) -> dict:
    """Fresh device-resident per-slot sampling state (all slots empty,
    hence done). See SLOT_STATE_KEYS for the contract."""
    return {
        "last": jnp.zeros((slots,), jnp.int32),
        "keys": jnp.zeros((slots, 2), jnp.uint32),
        "step_idx": jnp.zeros((slots,), jnp.int32),
        "temperature": jnp.zeros((slots,), jnp.float32),
        "top_k": jnp.zeros((slots,), jnp.int32),
        "top_p": jnp.zeros((slots,), jnp.float32),
        "eos_id": jnp.full((slots,), -1, jnp.int32),
        "pad_id": jnp.zeros((slots,), jnp.int32),
        "min_new": jnp.zeros((slots,), jnp.int32),
        "presence": jnp.zeros((slots,), jnp.float32),
        "frequency": jnp.zeros((slots,), jnp.float32),
        "bias_idx": jnp.full((slots, BIAS_SLOTS_MAX), -1, jnp.int32),
        "bias_val": jnp.zeros((slots, BIAS_SLOTS_MAX), jnp.float32),
        "counts": jnp.zeros((slots, cfg.vocab_size), jnp.float32),
        "done": jnp.ones((slots,), jnp.bool_),
    }


@functools.lru_cache(maxsize=8)
def _jitted_admit(cfg: TransformerConfig, out_sharding=None):
    """ONE dispatch writing a whole admission's row into every state
    leaf (the state dict is donated — single-row .at[slot].set per
    leaf, no full-array copies). The counts row seeds on device
    (seed_counts_row) from the first sample, so admission needs no
    extra host round trip for it. ``out_sharding`` pins the output
    placement exactly like _jitted_insert's."""

    def admit(state, slot, last, key, step_idx, temperature, top_k,
              top_p, eos_id, pad_id, min_new, presence, frequency,
              bias_idx, bias_val, done):
        vocab = state["counts"].shape[1]
        row = {
            "last": last, "keys": key, "step_idx": step_idx,
            "temperature": temperature, "top_k": top_k,
            "top_p": top_p, "eos_id": eos_id, "pad_id": pad_id,
            "min_new": min_new, "presence": presence,
            "frequency": frequency, "bias_idx": bias_idx,
            "bias_val": bias_val,
            "counts": seed_counts_row(vocab, last, eos_id),
            "done": done,
        }
        return {
            name: state[name].at[slot].set(
                row[name].astype(state[name].dtype)
            )
            for name in state
        }

    return jax.jit(
        admit, donate_argnums=(0,), out_shardings=out_sharding
    )


def admit_slot_state(
    state: dict, slot: int, cfg: TransformerConfig, *,
    last, key, temperature, top_k, top_p, eos_id, pad_id,
    min_new, presence, frequency, bias_idx, bias_val, done,
    step_idx: int = 1, out_sharding=None,
) -> dict:
    """Write one admitted request's sampling knobs into ``slot``
    across the (donated) state dict in a single dispatch. ``last`` is
    the first sampled token (device scalar or int); the slot's counts
    row seeds from it on device."""
    return _jitted_admit(cfg, out_sharding)(
        state, jnp.asarray(slot, jnp.int32),
        jnp.asarray(last, jnp.int32),
        jnp.asarray(key, jnp.uint32),
        jnp.asarray(step_idx, jnp.int32),
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        jnp.asarray(top_p, jnp.float32),
        jnp.asarray(eos_id, jnp.int32),
        jnp.asarray(pad_id, jnp.int32),
        jnp.asarray(min_new, jnp.int32),
        jnp.asarray(presence, jnp.float32),
        jnp.asarray(frequency, jnp.float32),
        jnp.asarray(bias_idx, jnp.int32),
        jnp.asarray(bias_val, jnp.float32),
        jnp.asarray(done, jnp.bool_),
    )


@functools.lru_cache(maxsize=8)
def _jitted_retire(out_sharding=None):
    return jax.jit(
        lambda done, slot: done.at[slot].set(True),
        donate_argnums=(0,), out_shardings=out_sharding,
    )


def retire_slot(state: dict, slot: int, out_sharding=None) -> dict:
    """Mark ``slot`` done (harvested/cancelled — pads from here until
    re-admission). Only the done leaf is touched; the rest of the
    state rides along untouched until the next admission."""
    new = dict(state)
    new["done"] = _jitted_retire(out_sharding)(
        state["done"], jnp.asarray(slot, jnp.int32)
    )
    return new


def slot_cache(cfg: TransformerConfig, slots: int, max_len: int) -> Cache:
    """A pool of ``slots`` single-row caches, stacked on a leading
    slot axis (k/v: [S, layers, 1, length, kv_heads, head_dim];
    pos: [S])."""
    row = init_cache(cfg, 1, max_len)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(
            x[None], (slots,) + x.shape
        ).copy() if x.ndim else jnp.zeros((slots,), x.dtype),
        row,
    )


@functools.lru_cache(maxsize=8)
def _jitted_insert(cfg: TransformerConfig, out_sharding=None):
    """(pool, row_cache, slot) -> pool with the row written at slot.
    donate the pool: insertion must not copy S full cache rows.

    ``out_sharding`` (a NamedSharding, hashable) pins the output
    placement — multi-process serving passes fully-replicated so the
    pool NEVER drifts into whatever sharding GSPMD would pick for
    this program (a drifting pool re-enters the next donating program
    under a different layout; pinning keeps every process's copy
    bit-identical by construction)."""

    def insert(pool: Cache, row: Cache, slot: jax.Array) -> Cache:
        def put(big, small):
            if big.ndim == 1:  # pos: [S] <- scalar
                return lax.dynamic_update_slice(
                    big, small[None].astype(big.dtype), (slot,)
                )
            return lax.dynamic_update_slice(
                big, small[None].astype(big.dtype),
                (slot,) + (0,) * small.ndim,
            )

        return jax.tree.map(put, pool, row)

    return jax.jit(
        insert, donate_argnums=(0,), out_shardings=out_sharding
    )


def insert_row(pool: Cache, row: Cache, slot: int,
               cfg: TransformerConfig, out_sharding=None) -> Cache:
    """Write a freshly prefilled single-row cache into the pool.
    The pool buffer is donated (in-place update)."""
    return _jitted_insert(cfg, out_sharding)(
        pool, row, jnp.asarray(slot, jnp.int32)
    )


def _vstep(cfg: TransformerConfig):
    """The single-row decode step vmapped over the slot axis — the
    shared device kernel of the chunk AND fused-window programs."""
    return jax.vmap(
        lambda params, cache, token: decode_step(
            params, cache, token, cfg
        ),
        in_axes=(None, 0, 0),
    )


def _round_step_body(params, state, vstep):
    """The ONE per-token step body (scan shape) shared by the chunk
    program and the fused K-round window program: both trace exactly
    this function, so a fused window is the same computation as K
    sequential chunk rounds token for token — the byte-parity
    contract between them holds by construction, not by numerical
    luck. Carry: (pool, last_token, done, step_idx, counts)."""
    row_keys = state["keys"]
    pad_id = state["pad_id"]
    eos_id = state["eos_id"]

    def body(carry, _):
        pool, tok, done, idx, counts = carry
        logits, pool = vstep(params, pool, tok[:, None])  # [S,1,V]
        keys = jax.vmap(jax.random.fold_in)(row_keys, idx)
        masked = apply_token_penalties(
            logits[:, 0, :], counts, state["presence"],
            state["frequency"],
        )
        # always-on operand (the pool program is ONE compile):
        # idx -1 rows add exactly zero, bitwise-neutral
        masked = apply_logit_bias(
            masked, state["bias_idx"], state["bias_val"]
        )
        masked = mask_eos_before_min(
            masked, idx, state["min_new"], eos_id
        )
        nxt = sample_logits(
            masked, keys, state["temperature"], state["top_k"],
            state["top_p"],
        ).astype(jnp.int32)
        nxt = jnp.where(done, pad_id, nxt)
        done = done | (nxt == eos_id)
        counts = count_token(counts, nxt, ~done)
        return (pool, nxt, done, idx + 1, counts), nxt

    return body


@functools.lru_cache(maxsize=8)
def _jitted_chunk(cfg: TransformerConfig, slots: int, chunk: int,
                  out_sharding=None):
    """One compiled program advancing every slot ``chunk`` tokens.

    Operands: the pool cache and the per-slot sampling-state dict
    (SLOT_STATE_KEYS), BOTH donated — the per-round dispatch ships
    exactly three operands (params, pool, state), all already on
    device. Returns (pool, state, tokens [S, chunk]) where the state
    carries the advanced last/done/counts AND step_idx (advanced on
    device — no host buffer to mutate in place, so the historical
    torn-step-index hazard cannot recur); the untouched knob leaves
    alias straight through the donation.
    """
    vstep = _vstep(cfg)

    def run(params, pool, state):
        body = _round_step_body(params, state, vstep)
        (pool, last, done, idx, counts), toks = lax.scan(
            body,
            (pool, state["last"], state["done"], state["step_idx"],
             state["counts"]),
            None, length=chunk,
        )
        new_state = dict(
            state, last=last, done=done, counts=counts,
            step_idx=idx,
        )
        return pool, new_state, toks.T  # [S, chunk]

    return jax.jit(
        run, donate_argnums=(1, 2), out_shardings=out_sharding
    )


@functools.lru_cache(maxsize=8)
def _jitted_window(cfg: TransformerConfig, slots: int, chunk: int,
                   rounds: int, out_sharding=None):
    """K = ``rounds`` chunk-rounds fused into ONE dispatched program:
    a device-side ``lax.while_loop`` whose body is the exact per-step
    scan ``_jitted_chunk`` runs (``_round_step_body``), so the tokens
    a window emits are byte-identical to K sequential chunk
    dispatches. The loop exits EARLY when no slot is live — a slot is
    live while its device ``done`` flag is clear AND it still has
    window budget (``budget`` [S] int32, the host's remaining
    max_new allowance per slot). Budget gates ONLY the exit test,
    never the emission: a slot past its budget keeps decoding real
    (append-discarded) tokens exactly like the sequential engine
    whose host hadn't retired it yet, preserving bit-equality of
    the shared rounds.

    Returns (pool, state, tokens [S, rounds*chunk], rounds_run):
    rounds not executed leave their token columns at the slot's
    pad_id, and the state advances by exactly rounds_run chunks.
    Pool and state are donated like the chunk program's."""
    vstep = _vstep(cfg)

    def run(params, pool, state, budget):
        body = _round_step_body(params, state, vstep)
        pad = state["pad_id"].astype(jnp.int32)
        out0 = jnp.broadcast_to(
            pad[:, None], (slots, rounds * chunk)
        )

        def cond(carry):
            r, _pool, _last, done, _idx, _counts, _out = carry
            return (r < rounds) & jnp.any(
                ~done & (r * chunk < budget)
            )

        def round_body(carry):
            r, pool, last, done, idx, counts, out = carry
            (pool, last, done, idx, counts), toks = lax.scan(
                body, (pool, last, done, idx, counts),
                None, length=chunk,
            )
            out = lax.dynamic_update_slice(
                out, toks.T, (0, r * chunk)
            )
            return (r + 1, pool, last, done, idx, counts, out)

        r, pool, last, done, idx, counts, out = lax.while_loop(
            cond, round_body,
            (jnp.int32(0), pool, state["last"], state["done"],
             state["step_idx"], state["counts"], out0),
        )
        new_state = dict(
            state, last=last, done=done, counts=counts, step_idx=idx,
        )
        return pool, new_state, out, r

    return jax.jit(
        run, donate_argnums=(1, 2), out_shardings=out_sharding
    )


def decode_slots_chunk(
    params: Params,
    pool: Cache,
    state: dict,
    cfg: TransformerConfig,
    chunk: int,
    out_sharding=None,
):
    """Advance the whole pool ``chunk`` tokens; see _jitted_chunk.
    ``state`` is the device-resident per-slot sampling dict
    (init_slot_state / admit_slot_state); its bias_idx/bias_val are
    [S, K] per-slot logit_bias operands (-1 = unused slot; serving
    uses K = BIAS_SLOTS_MAX so one program covers every legal
    request). Returns (pool, state, tokens [S, chunk]); the pool AND
    the whole state dict are donated. ``out_sharding`` pins every
    output's placement (see _jitted_insert) — the pod passes
    fully-replicated."""
    slots = int(state["last"].shape[0])
    return _jitted_chunk(cfg, slots, chunk, out_sharding)(
        params, pool, state
    )


def decode_slots_window(
    params: Params,
    pool: Cache,
    state: dict,
    cfg: TransformerConfig,
    chunk: int,
    rounds: int,
    budget,
    out_sharding=None,
):
    """Advance the whole pool up to ``rounds`` chunk-rounds in ONE
    host->device dispatch (see _jitted_window): the device loops over
    the same per-step body the chunk program runs and exits early
    once every slot is done or out of ``budget`` (a [S] int32 of
    remaining-token allowances — the one small host->device upload a
    window pays, per K rounds instead of per round). Returns
    (pool, state, tokens [S, rounds*chunk], rounds_run); pool and
    state are donated, ``out_sharding`` pins output placement exactly
    like decode_slots_chunk's."""
    slots = int(state["last"].shape[0])
    return _jitted_window(cfg, slots, chunk, rounds, out_sharding)(
        params, pool, state, jnp.asarray(budget, jnp.int32)
    )


@functools.lru_cache(maxsize=8)
def _jitted_first_sample(cfg: TransformerConfig):
    """Sample token 0 from prefill logits with generate's key
    schedule (fold_in(row_key, 0))."""

    def first(logits, row_key, temperature, top_k, top_p, eos_id,
              min_new, bias_idx, bias_val):
        # counts are empty at sample 0, so penalties are a no-op here
        # by construction — identical to generate's first sample.
        # logit_bias DOES apply at sample 0 (generate biases every
        # draw), hence the operands here.
        key = jax.random.fold_in(row_key, jnp.int32(0))
        masked = apply_logit_bias(
            logits, bias_idx[None], bias_val[None]
        )
        masked = mask_eos_before_min(
            masked, jnp.int32(0), min_new[None], eos_id[None]
        )
        return sample_logits(
            masked, key[None], temperature[None], top_k[None],
            top_p[None],
        )[0].astype(jnp.int32)

    return jax.jit(first)


def first_sample(logits, row_key, temperature, top_k, top_p,
                 cfg: TransformerConfig, eos_id: int = -1,
                 min_new: int = 0, bias_idx=None,
                 bias_val=None) -> jax.Array:
    """logits: [1, vocab] from prefill -> token 0 (scalar).
    ``bias_idx``/``bias_val``: a [K] logit_bias row (None = no bias;
    the default materializes at BIAS_SLOTS_MAX — the width serving
    always passes — so biased and plain callers share one compiled
    program)."""
    if bias_idx is None:
        bias_idx = jnp.full((BIAS_SLOTS_MAX,), -1, jnp.int32)
        bias_val = jnp.zeros((BIAS_SLOTS_MAX,), jnp.float32)
    return _jitted_first_sample(cfg)(
        logits, row_key,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        jnp.asarray(top_p, jnp.float32),
        jnp.asarray(eos_id, jnp.int32),
        jnp.asarray(min_new, jnp.int32),
        jnp.asarray(bias_idx, jnp.int32),
        jnp.asarray(bias_val, jnp.float32),
    )
