"""Incremental decoding: prefill + single-token steps with a KV cache.

TPU-first inference for the flagship transformer:

- static shapes throughout — the cache is allocated at ``max_len`` and
  masked by position, so XLA compiles exactly two programs (prefill and
  decode step) regardless of generation length;
- the decode loop is a ``lax.scan`` over steps, the layer stack a
  ``lax.scan`` over stacked layer params (same as training);
- greedy or temperature sampling.

Numerics are identical to the full forward: the parity test asserts
incremental logits match ``forward``'s per-position logits.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .quantized import (
    can_fuse_int8,
    embed_lookup,
    fused_attn_out,
    fused_mlp,
    fused_qkv,
    maybe_dequant_layer,
    maybe_dequant_top,
)
from .transformer import (
    Params,
    TransformerConfig,
    _attn_out,
    _ffn,
    _qkv,
    _rms_norm,
    flash_eligible,
    repeat_kv,
)
from ..ops.attention import NEG_INF, causal_attention
from ..ops.flash import flash_attention_forward

Cache = Dict[str, jax.Array]


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int
) -> Cache:
    """Zeroed KV cache: k/v are [layers, batch, length, kv_heads,
    head_dim] — under GQA the cache holds only the kv heads, which is
    the whole point (n_heads/kv_heads smaller cache).

    With sliding-window attention (cfg.window > 0) the cache is a RING
    of ``min(window, max_len)`` entries — position p lives at slot
    ``p % length`` and old entries are overwritten as the window
    slides, so decode KV memory is bounded by the window, not the
    generation length.

    With ``cfg.kv_int8`` k/v store as int8 with a per-(token, head)
    scale over the head_dim axis — KV memory halves vs bf16,
    composing with both levers above."""
    length = max_len if cfg.window <= 0 else min(cfg.window, max_len)
    shape = (cfg.n_layers, batch, length, cfg.kv_heads, cfg.head_dim)
    cache: Cache = {
        "pos": jnp.zeros((), jnp.int32),  # number of tokens cached
    }
    if cfg.kv_int8:
        cache["k"] = jnp.zeros(shape, jnp.int8)
        cache["v"] = jnp.zeros(shape, jnp.int8)
        cache["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
    else:
        cache["k"] = jnp.zeros(shape, cfg.dtype)
        cache["v"] = jnp.zeros(shape, cfg.dtype)
    return cache


def _kv_quant(x: jax.Array):
    """Symmetric int8 over the head_dim axis via the codebase's one
    quantization formula (ops/quant.py); returns (q int8, scale f32
    without the trailing axis)."""
    from ..ops.quant import quantize_int8_axes

    q, scale = quantize_int8_axes(x, (-1,))
    return q, scale[..., 0]


def _kv_dequant(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _logits(params: Params, x: jax.Array, cfg: TransformerConfig) -> jax.Array:
    x = _rms_norm(x, params["norm_out"])
    return jnp.einsum(
        "bsd,dv->bsv", x, maybe_dequant_top(params, "unembed", cfg.dtype),
        preferred_element_type=jnp.float32,
    )


def prefill(
    params: Params, tokens: jax.Array, cfg: TransformerConfig, max_len: int
) -> Tuple[jax.Array, Cache]:
    """Process the prompt; returns (logits for the last position, cache).

    tokens: [batch, prompt_len] int32; prompt_len <= max_len.
    """
    if cfg.moe_experts > 0 and cfg.moe_train_capacity > 0:
        raise ValueError(
            "incremental decoding requires a serving config with "
            "moe_train_capacity=0 (capacity routing is sequence-length "
            "dependent and cannot match decode)"
        )
    b, s = tokens.shape
    x = embed_lookup(params, tokens, cfg.dtype)

    # long prompts go through the pallas flash kernels, same threshold
    # as training; short prompts stay einsum. The flash path is
    # GQA-native: it reads the unrepeated kv heads straight from the
    # cache layout, skipping the repeat_kv copy. Sliding windows ride
    # both paths (the flash kernels block-skip old KV; the einsum path
    # masks).
    gqa_flash = cfg.attention_fn is None and flash_eligible(
        cfg, s, kind="fwd"
    )
    if cfg.attention_fn is not None:
        attn_fn = cfg.attention_fn
    elif cfg.window > 0:
        import functools as _ft

        attn_fn = _ft.partial(causal_attention, window=cfg.window)
    else:
        attn_fn = causal_attention

    def body(carry, layer_params):
        layer_params = maybe_dequant_layer(layer_params, cfg.dtype)
        q, k, v = _qkv(carry, layer_params, cfg)
        if cfg.kv_int8:
            # attention reads the quantization roundtrip, exactly what
            # any later decode reads from the cache — prefill,
            # chunked_prefill, and decode stay numerically consistent
            k = _kv_dequant(*_kv_quant(k), cfg.dtype)
            v = _kv_dequant(*_kv_quant(v), cfg.dtype)
        if gqa_flash:
            from ..ops import tuning as _tuning

            fq, fk = _tuning.pick_blocks("fwd", s)
            attn = flash_attention_forward(
                q, k, v, block_q=fq, block_k=fk, window=cfg.window
            )
        elif getattr(attn_fn, "gqa_native", False):
            # ring attention (context-parallel prefill): the ring
            # rotates the SMALL grouped K/V over ICI — repeating
            # first would ship n_heads/kv_heads x more bytes per hop
            # (transformer.py honors the same flag)
            attn = attn_fn(q, k, v)
        else:
            attn = attn_fn(
                q, repeat_kv(k, cfg.n_heads), repeat_kv(v, cfg.n_heads)
            )
        out, _aux = _ffn(
            _attn_out(carry, attn, layer_params, cfg), layer_params, cfg
        )
        return out, (k, v)  # cache stores the unrepeated kv heads

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    cache = init_cache(cfg, b, max_len)
    length = cache["k"].shape[2]
    writes = {"k": ks, "v": vs}
    if cfg.kv_int8:
        writes["k"], writes["k_scale"] = _kv_quant(ks)
        writes["v"], writes["v_scale"] = _kv_quant(vs)
    if s > length:
        # ring cache smaller than the prompt: keep the last `length`
        # positions, each at its slot p % length (static scatter)
        import numpy as _np

        slots = _np.arange(s - length, s) % length
        for name, arr in writes.items():
            cache[name] = cache[name].at[:, :, slots].set(
                arr[:, :, s - length:]
            )
    else:
        for name, arr in writes.items():
            cache[name] = lax.dynamic_update_slice(
                cache[name], arr, (0,) * cache[name].ndim
            )
    cache["pos"] = jnp.asarray(s, jnp.int32)
    logits = _logits(params, x[:, -1:, :], cfg)
    return logits[:, 0, :], cache


def chunked_prefill(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    max_len: int,
    chunk_len: int = 512,
) -> Tuple[jax.Array, Cache]:
    """``prefill`` in fixed-size pieces: the prompt streams through
    ``decode_chunk`` ``chunk_len`` tokens at a time, so peak
    activation memory is O(chunk) instead of O(prompt) — the serving
    answer to prompts long enough that one-shot prefill attention
    blows HBM. Numerics match ``prefill`` (same masked paths).

    Compile churn is bounded by construction: the ragged remainder is
    processed first as (at most) one sub-16 piece plus 16-token
    pieces, then full chunks — so piece lengths come from
    {1..15, 16, chunk_len} regardless of prompt length, instead of a
    fresh program per distinct ``prompt_len % chunk_len``. With a
    sliding window, pieces are capped at the ring length.
    """
    b, s = tokens.shape
    if chunk_len < 1:
        raise ValueError("chunk_len must be >= 1")
    cache = init_cache(cfg, b, max_len)
    if cfg.window > 0:
        chunk_len = min(chunk_len, cache["k"].shape[2])
    return extend_pieces(params, cache, tokens, cfg, chunk_len)


def extend_pieces(
    params: Params,
    cache: Cache,
    tokens: jax.Array,
    cfg: TransformerConfig,
    chunk_len: int,
) -> Tuple[jax.Array, Cache]:
    """Extend ``tokens`` into ``cache`` in bounded pieces — the
    chunked_prefill piece plan ({1..15, 16, chunk_len} lengths), also
    applied by the slot engine's prefix-hit path so a huge cached-hit
    suffix honors the same O(chunk) activation bound as a cold
    prompt. Returns (last logits, cache)."""
    s = tokens.shape[1]
    bucket = min(16, chunk_len)
    lead = s % chunk_len
    plan = []
    if lead % bucket:
        plan.append(lead % bucket)
    plan += [bucket] * (lead // bucket)
    plan += [chunk_len] * (s // chunk_len)
    extend = _jitted_extend(cfg)
    logits = None
    start = 0
    for piece in plan:
        logits, cache = extend(
            params, cache, tokens[:, start:start + piece]
        )
        start += piece
    return logits, cache


def decode_step(
    params: Params, cache: Cache, token: jax.Array, cfg: TransformerConfig
) -> Tuple[jax.Array, Cache]:
    """One autoregressive step. token: [batch] int32 (the token at
    position cache['pos']); returns (logits [batch, vocab], new cache).
    The m=1 case of decode_chunk — one shared implementation keeps
    single-step and speculative-verify numerics identical by
    construction."""
    logits, new_cache = decode_chunk(params, cache, token[:, None], cfg)
    return logits[:, 0, :], new_cache


def decode_chunk(
    params: Params, cache: Cache, tokens: jax.Array, cfg: TransformerConfig
) -> Tuple[jax.Array, Cache]:
    """Process m tokens against the cache in ONE forward — the verify
    step of speculative decoding (m = speculate+1), and the general
    multi-token incremental step.

    ``tokens[:, i]`` sits at position ``pos + i``; ``logits[:, i]``
    predicts position ``pos + i + 1``. Within the chunk attention is
    causal; everything already cached is visible. Numerics match m
    sequential ``decode_step`` calls (and therefore the full forward).
    """
    pos = cache["pos"]
    b, m = tokens.shape
    length = cache["k"].shape[2]
    ring = cfg.window > 0
    if ring and m > length:
        raise ValueError(
            f"decode chunk of {m} tokens exceeds the {length}-slot "
            "window ring; chunk at most `window` tokens"
        )
    x = embed_lookup(params, tokens, cfg.dtype)  # [b, m, d]
    q_idx = jnp.arange(m)
    q_pos = pos + q_idx
    if ring:
        # ring slot j holds the newest position p < pos with
        # p % length == j (negative = never written); a query at
        # pos+i sees ring entries inside its window plus the chunk's
        # own causal prefix — the chunk k/v are CONCATENATED after the
        # ring so in-chunk keys are never read from slots they are
        # about to overwrite
        j = jnp.arange(length)
        ring_pos = pos - 1 - jnp.mod(pos - 1 - j, length)
        ring_ok = (
            (ring_pos[None, :] >= 0)
            & (ring_pos[None, :] > q_pos[:, None] - cfg.window)
        )
        chunk_ok = (
            (q_idx[None, :] <= q_idx[:, None])
            & (q_idx[:, None] - q_idx[None, :] < cfg.window)
        )
        valid = jnp.concatenate([ring_ok, chunk_ok], axis=1)
    else:
        key_pos = jnp.arange(length)
        valid = key_pos[None, :] <= q_pos[:, None]  # [m, length]
    # int8-quantized dense models run their projections through the
    # fused dequant pallas GEMM: decode is weight-streaming bound, so
    # reading int8 instead of dequantized bf16 halves the HBM traffic
    fused = can_fuse_int8(params["layers"], cfg, rows=b * m)

    kv_int8 = cfg.kv_int8

    def body(carry, inputs):
        x = carry
        layer_params, kv_layer = inputs
        k_cache, v_cache = kv_layer["k"], kv_layer["v"]
        if fused:
            q, k, v = fused_qkv(x, layer_params, cfg, offset=pos)
        else:
            layer_params = maybe_dequant_layer(layer_params, cfg.dtype)
            q, k, v = _qkv(x, layer_params, cfg, offset=pos)
        if kv_int8:
            k_q, k_s = _kv_quant(k)
            v_q, v_s = _kv_quant(v)
        if ring:
            # the chunk's own k/v also read through the quantization
            # roundtrip, so chunked decode matches sequential steps
            # (which read their keys back from the quantized ring)
            cached_k = (
                _kv_dequant(k_cache, kv_layer["k_scale"], cfg.dtype)
                if kv_int8 else k_cache
            )
            cached_v = (
                _kv_dequant(v_cache, kv_layer["v_scale"], cfg.dtype)
                if kv_int8 else v_cache
            )
            chunk_k = _kv_dequant(k_q, k_s, cfg.dtype) if kv_int8 else k
            chunk_v = _kv_dequant(v_q, v_s, cfg.dtype) if kv_int8 else v
            keys = jnp.concatenate([cached_k, chunk_k], axis=1)
            values = jnp.concatenate([cached_v, chunk_v], axis=1)
            slots = jnp.mod(pos + q_idx, length)
            new_kv = dict(kv_layer)
            if kv_int8:
                new_kv["k"] = k_cache.at[:, slots].set(k_q)
                new_kv["v"] = v_cache.at[:, slots].set(v_q)
                new_kv["k_scale"] = kv_layer["k_scale"].at[:, slots].set(k_s)
                new_kv["v_scale"] = kv_layer["v_scale"].at[:, slots].set(v_s)
            else:
                new_kv["k"] = k_cache.at[:, slots].set(k)
                new_kv["v"] = v_cache.at[:, slots].set(v)
        else:
            new_kv = dict(kv_layer)
            if kv_int8:
                new_kv["k"] = lax.dynamic_update_slice(
                    k_cache, k_q, (0, pos, 0, 0)
                )
                new_kv["v"] = lax.dynamic_update_slice(
                    v_cache, v_q, (0, pos, 0, 0)
                )
                new_kv["k_scale"] = lax.dynamic_update_slice(
                    kv_layer["k_scale"], k_s, (0, pos, 0)
                )
                new_kv["v_scale"] = lax.dynamic_update_slice(
                    kv_layer["v_scale"], v_s, (0, pos, 0)
                )
                keys = _kv_dequant(
                    new_kv["k"], new_kv["k_scale"], cfg.dtype
                )
                values = _kv_dequant(
                    new_kv["v"], new_kv["v_scale"], cfg.dtype
                )
            else:
                new_kv["k"] = lax.dynamic_update_slice(
                    k_cache, k, (0, pos, 0, 0)
                )
                new_kv["v"] = lax.dynamic_update_slice(
                    v_cache, v, (0, pos, 0, 0)
                )
                keys, values = new_kv["k"], new_kv["v"]
        k_full = repeat_kv(keys, cfg.n_heads)
        v_full = repeat_kv(values, cfg.n_heads)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(jnp.float32) * cfg.head_dim ** -0.5,
            k_full.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # [b, h, m, length(+m)]
        scores = jnp.where(valid[None, None, :, :], scores, NEG_INF)
        weights = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum(
            "bhqk,bkhd->bqhd", weights, v_full,
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        if fused:
            x = fused_attn_out(x, attn, layer_params, cfg)
            x = fused_mlp(x, layer_params, cfg)
        else:
            x = _attn_out(x, attn, layer_params, cfg)
            x, _aux = _ffn(x, layer_params, cfg)
        return x, new_kv

    kv_in = {
        name: cache[name] for name in cache if name != "pos"
    }
    x, new_kv = lax.scan(body, x, (params["layers"], kv_in))
    logits = _logits(params, x, cfg)  # [b, m, vocab]
    return logits, {**new_kv, "pos": pos + m}


import functools


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
    top_k=None,
    top_p=None,
) -> jax.Array:
    """Sample token ids from [batch, vocab] logits.

    Every sampling knob may be a traced scalar OR a per-row [batch]
    array — both filters are static-shape masks over one shared sorted
    copy of the logits, so arbitrary per-request values run in a single
    compiled program, and co-batched requests can each carry their own
    settings. A row whose temperature is <= 0 decodes greedily
    (argmax). top-k keeps the k highest logits (k <= 0 keeps all; ties
    at the k-th value all survive); nucleus keeps the smallest set of
    tokens whose probability mass reaches p (the top token always
    survives; p outside (0,1) keeps all). ``None`` disables a filter
    statically, skipping the sort when both are off.

    ``key`` is one PRNG key shared by the batch, or [batch] stacked
    per-row keys (``jax.random.split`` output) — per-row keys make each
    row's draw independent of what it is batched with.
    """
    b, vocab = logits.shape
    t = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), (b,)
    )[:, None]
    raw = logits.astype(jnp.float32)
    x = raw / jnp.maximum(t, 1e-6)
    if top_k is not None or top_p is not None:
        sorted_logits = jnp.sort(x, axis=-1)[:, ::-1]
        keep = jnp.ones(sorted_logits.shape, bool)
        if top_k is not None:
            k = jnp.broadcast_to(
                jnp.asarray(top_k, jnp.int32), (b,)
            )[:, None]
            k = jnp.where(k > 0, k, vocab)
            keep &= jnp.arange(vocab)[None, :] < k
        if top_p is not None:
            p = jnp.broadcast_to(
                jnp.asarray(top_p, jnp.float32), (b,)
            )[:, None]
            p = jnp.where((p > 0.0) & (p < 1.0), p, 1.0)
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            keep &= (jnp.cumsum(probs, axis=-1) - probs) < p
        threshold = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        x = jnp.where(x < threshold, NEG_INF, x)
    if key.ndim > 1:  # stacked per-row keys
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row)
        )(key, x)
    else:
        sampled = jax.random.categorical(key, x, axis=-1)
    return jnp.where(t[:, 0] <= 0.0, jnp.argmax(raw, axis=-1), sampled)


def mask_eos_before_min(
    logits: jax.Array, step_idx, min_new, eos_id
) -> jax.Array:
    """NEG_INF the eos logit for rows still under their min_new
    floor — sample i honors `min_new_tokens` by construction on every
    decode path (sampled AND greedy draw from the same masked logits).
    eos_id < 0 (disabled) indexes nothing thanks to the suppress
    gate."""
    b, vocab = logits.shape
    eos_row = jnp.broadcast_to(jnp.asarray(eos_id, jnp.int32), (b,))
    min_row = jnp.broadcast_to(jnp.asarray(min_new, jnp.int32), (b,))
    suppress = (step_idx < min_row) & (eos_row >= 0)
    eos_onehot = (
        jnp.arange(vocab)[None, :] == jnp.clip(eos_row, 0)[:, None]
    )
    return jnp.where(
        suppress[:, None] & eos_onehot, NEG_INF, logits
    )


def apply_token_penalties(
    logits: jax.Array,
    counts: jax.Array,
    presence_penalty,
    frequency_penalty,
) -> jax.Array:
    """OpenAI-style repetition control over the GENERATED tokens so
    far (counts: [batch, vocab]): logit -= presence * (count > 0)
    + frequency * count. Generated-only (not the prompt) keeps ONE
    semantic on every decode path — the slot engine and the
    prefix-cache path have no prompt in scope at sampling time. Both
    penalties 0 leave logits bitwise-unchanged."""
    b = logits.shape[0]
    pres = jnp.broadcast_to(
        jnp.asarray(presence_penalty, jnp.float32), (b,)
    )[:, None]
    freq = jnp.broadcast_to(
        jnp.asarray(frequency_penalty, jnp.float32), (b,)
    )[:, None]
    return logits - pres * (counts > 0) - freq * counts


BIAS_SLOTS = 16  # fast-path static per-row logit_bias capacity:
# almost every real request carries a handful of entries, and a
# static K keeps ONE compiled program for all of them
BIAS_SLOTS_MAX = 300  # OpenAI's documented logit_bias cap; a request
# with more than BIAS_SLOTS entries selects this wider static table
# at normalize time (one extra program keyed by the operand shape)
# instead of being rejected


def apply_logit_bias(
    logits: jax.Array, bias_idx: jax.Array, bias_val: jax.Array
) -> jax.Array:
    """OpenAI-style logit_bias: add ``bias_val[b, j]`` to token
    ``bias_idx[b, j]``'s logit before temperature/filters. Sparse and
    static-shape: idx/val are [batch, K] with -1 marking unused slots,
    so arbitrary per-request bias sets run in one compiled program.
    Applied BEFORE the min_new eos mask, so a positive eos bias can
    never break the min_new_tokens floor."""
    b, vocab = logits.shape
    valid = bias_idx >= 0
    idx = jnp.where(valid, bias_idx, 0)
    add = jnp.zeros_like(logits, shape=(b, vocab)).at[
        jnp.arange(b)[:, None], idx
    ].add(jnp.where(valid, bias_val, 0.0).astype(logits.dtype))
    return logits + add


def count_token(
    counts: jax.Array, token: jax.Array, alive
) -> jax.Array:
    """counts[b, token[b]] += 1 for rows still alive (a done row's
    pad filler must not be penalized)."""
    b, vocab = counts.shape
    onehot = (
        jnp.arange(vocab)[None, :] == token[:, None]
    ).astype(counts.dtype)
    return counts + onehot * jnp.asarray(alive, counts.dtype)[:, None]


def seed_counts_row(vocab_size: int, first, eos_id) -> jax.Array:
    """The generated-token counts row right after sample 0 — the
    just-drawn token counts once unless it ended the row, matching
    generate's scan exactly. Lives here with count_token so the whole
    penalty-counts convention has one home; runs INSIDE the slot
    admission program (traceable), so seeding costs no host round
    trip."""
    row = jnp.zeros((vocab_size,), jnp.float32)
    return row.at[first].set(
        jnp.where(first == eos_id, 0.0, 1.0)
    )


def _sampling_scan(cfg, max_new_tokens: int, greedy: bool,
                   filtered: bool, penalized: bool = False,
                   biased: bool = False):
    """The shared decode loop: from (cache, next-token logits) sample
    max_new_tokens with eos/pad handling. Used by the prefill-fused
    generate program and the prefix-cache extend path.

    ``penalized``/``biased`` are static compile-key flags (like
    greedy/filtered): only requests that actually set
    presence/frequency penalties pay the [batch, vocab] counts carry,
    and only requests carrying a logit_bias pay the per-step
    scatter-add — the common plain program is unchanged."""

    def scan(params, cache, logits, row_keys, temperature, top_k,
             top_p, eos_id, pad_id, min_new, presence, frequency,
             bias_idx, bias_val):
        def sample(logits, step_idx, counts):
            if penalized:
                logits = apply_token_penalties(
                    logits, counts, presence, frequency
                )
            if biased:
                logits = apply_logit_bias(logits, bias_idx, bias_val)
            logits = mask_eos_before_min(
                logits, step_idx, min_new, eos_id
            )
            if greedy:
                return jnp.argmax(logits, axis=-1)
            keys = jax.vmap(
                lambda k: jax.random.fold_in(k, step_idx)
            )(row_keys)
            return sample_logits(
                logits, keys, temperature,
                top_k if filtered else None,
                top_p if filtered else None,
            )

        counts = (
            jnp.zeros(logits.shape, jnp.float32) if penalized else None
        )
        first = sample(logits, jnp.int32(0), counts).astype(jnp.int32)
        # rows that have emitted eos keep decoding (static shapes) but
        # emit pad from then on; eos_id == -1 disables the early stop
        # dynamically (token ids are non-negative, so it never matches)
        done = first == eos_id
        if penalized:
            counts = count_token(counts, first, ~done)

        def step(carry, step_idx):
            if penalized:
                cache, token, done, counts = carry
            else:
                cache, token, done = carry
                counts = None
            logits, cache = decode_step(params, cache, token, cfg)
            next_token = sample(
                logits, step_idx, counts
            ).astype(jnp.int32)
            next_token = jnp.where(done, pad_id, next_token)
            done = done | (next_token == eos_id)
            if penalized:
                counts = count_token(counts, next_token, ~done)
                return (cache, next_token, done, counts), next_token
            return (cache, next_token, done), next_token

        init = (
            (cache, first, done, counts) if penalized
            else (cache, first, done)
        )
        _final, rest = lax.scan(
            step, init, jnp.arange(1, max_new_tokens, dtype=jnp.int32),
        )
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    return scan


@functools.lru_cache(maxsize=32)
def _jitted_generate(cfg: TransformerConfig, max_new_tokens: int,
                     max_len: int, greedy: bool, filtered: bool,
                     penalized: bool = False, biased: bool = False):
    """One compiled program per (config, lengths, sampling mode); jit's
    own cache covers distinct prompt lengths and batch sizes.
    Everything request-controlled that doesn't change shapes
    (temperature, top_k, top_p, eos_id, pad_id — all per-row arrays)
    is a traced operand, so per-request variation can't churn this
    cache, and co-batched requests keep independent settings. Each row
    samples from its own key (fold_in per step), so a row's output
    never depends on what it was batched with."""
    scan = _sampling_scan(cfg, max_new_tokens, greedy, filtered,
                          penalized, biased)

    def fn(params, prompt, row_keys, temperature, top_k, top_p, eos_id,
           pad_id, min_new, presence, frequency, bias_idx, bias_val):
        logits, cache = prefill(params, prompt, cfg, max_len)
        return scan(params, cache, logits, row_keys, temperature,
                    top_k, top_p, eos_id, pad_id, min_new, presence,
                    frequency, bias_idx, bias_val)

    return jax.jit(fn)


@functools.lru_cache(maxsize=8)
def _jitted_prefill(cfg: TransformerConfig, max_len: int):
    """Standalone jitted prefill returning (last logits, cache) — the
    prefix-cache entry point (generate's fused program never exposes
    its cache)."""
    return jax.jit(lambda p, t: prefill(p, t, cfg, max_len))


@functools.lru_cache(maxsize=8)
def _jitted_extend(cfg: TransformerConfig):
    """Jitted cache extension: consume a token chunk against a cache
    (decode_chunk) and return (last logits, cache). jit re-specializes
    per chunk length; serving buckets those."""

    def fn(params, cache, chunk):
        logits, cache = decode_chunk(params, cache, chunk, cfg)
        return logits[:, -1, :], cache

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _jitted_decode_from_cache(cfg: TransformerConfig,
                              max_new_tokens: int, greedy: bool,
                              filtered: bool, penalized: bool = False,
                              biased: bool = False):
    return jax.jit(
        _sampling_scan(cfg, max_new_tokens, greedy, filtered,
                       penalized, biased)
    )


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: TransformerConfig,
    max_new_tokens: int,
    max_len: int,
    temperature=0.0,
    rng: jax.Array = None,
    top_k=0,
    top_p=0.0,
    eos_id=-1,
    pad_id=0,
    min_new_tokens=0,
    presence_penalty=0.0,
    frequency_penalty=0.0,
    logit_bias=None,
) -> jax.Array:
    """Autoregressive generation. prompt: [batch, prompt_len] int32;
    returns [batch, max_new_tokens] int32.

    Every sampling knob accepts a scalar or a per-row [batch] sequence
    (so a serving batcher can coalesce requests with different
    settings). ``top_k``/``top_p`` filter the sampling distribution
    (0 disables either; both compose). A row with temperature <= 0
    decodes greedily. ``eos_id >= 0`` enables early stop: once a row
    samples eos, the rest of that row is ``pad_id``;
    ``min_new_tokens`` suppresses eos for a row's first N samples so
    short answers can be floored. ``presence_penalty`` /
    ``frequency_penalty`` subtract from the logits of tokens already
    GENERATED this call (OpenAI semantics over the output, prompt
    excluded — one semantic across every decode path).
    ``logit_bias`` adds per-token offsets to the logits before
    temperature/filters (OpenAI semantics: -100 effectively bans a
    token, +100 effectively forces it) — one ``{token_id: bias}``
    dict for the whole batch or a per-row list of dicts, at most
    BIAS_SLOTS_MAX (= OpenAI's 300) entries per row — rows within
    BIAS_SLOTS ride the fast-path program; applied before the min_new
    eos mask
    so a positive eos bias cannot break the floor. ``rng`` is one
    key (split per row internally) or [batch] stacked per-row keys —
    per-row keys keep each row's output independent of co-batched
    rows.
    """
    operands = _normalize_sampling(
        cfg, prompt.shape[0], max_new_tokens, temperature, rng, top_k,
        top_p, eos_id, pad_id, min_new_tokens, presence_penalty,
        frequency_penalty, logit_bias,
    )
    if prompt.shape[1] + max_new_tokens > max_len:
        # an overflowing decode would silently clamp cache writes onto
        # the last slot and return garbage — fail loudly instead
        raise ValueError(
            f"prompt_len {prompt.shape[1]} + max_new_tokens "
            f"{max_new_tokens} exceeds max_len {max_len}"
        )
    greedy, filtered, penalized, biased, op_arrays = operands
    fn = _jitted_generate(
        cfg, max_new_tokens, max_len, greedy, filtered, penalized,
        biased,
    )
    return fn(params, prompt, *op_arrays)


def _normalize_sampling(cfg, b, max_new_tokens, temperature, rng,
                        top_k, top_p, eos_id, pad_id,
                        min_new_tokens=0, presence_penalty=0.0,
                        frequency_penalty=0.0, logit_bias=None):
    """Validate/broadcast the per-row sampling knobs exactly as
    ``generate`` documents; returns (greedy, filtered, penalized,
    biased, operand arrays in _sampling_scan order after the
    cache/logits)."""
    import numpy as np

    def row(v, dtype, name):
        arr = np.asarray(jax.device_get(v), dtype)
        if arr.ndim == 0:
            arr = np.full((b,), arr)
        if arr.shape != (b,):
            raise ValueError(f"{name} must be a scalar or [batch] array")
        return arr

    t = row(temperature, np.float32, "temperature")
    k_arr = row(top_k, np.int64, "top_k")
    p_arr = row(top_p, np.float64, "top_p")
    eos_arr = row(eos_id, np.int64, "eos_id")
    pad_arr = row(pad_id, np.int64, "pad_id")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if (
        (k_arr < 0).any() or (k_arr > cfg.vocab_size).any()
        or (p_arr < 0.0).any() or (p_arr > 1.0).any()
    ):
        raise ValueError(
            f"top_k must be in [0, vocab {cfg.vocab_size}] and "
            "top_p in [0, 1]"
        )
    if (eos_arr >= cfg.vocab_size).any() or (
        (pad_arr < 0) | (pad_arr >= cfg.vocab_size)
    ).any():
        raise ValueError(
            f"eos_id (< 0 disables) and pad_id must be < vocab "
            f"{cfg.vocab_size}, pad_id non-negative"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    row_keys = rng if rng.ndim > 1 else jax.random.split(rng, b)
    if row_keys.shape[0] != b:
        raise ValueError(f"rng must be one key or {b} stacked keys")
    min_arr = row(min_new_tokens, np.int64, "min_new_tokens")
    if (min_arr < 0).any() or (min_arr > max_new_tokens).any():
        raise ValueError(
            f"min_new_tokens must be in [0, max_new_tokens "
            f"{max_new_tokens}]"
        )
    pres_arr = row(presence_penalty, np.float32, "presence_penalty")
    freq_arr = row(frequency_penalty, np.float32, "frequency_penalty")
    if (np.abs(pres_arr) > 100).any() or (np.abs(freq_arr) > 100).any():
        raise ValueError(
            "presence/frequency penalties must be in [-100, 100]"
        )
    bias_idx, bias_val = normalize_logit_bias(cfg, b, logit_bias)
    greedy = bool((t <= 0.0).all())
    if greedy:
        # dead under argmax; normalize so the compile key can't churn
        k_arr = np.zeros_like(k_arr)
        p_arr = np.zeros_like(p_arr)
    filtered = bool(
        ((k_arr > 0) | ((p_arr > 0.0) & (p_arr < 1.0))).any()
    )
    penalized = bool(pres_arr.any() or freq_arr.any())
    biased = bool((bias_idx >= 0).any())
    return greedy, filtered, penalized, biased, (
        row_keys,
        jnp.asarray(t, jnp.float32), jnp.asarray(k_arr, jnp.int32),
        jnp.asarray(p_arr, jnp.float32),
        jnp.asarray(np.maximum(eos_arr, -1), jnp.int32),
        jnp.asarray(pad_arr, jnp.int32),
        jnp.asarray(min_arr, jnp.int32),
        jnp.asarray(pres_arr, jnp.float32),
        jnp.asarray(freq_arr, jnp.float32),
        jnp.asarray(bias_idx, jnp.int32),
        jnp.asarray(bias_val, jnp.float32),
    )


def normalize_logit_bias(cfg, b: int, logit_bias, slots: int = None):
    """[b, K] (idx, val) arrays from None, one {token: bias} dict
    applied to every row, or a per-row list of such dicts (None
    entries allowed). Unused slots carry idx -1. Validates ids, |bias|
    <= 100 (OpenAI's range), and the per-row entry cap
    (BIAS_SLOTS_MAX = OpenAI's 300).

    ``slots`` pins the static capacity K (fixed-width callers: the
    slot engine and the pod payload). When None, K is chosen per
    request: BIAS_SLOTS while every row fits it (the common fast
    path keeps its one compiled program), else BIAS_SLOTS_MAX — the
    operand shape keys the one extra program big requests compile."""
    import numpy as np

    # parse/validate FIRST so capacity can be picked from the real
    # row sizes; int-coerce keys BEFORE sorting (a dict mixing int
    # and str ids — str is OpenAI's JSON wire form — must fail the
    # documented ValueError way, not a raw TypeError from sorted)
    rows = []
    if logit_bias is not None:
        raw_rows = (
            logit_bias if isinstance(logit_bias, (list, tuple))
            else [logit_bias] * b
        )
        if len(raw_rows) != b:
            raise ValueError(f"logit_bias must be one dict or {b} rows")
        for entry in raw_rows:
            if entry is None:
                rows.append([])
                continue
            if not isinstance(entry, dict):
                raise ValueError("logit_bias rows must be dicts or None")
            try:
                # dict-dedup AFTER coercion (last wins, matching
                # parse_logit_bias): {"5": 100, 5: 100} must not
                # occupy two slots whose scatter-adds SUM past the
                # validated per-entry +/-100 bound
                items = sorted(
                    {int(t): float(v) for t, v in entry.items()}
                    .items()
                )
            except (TypeError, ValueError):
                raise ValueError(
                    "logit_bias keys must be token ids and values "
                    "numbers"
                ) from None
            for tok, bias in items:
                if not 0 <= tok < cfg.vocab_size:
                    raise ValueError(
                        f"logit_bias token ids must be in "
                        f"[0, {cfg.vocab_size})"
                    )
                if not abs(bias) <= 100:
                    raise ValueError(
                        "logit_bias values must be in [-100, 100]"
                    )
            rows.append(items)
    need = max((len(r) for r in rows), default=0)
    if slots is None:
        slots = BIAS_SLOTS if need <= BIAS_SLOTS else BIAS_SLOTS_MAX
    if need > slots:
        raise ValueError(
            f"logit_bias is capped at {slots} tokens per row"
        )
    idx = np.full((b, slots), -1, np.int32)
    val = np.zeros((b, slots), np.float32)
    for r, items in enumerate(rows):
        for j, (tok, bias) in enumerate(items):
            idx[r, j] = tok
            val[r, j] = bias
    return idx, val


def generate_from_cache(
    params: Params,
    cache: Cache,
    logits: jax.Array,
    cfg: TransformerConfig,
    max_new_tokens: int,
    temperature=0.0,
    rng: jax.Array = None,
    top_k=0,
    top_p=0.0,
    eos_id=-1,
    pad_id=0,
    pos: int = None,
    min_new_tokens=0,
    presence_penalty=0.0,
    frequency_penalty=0.0,
    logit_bias=None,
) -> jax.Array:
    """``generate`` starting from an existing (cache, next-token
    logits) pair — the prefix-cache serving path: the caller restored
    or extended a cached prompt prefix (prefill/_jitted_extend) and
    only the new tokens decode here. Same sampling contract as
    ``generate``.

    ``pos`` is the host-known value of cache['pos'] (tokens already
    cached); pass it to get the same loud overflow check ``generate``
    does without a device fetch. When omitted, the scalar is fetched —
    correctness over latency."""
    length = cache["k"].shape[2]
    if cfg.window <= 0 or length < cfg.window:
        # a FULL ring cache (length == window) legally decodes past
        # its length: positions wrap by design and every overwritten
        # slot is already outside the attention window. A linear cache
        # overflows, and so does a TRUNCATED ring (window > max_len at
        # init_cache shrinks the ring to max_len slots): wrapping there
        # overwrites keys still inside the window — in-window context
        # silently dropped.
        if pos is None:
            pos = int(jax.device_get(cache["pos"]))
        if pos + max_new_tokens > length:
            # an overflowing decode would silently clamp cache writes
            # onto the last slot and return garbage — same contract as
            # generate
            raise ValueError(
                f"cache pos {pos} + max_new_tokens {max_new_tokens} "
                f"exceeds cache length {length}"
            )
    greedy, filtered, penalized, biased, op_arrays = (
        _normalize_sampling(
            cfg, logits.shape[0], max_new_tokens, temperature, rng,
            top_k, top_p, eos_id, pad_id, min_new_tokens,
            presence_penalty, frequency_penalty, logit_bias,
        )
    )
    fn = _jitted_decode_from_cache(
        cfg, max_new_tokens, greedy, filtered, penalized, biased
    )
    return fn(params, cache, logits, *op_arrays)
