"""Flagship model: a decoder-only transformer, TPU-first.

Design choices for the MXU/XLA (not a port of anything):

- all matmuls run in bfloat16 with float32 accumulation
  (``preferred_element_type``), params kept in float32;
- static shapes everywhere; the layer stack is a ``lax.scan`` over
  stacked per-layer parameters, so XLA compiles ONE layer body
  regardless of depth (fast compiles, perfect for pjit);
- RMSNorm + rotary embeddings + SwiGLU — all bandwidth-light
  elementwise ops that XLA fuses into the surrounding matmuls;
- head dim and hidden dims sized to multiples of 128 (lane width);
- attention is causal with an optional pallas flash kernel
  (ops/attention.py) for long sequences.

Parameters are a plain pytree (dict), so sharding rules are just
PartitionSpecs over the tree (parallel/sharding.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import tuning
from ..ops.attention import causal_attention
from .quantized import embed_lookup, maybe_dequant_layer, maybe_dequant_top


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    # grouped-query attention: fewer K/V heads than query heads shrinks
    # the KV cache by n_heads/n_kv_heads; 0 means full multi-head
    n_kv_heads: int = 0
    n_layers: int = 4
    d_ff: int = 1408  # SwiGLU hidden (multiple of 128)
    max_seq_len: int = 2048
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16  # compute dtype
    # attention override: None = auto (pallas flash at/after
    # flash_min_seq, XLA causal below it); set to e.g. a mesh-bound
    # ring_attention for context parallelism (parallel/context.py)
    attention_fn: Any = None
    # sequences at/above this length (and 128-aligned) run the pallas
    # flash kernels — fwd AND bwd (ops/flash.py); 0 disables auto-flash;
    # -1 (AUTO) takes the measured flash/XLA crossover from the
    # platform's tuned table (ops/tuning.py), falling back to 1024
    # when none is shipped. Block sizes come from the same table.
    # Mesh-parallel trainers bind the shard_map-wrapped equivalent via
    # parallel.context.flash_parallel_config (pallas calls don't
    # partition under automatic pjit sharding).
    flash_min_seq: int = tuning.AUTO
    # rematerialize each layer in the backward pass instead of saving
    # its activations: the standard TPU trade of MXU FLOPs (~1/3 extra)
    # for HBM. Without it the scan-over-layers saves every layer's MLP
    # hiddens ([L, b, s, d_ff]) and real model sizes blow the 16GB HBM.
    # True/"full" = discard everything per layer; "dots" = keep matmul
    # outputs, recompute only elementwise (less HBM saved, almost no
    # recompute FLOPs); False = save everything.
    remat: Any = True
    # >0: the training loss streams the unembed projection +
    # log-softmax over sequence chunks of this size instead of
    # materializing [batch, seq, vocab] logits (gigabytes at real
    # vocab sizes); the backward recomputes each chunk's logits.
    # 0 = whole-logits loss.
    loss_chunk: int = 0
    # int8 KV cache for serving (models/decode.py): k/v quantize
    # per-(token, head) on write and dequantize on read — KV memory
    # halves vs bf16, composing with GQA and the window ring. Training
    # is unaffected (no cache there).
    kv_int8: bool = False
    # sliding-window attention (Mistral-style): each position attends
    # only the last `window` positions. 0 = full causal. Bounds the
    # decode KV cache to a ring of `window` entries (models/decode.py)
    # and the attention FLOPs to O(s*window).
    window: int = 0
    # mixture-of-experts: 0 = dense SwiGLU; >0 replaces the MLP with
    # switch-routed experts (models/moe.py — drop-free routing, expert
    # axis sharded over the mesh's "model" axis for expert parallelism)
    moe_experts: int = 0
    moe_aux_weight: float = 0.01
    # >0 enables capacity-bounded expert compute for TRAINING (tokens
    # past ceil(factor*s/E) per expert drop to the residual — standard
    # switch training). Inference/serving configs must leave this 0:
    # capacity routing can't match incremental decode.
    moe_train_capacity: float = 0.0

    def __post_init__(self) -> None:
        if self.moe_train_capacity > 0 and self.moe_experts == 0:
            raise ValueError(
                "moe_train_capacity requires moe_experts > 0"
            )
        if self.remat not in (True, False, "full", "dots", "none"):
            raise ValueError(
                f"remat must be True/False/'full'/'dots'/'none', "
                f"got {self.remat!r}"
            )
        if self.loss_chunk < 0:
            raise ValueError(
                f"loss_chunk must be >= 0, got {self.loss_chunk}"
            )

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads or self.n_heads
        assert self.n_heads % kv == 0, "n_heads must divide by n_kv_heads"
        return kv


Params = Dict[str, Any]

FLASH_BLOCK = 128


def flash_eligible(
    cfg: "TransformerConfig", seq: int, kind: str = "train"
) -> bool:
    """True when the auto-selected attention should be the pallas flash
    path: at/above the (possibly table-resolved) threshold and
    block-aligned. ``kind`` picks which measured crossover an AUTO
    threshold resolves through — 'train' for the differentiable path,
    'fwd' for inference prefill. A sliding window must itself be
    block-aligned for the kernels' block-skip logic."""
    min_seq = tuning.resolve_min_seq(cfg.flash_min_seq, kind=kind)
    return (
        min_seq > 0
        and seq >= min_seq
        and seq % FLASH_BLOCK == 0
        and (cfg.window == 0 or cfg.window % FLASH_BLOCK == 0)
    )


def _auto_attention(cfg: "TransformerConfig", seq: int) -> Any:
    import functools

    if flash_eligible(cfg, seq):
        from ..ops.flash import flash_attention

        # 'train' blocks: forward() is the differentiable path, so one
        # custom_vjp call carries fwd AND bwd through these blocks
        bq, bk = tuning.pick_blocks("train", seq)
        return functools.partial(
            flash_attention, block_q=bq, block_k=bk, window=cfg.window
        )
    if cfg.window > 0:
        return functools.partial(causal_attention, window=cfg.window)
    return causal_attention


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    """Initialize parameters as stacked-per-layer arrays (leading axis =
    layer), ready for the scan-based forward."""
    k_emb, k_attn, k_mlp, k_out = jax.random.split(rng, 4)
    d, h, hd, f, L = (
        cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers,
    )
    kv = cfg.kv_heads

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5))

    ks = jax.random.split(k_attn, 4)
    km = jax.random.split(k_mlp, 3)
    layers: Dict[str, Any] = {
        # attention projections, stacked over layers
        "wq": dense(ks[0], (L, d, h, hd), d),
        "wk": dense(ks[1], (L, d, kv, hd), d),
        "wv": dense(ks[2], (L, d, kv, hd), d),
        "wo": dense(ks[3], (L, h, hd, d), h * hd),
        "norm_attn": jnp.ones((L, d), jnp.float32),
        "norm_mlp": jnp.ones((L, d), jnp.float32),
    }
    if cfg.moe_experts > 0:
        E = cfg.moe_experts
        layers["router"] = dense(km[0], (L, d, E), d)
        layers["moe_w_in"] = dense(km[1], (L, E, d, f), d)
        layers["moe_w_out"] = dense(km[2], (L, E, f, d), f)
    else:
        layers["w_gate"] = dense(km[0], (L, d, f), d)
        layers["w_up"] = dense(km[1], (L, d, f), d)
        layers["w_down"] = dense(km[2], (L, f, d), f)
    return {
        "embed": jax.random.normal(k_emb, (cfg.vocab_size, d), jnp.float32)
        * 0.02,
        "layers": layers,
        "norm_out": jnp.ones((d,), jnp.float32),
        "unembed": dense(k_out, (d, cfg.vocab_size), d),
    }


def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def _rope(x: jax.Array, theta: float, offset: Any = 0) -> jax.Array:
    """Rotary position embedding over the last (head_dim) axis.
    x: [batch, seq, heads, head_dim]; ``offset`` shifts the absolute
    positions (needed by incremental decoding — models/decode.py)."""
    b, s, h, hd = x.shape
    half = hd // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    positions = offset + jnp.arange(s, dtype=jnp.float32)
    angles = positions[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _qkv(
    x: jax.Array,
    layer_params: Dict[str, jax.Array],
    cfg: TransformerConfig,
    offset: Any = 0,
):
    """Pre-norm + q/k/v projections with RoPE applied at ``offset``.

    Under GQA, k/v come back with ``cfg.kv_heads`` heads — callers
    either store them that way (the KV cache, which is the point of
    GQA) or broadcast to full heads via ``repeat_kv`` for attention.
    """
    dt = cfg.dtype
    h = _rms_norm(x, layer_params["norm_attn"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer_params["wq"].astype(dt),
                   preferred_element_type=jnp.float32).astype(dt)
    k = jnp.einsum("bsd,dhk->bshk", h, layer_params["wk"].astype(dt),
                   preferred_element_type=jnp.float32).astype(dt)
    v = jnp.einsum("bsd,dhk->bshk", h, layer_params["wv"].astype(dt),
                   preferred_element_type=jnp.float32).astype(dt)
    q = _rope(q, cfg.rope_theta, offset)
    k = _rope(k, cfg.rope_theta, offset)
    return q, k, v


def repeat_kv(x: jax.Array, n_heads: int) -> jax.Array:
    """Broadcast GQA k/v [b,s,kv,hd] to [b,s,n_heads,hd]."""
    kv = x.shape[2]
    if kv == n_heads:
        return x
    return jnp.repeat(x, n_heads // kv, axis=2)


def _attn_out(
    x: jax.Array,
    attn: jax.Array,
    layer_params: Dict[str, jax.Array],
    cfg: TransformerConfig,
) -> jax.Array:
    """Output projection + residual."""
    dt = cfg.dtype
    attn_out = jnp.einsum("bshk,hkd->bsd", attn,
                          layer_params["wo"].astype(dt),
                          preferred_element_type=jnp.float32).astype(dt)
    return x + attn_out


def _mlp(
    x: jax.Array, layer_params: Dict[str, jax.Array], cfg: TransformerConfig
) -> jax.Array:
    """SwiGLU block + residual."""
    dt = cfg.dtype
    h = _rms_norm(x, layer_params["norm_mlp"])
    gate = jnp.einsum("bsd,df->bsf", h, layer_params["w_gate"].astype(dt),
                      preferred_element_type=jnp.float32)
    up = jnp.einsum("bsd,df->bsf", h, layer_params["w_up"].astype(dt),
                    preferred_element_type=jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(dt)
    down = jnp.einsum("bsf,fd->bsd", act, layer_params["w_down"].astype(dt),
                      preferred_element_type=jnp.float32).astype(dt)
    return x + down


def _ffn(
    x: jax.Array, layer_params: Dict[str, jax.Array], cfg: TransformerConfig
):
    """The feed-forward half: dense SwiGLU or switch-routed experts.
    Returns (x, aux_loss)."""
    if cfg.moe_experts > 0:
        from .moe import moe_layer, moe_layer_capacity

        h = _rms_norm(x, layer_params["norm_mlp"])
        if cfg.moe_train_capacity > 0:
            out, aux = moe_layer_capacity(
                h,
                layer_params["router"],
                layer_params["moe_w_in"],
                layer_params["moe_w_out"],
                cfg.moe_train_capacity,
            )
        else:
            out, aux = moe_layer(
                h,
                layer_params["router"],
                layer_params["moe_w_in"],
                layer_params["moe_w_out"],
            )
        return x + out, aux
    return _mlp(x, layer_params, cfg), jnp.zeros((), jnp.float32)


def _layer(
    x: jax.Array, layer_params: Dict[str, jax.Array], cfg: TransformerConfig
):
    """One transformer block. x: [batch, seq, d_model] in compute dtype.
    Returns (x, aux_loss)."""
    layer_params = maybe_dequant_layer(layer_params, cfg.dtype)
    q, k, v = _qkv(x, layer_params, cfg)
    attn_fn = cfg.attention_fn or _auto_attention(cfg, q.shape[1])
    if not getattr(attn_fn, "gqa_native", False):
        # fns that handle grouped kv themselves (e.g. ring attention)
        # get the small K/V — rotating the unrepeated heads over ICI is
        # the point of GQA; everything else gets full heads
        k = repeat_kv(k, cfg.n_heads)
        v = repeat_kv(v, cfg.n_heads)
    attn = attn_fn(q, k, v)
    x = _attn_out(x, attn, layer_params, cfg)
    return _ffn(x, layer_params, cfg)


def forward_hidden(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
):
    """tokens: [batch, seq] int32 -> (final normed hidden
    [batch, seq, d_model], aux_loss scalar) — everything up to (not
    including) the unembed projection, so losses may stream the vocab
    projection in pieces (chunked cross-entropy) instead of
    materializing [batch, seq, vocab] logits.

    The layer stack is a lax.scan over stacked layer params: one
    compiled block body, L iterations, rematerialization-friendly.
    """
    x = embed_lookup(params, tokens, cfg.dtype)

    def body(carry, layer_params):
        x, aux = carry
        x, layer_aux = _layer(x, layer_params, cfg)
        return (x, aux + layer_aux), None

    if cfg.remat and cfg.remat != "none":
        # remat="dots" keeps the MXU outputs (the expensive matmuls)
        # and recomputes only elementwise work in the backward pass —
        # most of full remat's memory win at a fraction of its ~1/3
        # recompute FLOPs. True/"full" discards everything per layer.
        if cfg.remat == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable,
            )
        else:
            body = jax.checkpoint(body)
    (x, aux), _ = lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), params["layers"]
    )
    return _rms_norm(x, params["norm_out"]), aux


def forward_with_aux(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
):
    """tokens: [batch, seq] int32 -> (logits [batch, seq, vocab] f32,
    aux_loss scalar — MoE load balance; zero for dense models)."""
    x, aux = forward_hidden(params, tokens, cfg)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, maybe_dequant_top(params, "unembed", cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits, aux


def forward(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
) -> jax.Array:
    """tokens: [batch, seq] int32 -> logits [batch, seq, vocab] f32."""
    return forward_with_aux(params, tokens, cfg)[0]


def _ce_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-position negative log-likelihood — the ONE cross-entropy
    core shared by the whole-logits and chunked losses, so a change
    to the objective (z-loss, label smoothing, soft-capping) cannot
    silently apply to only one path."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def next_token_loss(
    logits: jax.Array,
    aux: jax.Array,
    tokens: jax.Array,
    cfg: TransformerConfig,
) -> jax.Array:
    """Next-token CE over logits for tokens[:, :-1], plus weighted MoE
    aux — shared by the plain and pipelined losses."""
    return jnp.mean(_ce_nll(logits, tokens[:, 1:])) + (
        cfg.moe_aux_weight * aux
    )


def _chunked_next_token_loss(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
) -> jax.Array:
    """CE without ever materializing the full [b, s, vocab] logits:
    the unembed projection + log-softmax + gather run over sequence
    chunks inside a remat'd scan, so peak activation memory for the
    loss head is [b, loss_chunk, vocab] (the backward recomputes each
    chunk's logits — one extra unembed matmul, a few percent of step
    FLOPs, against gigabytes of saved HBM at real vocab sizes)."""
    x, aux = forward_hidden(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    b, s, d = x.shape
    chunk = min(cfg.loss_chunk, s)
    n = -(-s // chunk)
    pad = n * chunk - s
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    mask = (jnp.arange(n * chunk) < s)[None, :]  # [1, n*chunk]
    unembed = maybe_dequant_top(params, "unembed", cfg.dtype)

    x_chunks = x.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n,b,c,d]
    t_chunks = targets.reshape(b, n, chunk).swapaxes(0, 1)
    m_chunks = mask.reshape(1, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def piece(total, inputs):
        xc, tc, mc = inputs
        logits = jnp.einsum(
            "bcd,dv->bcv", xc, unembed,
            preferred_element_type=jnp.float32,
        )
        return total + jnp.sum(_ce_nll(logits, tc) * mc), None

    total, _ = lax.scan(
        piece, jnp.zeros((), jnp.float32), (x_chunks, t_chunks, m_chunks)
    )
    return total / (b * s) + cfg.moe_aux_weight * aux


def loss_fn(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
) -> jax.Array:
    """Next-token cross-entropy (+ weighted MoE aux loss when routed).
    ``cfg.loss_chunk > 0`` streams the vocab projection in sequence
    chunks instead of materializing full logits."""
    if cfg.loss_chunk > 0:
        return _chunked_next_token_loss(params, tokens, cfg)
    logits, aux = forward_with_aux(params, tokens[:, :-1], cfg)
    return next_token_loss(logits, aux, tokens, cfg)
