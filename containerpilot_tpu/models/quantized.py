"""Model-level weight-only int8: quantize a trained param pytree and
run the same forward/decode code on it.

``quantize_model_params`` converts every large matmul weight (attention
projections, MLP/MoE, embed/unembed) to int8 with broadcast-ready
per-output-channel scales; norms stay float. Two execution paths:

- **dense dequant** (``maybe_dequant_layer``): rebuild one layer's
  bf16 weights inside the scan body — quantized and full-precision
  params flow through identical math. Used for training-size token
  counts and any non-tile-aligned/MoE model.
- **fused int8** (``fused_qkv``/``fused_attn_out``/``fused_mlp``):
  the decode step's projections run through ops/quant.py's pallas
  dequant-GEMM, so weights stream from HBM as int8 and upcast in
  VMEM — half the weight traffic in the weight-streaming-bound decode
  regime. Selected by ``can_fuse_int8`` (models/decode.py wires it).

Resident weight memory shrinks ~4x either way (int8 vs f32 masters).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

# layers-dict keys to quantize -> axes reduced for the scale (the input
# axes of the matmul; remaining axes are output channels). Leading axis
# 0 is the stacked-layer axis, never reduced.
_LAYER_QUANT_AXES: Dict[str, Tuple[int, ...]] = {
    "wq": (1,),        # [L, d, h, hd]: reduce d
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),      # [L, h, hd, d]: reduce h, hd
    "w_gate": (1,),    # [L, d, f]
    "w_up": (1,),
    "w_down": (1,),    # [L, f, d]
    "moe_w_in": (2,),  # [L, E, d, f]: reduce d (per expert)
    "moe_w_out": (2,), # [L, E, f, d]
}

_TOP_QUANT_AXES: Dict[str, Tuple[int, ...]] = {
    "embed": (1,),     # [vocab, d]: reduce d -> scale per vocab row
    "unembed": (0,),   # [d, vocab]: reduce d -> scale per vocab col
}


def _quantize_tensor(
    w: jax.Array, axes: Tuple[int, ...]
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 with scales keepdims-shaped for one-multiply
    dequant (and clean slicing through the stacked-layer axis)."""
    from ..ops.quant import quantize_int8_axes

    return quantize_int8_axes(w, axes)


def quantize_model_params(params: Any) -> Any:
    """Quantize a transformer param pytree in place-shape: each listed
    weight W becomes W_q (int8) + W_s (f32 scales); others unchanged."""
    out = dict(params)
    layers = dict(params["layers"])
    for key, axes in _LAYER_QUANT_AXES.items():
        if key in layers:
            w_q, scales = _quantize_tensor(layers.pop(key), axes)
            layers[key + "_q"] = w_q
            layers[key + "_s"] = scales
    out["layers"] = layers
    for key, axes in _TOP_QUANT_AXES.items():
        if key in out:
            w_q, scales = _quantize_tensor(out.pop(key), axes)
            out[key + "_q"] = w_q
            out[key + "_s"] = scales
    return out


def is_quantized(params: Any) -> bool:
    return "wq_q" in params.get("layers", {}) or "embed_q" in params


def maybe_dequant_layer(
    layer_params: Dict[str, jax.Array], dtype: Any
) -> Dict[str, jax.Array]:
    """Rebuild a dense layer-params dict from a quantized one (no-op
    for full-precision input). Runs inside the layer scan body, so only
    one layer's weights are ever dense at a time."""
    if "wq_q" not in layer_params and "moe_w_in_q" not in layer_params:
        return layer_params
    dense = dict(layer_params)
    for key in _LAYER_QUANT_AXES:
        q = dense.pop(key + "_q", None)
        s = dense.pop(key + "_s", None)
        if q is not None:
            dense[key] = (q.astype(jnp.float32) * s).astype(dtype)
    return dense


def embed_lookup(params: Any, tokens: jax.Array, dtype: Any) -> jax.Array:
    """Embedding gather that dequantizes only the gathered rows when
    the table is stored int8."""
    if "embed" in params:
        return params["embed"].astype(dtype)[tokens]
    rows = params["embed_q"][tokens].astype(jnp.float32)
    scales = params["embed_s"][tokens][..., 0][..., None]  # [., 1]
    return (rows * scales).astype(dtype)


def maybe_dequant_top(params: Any, key: str, dtype: Any) -> jax.Array:
    """Fetch a top-level tensor, dequantizing if stored int8."""
    if key in params:
        return params[key].astype(dtype)
    q = params[key + "_q"]
    s = params[key + "_s"]
    return (q.astype(jnp.float32) * s).astype(dtype)


def param_bytes(params: Any) -> int:
    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
    )


# ---------------------------------------------------------------------------
# fused int8 serving path: projections through the pallas dequant-GEMM
# ---------------------------------------------------------------------------

# beyond this many rows (batch*seq tokens) the GEMMs are MXU-bound and
# bf16 wins; below it they are weight-streaming-bound and reading int8
# halves the HBM traffic — the decode regime
FUSED_MAX_ROWS = 256

_GEMM_TILE = 128


def can_fuse_int8(
    layers: Dict[str, jax.Array], cfg: Any, rows: int
) -> bool:
    """True when the decode-step projections can run through the fused
    int8 pallas GEMM: dense (non-MoE) quantized weights, a
    weight-streaming-bound row count, and tile-aligned dims."""
    if "wq_q" not in layers or "w_gate_q" not in layers:
        return False
    if rows > FUSED_MAX_ROWS:
        return False
    d = cfg.d_model
    kv_out = cfg.kv_heads * cfg.head_dim
    return (
        d % _GEMM_TILE == 0
        and kv_out % _GEMM_TILE == 0
        and cfg.d_ff % _GEMM_TILE == 0
    )


def _fused_proj(
    h2d: jax.Array, layer_params: Dict[str, jax.Array], key: str
) -> jax.Array:
    """[rows, k] @ dequant(W[key]) via the pallas kernel; W's non-layer
    leading axes flatten to the GEMM's (k, n)."""
    from ..ops.quant import int8_matmul_padded

    w_q = layer_params[key + "_q"]
    k = h2d.shape[-1]
    return int8_matmul_padded(
        h2d,
        w_q.reshape(k, -1),
        layer_params[key + "_s"].reshape(-1),
    )


def fused_qkv(
    x: jax.Array, layer_params: Dict[str, jax.Array], cfg: Any, offset: Any
):
    """The _qkv contract (pre-norm, projections, RoPE) with the
    projections running int8-fused — weights stream from HBM as int8
    and dequantize in VMEM (ops/quant.py)."""
    from .transformer import _rms_norm, _rope

    b, s, d = x.shape
    h = _rms_norm(x, layer_params["norm_attn"]).reshape(b * s, d)
    hd = cfg.head_dim
    q = _fused_proj(h, layer_params, "wq").reshape(b, s, cfg.n_heads, hd)
    k = _fused_proj(h, layer_params, "wk").reshape(b, s, cfg.kv_heads, hd)
    v = _fused_proj(h, layer_params, "wv").reshape(b, s, cfg.kv_heads, hd)
    q = _rope(q, cfg.rope_theta, offset)
    k = _rope(k, cfg.rope_theta, offset)
    return q, k, v


def fused_attn_out(
    x: jax.Array,
    attn: jax.Array,
    layer_params: Dict[str, jax.Array],
    cfg: Any,
) -> jax.Array:
    """Output projection + residual, int8-fused (wo is [h, hd, d]:
    the h*hd axes flatten to the GEMM's k)."""
    b, s, h, hd = attn.shape
    out = _fused_proj(
        attn.reshape(b * s, h * hd), layer_params, "wo"
    ).reshape(b, s, -1)
    return x + out


def fused_mlp(
    x: jax.Array, layer_params: Dict[str, jax.Array], cfg: Any
) -> jax.Array:
    """SwiGLU block + residual with all three GEMMs int8-fused."""
    from .transformer import _rms_norm

    b, s, d = x.shape
    h = _rms_norm(x, layer_params["norm_mlp"]).reshape(b * s, d)
    gate = _fused_proj(h, layer_params, "w_gate").astype(jnp.float32)
    up = _fused_proj(h, layer_params, "w_up").astype(jnp.float32)
    act = (jax.nn.silu(gate) * up).astype(cfg.dtype)
    down = _fused_proj(act, layer_params, "w_down").reshape(b, s, d)
    return x + down


# ---------------------------------------------------------------------------
# step-program face: int8 weights under the slot engine
# ---------------------------------------------------------------------------

# Defined lazily (PEP 562 module __getattr__): transformer.py imports
# this module at its top, and the step-program base lives in
# stepprog.py which imports transformer — an eager subclass here
# would close that cycle against a half-initialized module.
_QUANTIZED_PROGRAM = None


def _quantized_program_class():
    global _QUANTIZED_PROGRAM
    if _QUANTIZED_PROGRAM is not None:
        return _QUANTIZED_PROGRAM
    from .stepprog import PlainStepProgram

    class QuantizedStepProgram(PlainStepProgram):
        """Weight-only-int8 step program for the slot engine
        (models/stepprog.py's protocol): the SAME chunk and
        fused-window device programs as the plain transformer — the
        forward dequantizes one layer at a time inside its scan body
        (``maybe_dequant_layer``) or runs the fused int8 GEMMs
        (``can_fuse_int8``), so quantized weights compose with
        slots/prefix-cache/kvtier/pod parity structurally rather than
        by accident. This class makes the composition EXPLICIT: it
        validates the params really are quantized at construction (a
        mis-wired full-precision pytree fails loudly at startup, not
        as 4x the expected HBM at first decode) and is what
        ``make_step_program`` returns for an int8 pytree. Everything
        else is PlainStepProgram — deliberately: one decode
        implementation, two weight layouts."""

        def __init__(self, cfg, params, max_len, slots, chunk,
                     rounds=1, out_sharding=None):
            if not is_quantized(params):
                raise ValueError(
                    "QuantizedStepProgram needs "
                    "quantize_model_params output (no *_q leaves "
                    "found)"
                )
            super().__init__(
                cfg, params, max_len, slots, chunk,
                rounds=rounds, out_sharding=out_sharding,
            )

    _QUANTIZED_PROGRAM = QuantizedStepProgram
    return _QUANTIZED_PROGRAM


def __getattr__(name: str):
    if name == "QuantizedStepProgram":
        return _quantized_program_class()
    raise AttributeError(name)
