"""Model-level weight-only int8: quantize a trained param pytree and
run the same forward/decode code on it.

``quantize_model_params`` converts every large matmul weight (attention
projections, MLP/MoE, embed/unembed) to int8 with broadcast-ready
per-output-channel scales; norms stay float. The model's scan bodies
call ``maybe_dequant_layer`` first, so quantized and full-precision
params flow through identical math — resident weight memory shrinks ~4x (int8 vs the f32 master copies)
(the per-layer bf16 dequant is transient, one layer at a time under the
scan; fusing the dequant into each matmul via ops/quant.py's pallas
GEMM is the round-2 step).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

# layers-dict keys to quantize -> axes reduced for the scale (the input
# axes of the matmul; remaining axes are output channels). Leading axis
# 0 is the stacked-layer axis, never reduced.
_LAYER_QUANT_AXES: Dict[str, Tuple[int, ...]] = {
    "wq": (1,),        # [L, d, h, hd]: reduce d
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),      # [L, h, hd, d]: reduce h, hd
    "w_gate": (1,),    # [L, d, f]
    "w_up": (1,),
    "w_down": (1,),    # [L, f, d]
    "moe_w_in": (2,),  # [L, E, d, f]: reduce d (per expert)
    "moe_w_out": (2,), # [L, E, f, d]
}

_TOP_QUANT_AXES: Dict[str, Tuple[int, ...]] = {
    "embed": (1,),     # [vocab, d]: reduce d -> scale per vocab row
    "unembed": (0,),   # [d, vocab]: reduce d -> scale per vocab col
}


def _quantize_tensor(
    w: jax.Array, axes: Tuple[int, ...]
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 with scales keepdims-shaped for one-multiply
    dequant (and clean slicing through the stacked-layer axis)."""
    from ..ops.quant import quantize_int8_axes

    return quantize_int8_axes(w, axes)


def quantize_model_params(params: Any) -> Any:
    """Quantize a transformer param pytree in place-shape: each listed
    weight W becomes W_q (int8) + W_s (f32 scales); others unchanged."""
    out = dict(params)
    layers = dict(params["layers"])
    for key, axes in _LAYER_QUANT_AXES.items():
        if key in layers:
            w_q, scales = _quantize_tensor(layers.pop(key), axes)
            layers[key + "_q"] = w_q
            layers[key + "_s"] = scales
    out["layers"] = layers
    for key, axes in _TOP_QUANT_AXES.items():
        if key in out:
            w_q, scales = _quantize_tensor(out.pop(key), axes)
            out[key + "_q"] = w_q
            out[key + "_s"] = scales
    return out


def is_quantized(params: Any) -> bool:
    return "wq_q" in params.get("layers", {}) or "embed_q" in params


def maybe_dequant_layer(
    layer_params: Dict[str, jax.Array], dtype: Any
) -> Dict[str, jax.Array]:
    """Rebuild a dense layer-params dict from a quantized one (no-op
    for full-precision input). Runs inside the layer scan body, so only
    one layer's weights are ever dense at a time."""
    if "wq_q" not in layer_params and "moe_w_in_q" not in layer_params:
        return layer_params
    dense = dict(layer_params)
    for key in _LAYER_QUANT_AXES:
        q = dense.pop(key + "_q", None)
        s = dense.pop(key + "_s", None)
        if q is not None:
            dense[key] = (q.astype(jnp.float32) * s).astype(dtype)
    return dense


def embed_lookup(params: Any, tokens: jax.Array, dtype: Any) -> jax.Array:
    """Embedding gather that dequantizes only the gathered rows when
    the table is stored int8."""
    if "embed" in params:
        return params["embed"].astype(dtype)[tokens]
    rows = params["embed_q"][tokens].astype(jnp.float32)
    scales = params["embed_s"][tokens][..., 0][..., None]  # [., 1]
    return (rows * scales).astype(dtype)


def maybe_dequant_top(params: Any, key: str, dtype: Any) -> jax.Array:
    """Fetch a top-level tensor, dequantizing if stored int8."""
    if key in params:
        return params[key].astype(dtype)
    q = params[key + "_q"]
    s = params[key + "_s"]
    return (q.astype(jnp.float32) * s).astype(dtype)


def param_bytes(params: Any) -> int:
    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
    )
