"""Beam-search decoding over the KV cache.

TPU-first shape discipline: the beam IS the batch axis. The prompt
prefills once (batch 1), the cache tiles to ``beam_width`` rows, and
every step is one batched ``decode_step`` over the beams — so the MXU
sees a [beam, ...] matmul, not beam sequential decodes. Beam
reordering is a gather along the cache's batch axis inside the same
compiled scan (no host roundtrips per step).

Finished beams (emitted eos) are frozen: they can only extend with
``pad_id`` at zero added log-probability, the standard trick that
keeps shapes static while finished candidates compete on their final
scores. ``length_penalty`` rescales scores by
``((5 + len) / 6) ** alpha`` (GNMT); 0 disables.

No reference analog (the reference is a process supervisor —
SURVEY.md §2); this is workload-half decoding breadth next to
greedy/sampled ``generate`` and speculative decoding.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import prefill
from .transformer import TransformerConfig, Params
from ..ops.attention import NEG_INF


def _gather_beams(tree, idx):
    """Reorder the beam axis of every cache leaf: all array entries
    (k/v and, under kv_int8, their scales) carry the batch/beam on
    axis 1; pos is scalar."""
    return {
        name: (arr if name == "pos" else arr[:, idx])
        for name, arr in tree.items()
    }


@functools.lru_cache(maxsize=16)
def _jitted_beam(cfg: TransformerConfig, max_new_tokens: int,
                 beam_width: int, length_penalty: float):
    from .decode import decode_step

    def penalize(scores, length):
        if length_penalty <= 0.0:
            return scores
        return scores / (((5.0 + length) / 6.0) ** length_penalty)

    def fn(params, cache, logits, eos_id, pad_id):
        # cache/logits come from prefill OR chunked_prefill (batch 1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # first expansion: top beam_width continuations of the prompt
        scores, first = lax.top_k(logp[0], beam_width)  # [beam]
        first = first.astype(jnp.int32)
        cache = _gather_beams(
            cache, jnp.zeros((beam_width,), jnp.int32)
        )  # tile batch 1 -> beam rows
        done = first == eos_id
        tokens0 = jnp.full(
            (beam_width, max_new_tokens), pad_id, jnp.int32
        ).at[:, 0].set(first)

        def step(carry, step_idx):
            cache, tokens, scores, done, last = carry
            logits, cache = decode_step(params, cache, last, cfg)
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1
            )  # [beam, vocab]
            vocab = logp.shape[-1]
            # finished beams: only pad survives, score unchanged
            frozen = jnp.full((vocab,), NEG_INF).at[pad_id].set(0.0)
            logp = jnp.where(done[:, None], frozen[None, :], logp)
            total = scores[:, None] + logp  # [beam, vocab]
            flat_scores, flat_idx = lax.top_k(
                total.reshape(-1), beam_width
            )
            parent = (flat_idx // vocab).astype(jnp.int32)
            token = (flat_idx % vocab).astype(jnp.int32)
            cache = _gather_beams(cache, parent)
            tokens = tokens[parent].at[:, step_idx].set(token)
            done = done[parent] | (token == eos_id)
            return (cache, tokens, flat_scores, done, token), None

        (cache, tokens, scores, done, _last), _ = lax.scan(
            step, (cache, tokens0, scores, done, first),
            jnp.arange(1, max_new_tokens, dtype=jnp.int32),
        )
        lengths = jnp.where(
            done,
            jnp.argmax(tokens == eos_id, axis=1) + 1,
            max_new_tokens,
        ).astype(jnp.float32)
        final = penalize(scores, lengths)
        best = jnp.argmax(final)
        return tokens[best], final[best]

    return jax.jit(fn)


def validate_beam_args(
    cfg: TransformerConfig, n_rows: int, beam_width: int
) -> None:
    """The request-shape rules shared by ``beam_search`` and the
    serving handler (one wording, no drift): single row, width within
    the vocab, no sliding-window configs (the beam gather permutes
    cache rows; the frozen-beam bookkeeping has not been validated
    against ring wraparound — refuse rather than risk silent
    divergence)."""
    if n_rows != 1:
        raise ValueError("beam search decodes one prompt at a time")
    if not 1 <= beam_width <= cfg.vocab_size:
        raise ValueError(
            f"beam_width must be in [1, vocab {cfg.vocab_size}]"
        )
    if cfg.window > 0:
        raise ValueError(
            "beam search does not support sliding-window configs yet"
        )


def beam_search(
    params: Params,
    prompt: jax.Array,
    cfg: TransformerConfig,
    max_new_tokens: int,
    max_len: int,
    beam_width: int = 4,
    eos_id: int = -1,
    pad_id: int = 0,
    length_penalty: float = 0.0,
    prefill_chunk: int = 0,
) -> Tuple[jax.Array, float]:
    """Deterministic beam search; prompt is [1, prompt_len] int32.
    Returns (tokens [max_new_tokens] int32, score float) — the
    highest-scoring beam, padded with ``pad_id`` past its eos.
    ``beam_width=1`` reduces exactly to greedy ``generate``.
    ``prefill_chunk > 0`` streams the prompt through chunked_prefill
    (peak prefill activations O(chunk)) — the long-prompt regime that
    asks for beams is exactly the one that needs the bound."""
    validate_beam_args(cfg, prompt.shape[0], beam_width)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if prompt.shape[1] + max_new_tokens > max_len:
        raise ValueError(
            f"prompt_len {prompt.shape[1]} + max_new_tokens "
            f"{max_new_tokens} exceeds max_len {max_len}"
        )
    if not 0 <= pad_id < cfg.vocab_size or eos_id >= cfg.vocab_size:
        # an out-of-range pad would be silently clamped by the jitted
        # scatter and pad finished beams with a garbage token
        raise ValueError(
            f"pad_id must be in [0, vocab {cfg.vocab_size}) and "
            f"eos_id < vocab (eos < 0 disables)"
        )
    if prefill_chunk > 0 and prompt.shape[1] > prefill_chunk:
        from .decode import chunked_prefill

        logits, cache = chunked_prefill(
            params, prompt, cfg, max_len, prefill_chunk
        )
    else:
        logits, cache = prefill(params, prompt, cfg, max_len)
    fn = _jitted_beam(
        cfg, max_new_tokens, beam_width, float(length_penalty)
    )
    tokens, score = fn(
        params, cache, logits, jnp.int32(eos_id), jnp.int32(pad_id)
    )
    return tokens, float(score)
