"""LoRA: low-rank adaptation for parameter-efficient fine-tuning.

Fine-tuning the flagship model normally costs 3x its parameter memory
(master weights + adam mu/nu). LoRA freezes the base weights and
learns low-rank deltas `W' = W + alpha * A @ B` on the attention
q/v projections (the classic target set; A's 1/sqrt(r) init keeps the
delta's starting scale rank-independent): trainable state shrinks to
~2*d*r per target per layer, so optimizer memory is negligible and
many adapters can share one frozen base.

TPU-first shape choices:

- LoRA pairs are scan-stacked over layers like every base param
  (`A: [L, d, r]`, `B: [L, r, out]`), so the existing scan forward,
  checkpointing, and sharding machinery apply unchanged;
- training uses the MERGED formulation: `apply_lora` materializes
  `W + delta` once per step outside the layer scan — three einsums
  over the full stack, MXU-shaped, trivially fused by XLA — and JAX
  autodiff through the merge yields dA/dB with the base frozen by
  construction (gradients are only taken w.r.t. the lora pytree);
- `B` initializes to zero, so a fresh adapter reproduces the base
  model exactly (tested) and training starts from the base loss.

Serving merges once at startup: zero runtime overhead, identical
decode path. Int8-quantized bases are not adaptable in-place (merge
into the bf16 weights BEFORE quantizing).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .transformer import Params, TransformerConfig

LORA_TARGETS = ("wq", "wv")  # the classic attention q/v target set


def lora_out_dim(cfg: TransformerConfig, target: str) -> int:
    """Flattened output dim of an attention projection target."""
    if target == "wq":
        return cfg.n_heads * cfg.head_dim
    if target in ("wk", "wv"):
        return cfg.kv_heads * cfg.head_dim
    raise ValueError(
        f"lora target must be one of wq/wk/wv, got {target!r}"
    )


def init_lora_params(
    rng: jax.Array,
    cfg: TransformerConfig,
    rank: int,
    targets: Tuple[str, ...] = LORA_TARGETS,
) -> Dict[str, jax.Array]:
    """Scan-stacked LoRA pairs. A ~ N(0, 1/r) and B = 0, so the
    initial delta is exactly zero."""
    if rank < 1:
        raise ValueError("lora rank must be >= 1")
    L, d = cfg.n_layers, cfg.d_model
    out: Dict[str, jax.Array] = {}
    keys = jax.random.split(rng, len(targets))
    for key, target in zip(keys, targets):
        n = lora_out_dim(cfg, target)
        out[f"{target}_a"] = (
            jax.random.normal(key, (L, d, rank), jnp.float32)
            * rank ** -0.5
        )
        out[f"{target}_b"] = jnp.zeros((L, rank, n), jnp.float32)
    return out


def apply_lora(
    params: Params,
    lora: Dict[str, jax.Array],
    cfg: TransformerConfig,
    alpha: float = 2.0,
) -> Params:
    """Merged weights: `W + (alpha) * A @ B` per target, reshaped to
    the base projection's [L, d, heads, head_dim] layout. ``alpha`` is
    the standard lora scaling (alpha/r folded with A's 1/sqrt(r) init
    leaves a plain multiplier here). Pure function — the base pytree
    is untouched, so gradients w.r.t. ``lora`` leave it frozen."""
    layers = dict(params["layers"])
    targets = sorted({k.rsplit("_", 1)[0] for k in lora})
    for target in targets:
        if f"{target}_q" in params["layers"] or target not in layers:
            raise ValueError(
                f"lora target {target!r} not adaptable (int8-quantized "
                "or missing); merge before quantizing"
            )
        base = layers[target]
        delta = jnp.einsum(
            "ldr,lrn->ldn", lora[f"{target}_a"], lora[f"{target}_b"]
        ) * alpha
        layers[target] = base + delta.reshape(base.shape).astype(base.dtype)
    return {**params, "layers": layers}
