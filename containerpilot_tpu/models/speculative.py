"""Speculative decoding: a cheap draft proposes, the target verifies.

Greedy speculative decoding (the deterministic core of Leviathan et
al.'s scheme): each round the draft model autoregressively proposes
``speculate`` tokens (tiny per-token cost), then the target model
scores the whole proposal in ONE chunked forward (`decode_chunk`) —
one target pass per round instead of one per token. Accepted prefix +
one target-chosen token are emitted; both KV caches roll back to the
accepted position by resetting ``pos`` (stale cache rows beyond pos
are masked/overwritten by design, models/decode.py).

**The output is exactly the target model's greedy decode** for any
draft — the draft only changes speed, never content (tested). Decode
is memory-bandwidth-bound on TPU (the whole model streams from HBM per
token), so accepting n tokens per round divides the dominant cost by
~n at small-batch serving.

The draft can be any same-vocab model; `layer_prefix_draft` builds one
for free from the target's own first N layers (scan-stacked params
slice — no extra checkpoint, self-speculative style).

TPU shape discipline: every jitted helper has static (k, lengths);
only the handful of distinct k values near the sequence end compile
extra variants. The accept/rollback decision is a few-byte host
round-trip per ROUND (not per token) — the same cadence a vanilla
decode loop pays for its sampled token.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import Cache, decode_chunk, decode_step, prefill
from .transformer import Params, TransformerConfig


def layer_prefix_draft(
    params: Params, cfg: TransformerConfig, n_layers: int
) -> Tuple[Params, TransformerConfig]:
    """A free draft model: the target's first ``n_layers`` layers with
    the shared embed/norm/unembed. Scan-stacked layer params make this
    a leading-axis slice — no copy of anything else, no checkpoint."""
    if not 0 < n_layers < cfg.n_layers:
        raise ValueError(
            f"draft layers must be in (0, {cfg.n_layers}), got {n_layers}"
        )
    draft_params = dict(params)
    draft_params["layers"] = jax.tree_util.tree_map(
        lambda a: a[:n_layers], params["layers"]
    )
    return draft_params, dataclasses.replace(cfg, n_layers=n_layers)


@functools.lru_cache(maxsize=64)
def _jit_draft_round(draft_cfg: TransformerConfig, k: int):
    """k greedy proposals from (cache, prev), via k+1 decode steps: the
    extra step consumes the last proposal so the draft cache ends
    holding kv for ALL k proposals (rows pos..pos+k) — aligned with the
    target's (k+1)-token verify chunk for every acceptance count. Its
    own (k+1)-th proposal is discarded."""

    def fn(draft_params, cache: Cache, prev: jax.Array):
        def step(carry, _):
            cache, tok = carry
            logits, cache = decode_step(draft_params, cache, tok, draft_cfg)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt), nxt

        (cache, _last), drafts = lax.scan(
            step, (cache, prev), None, length=k + 1
        )
        return drafts[:k, 0], cache  # [k] for batch 1

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _jit_verify_round(cfg: TransformerConfig, m: int):
    """One chunked target forward over the m = k+1 tokens
    [prev, d_1..d_k]: returns the target's greedy prediction at each
    position — its choices for d_1..d_k plus the bonus token that
    follows a full accept."""

    def fn(params, cache: Cache, chunk: jax.Array):
        logits, cache = decode_chunk(params, cache, chunk, cfg)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)[0], cache

    return jax.jit(fn)


def _clamp_k(speculate: int, remaining: int, max_len: int,
             pos: int) -> int:
    """The ONE per-round k clamp: proposals are bounded by the
    requested budget (``remaining`` tokens still wanted) and the
    cache horizon — the verify chunk writes k+1 rows at pos..pos+k,
    so k <= max_len - pos - 1. Shared by the standalone loop and the
    step program so their round geometry cannot drift."""
    return min(speculate, remaining, max_len - pos - 1)


def _dispatch_round(params, draft_params, cfg: TransformerConfig,
                    draft_cfg: TransformerConfig, cache: Cache,
                    dcache: Cache, prev, k: int):
    """The device half of one draft/verify round (two dispatches, no
    host sync): k greedy draft proposals from ``prev``, then the
    target's verify chunk over [prev, d_1..d_k]. Returns
    (drafts [k], target_choice [k+1], cache, dcache)."""
    drafts, dcache = _jit_draft_round(draft_cfg, k)(
        draft_params, dcache, prev
    )
    chunk = jnp.concatenate([prev, drafts])[None, :]  # [1, k+1]
    target_choice, cache = _jit_verify_round(cfg, k + 1)(
        params, cache, chunk
    )
    return drafts, target_choice, cache, dcache


def _accept_round(drafts_h, target_h, k: int) -> list:
    """The host half: greedy acceptance over the fetched proposals —
    the accepted prefix plus one target-chosen token (the correction
    at the first mismatch, or the bonus after a full accept)."""
    n_acc = 0
    while n_acc < k and int(drafts_h[n_acc]) == int(target_h[n_acc]):
        n_acc += 1
    emitted = [int(t) for t in drafts_h[:n_acc]]
    emitted.append(int(target_h[n_acc]))
    return emitted


def _rewind_caches(cache: Cache, dcache: Cache, pos: int):
    """Roll both caches back to the accepted frontier: the last
    emitted token is NOT processed yet — it is next round's prev.
    Stale rows beyond pos get overwritten by design."""
    p = jnp.asarray(pos, jnp.int32)
    return {**cache, "pos": p}, {**dcache, "pos": p}


def speculative_generate(
    params: Params,
    draft_params: Params,
    prompt: jax.Array,
    cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    max_new_tokens: int,
    max_len: int,
    speculate: int = 4,
    eos_id: int = -1,
) -> Tuple[jax.Array, dict]:
    """Greedy generation via draft-and-verify; batch 1.

    Returns ``(tokens [1, <=max_new_tokens], stats)`` where stats
    counts rounds and accepted drafts. Output is identical to
    ``generate(params, ..., temperature=0)`` up to and including the
    first ``eos_id`` token: with ``eos_id >= 0`` the round loop stops
    early once a round emits it (the per-round host check is free —
    acceptance already fetches the round's tokens), so the row may be
    shorter than ``max_new_tokens``; every token from the first eos on
    is exactly what the servers' eos trim discards. ``eos_id < 0``
    keeps the fixed-length contract.
    """
    if prompt.shape[0] != 1:
        raise ValueError("speculative decoding serves batch 1")
    if speculate < 1:
        raise ValueError("speculate must be >= 1")
    if cfg.window > 0 or draft_cfg.window > 0:
        # the rollback contract ("stale cache rows beyond pos are
        # masked/overwritten") does not hold for a ring cache: the
        # verify chunk overwrites the OLDEST in-window slots before
        # the accept decision, so a rejected round would permanently
        # corrupt window context
        raise ValueError(
            "speculative decoding does not compose with sliding-"
            "window attention (ring-cache writes are destructive; "
            "rollback would leave rejected k/v in live slots)"
        )
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocab")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if prompt.shape[1] + max_new_tokens > max_len:
        raise ValueError(
            f"prompt_len {prompt.shape[1]} + max_new_tokens "
            f"{max_new_tokens} exceeds max_len {max_len}"
        )

    logits, cache = prefill(params, prompt, cfg, max_len)
    _dlogits, dcache = prefill(draft_params, prompt, draft_cfg, max_len)
    prev = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [1]
    out = [int(prev[0])]
    pos = int(cache["pos"])  # == prompt_len; tracked on host
    rounds = 0
    accepted_total = 0

    while len(out) < max_new_tokens and not (
        eos_id >= 0 and out[0] == eos_id  # prefill's token can be eos
    ):
        # the verify chunk [prev, d_1..d_k] writes k+1 cache rows at
        # pos..pos+k (the draft's k+1 steps write the same rows), so
        # the round needs pos + k + 1 <= max_len (_clamp_k)
        k = _clamp_k(speculate, max_new_tokens - len(out), max_len,
                     pos)
        # invariant: pos == prompt_len + len(out) - 1 and
        # prompt_len + max_new_tokens <= max_len, so k >= 1 here
        assert k >= 1, (pos, len(out))
        drafts, target_choice, cache, dcache = _dispatch_round(
            params, draft_params, cfg, draft_cfg, cache, dcache,
            prev, k,
        )
        drafts_h = jax.device_get(drafts)
        target_h = jax.device_get(target_choice)  # [k+1]
        emitted = _accept_round(drafts_h, target_h, k)
        out.extend(emitted)
        rounds += 1
        accepted_total += len(emitted) - 1
        # both models hold rows pos..pos+k and len(emitted) <= k+1,
        # so the rewound frontier never exceeds what each cache
        # actually holds (_rewind_caches)
        pos += len(emitted)
        cache, dcache = _rewind_caches(cache, dcache, pos)
        prev = jnp.asarray([emitted[-1]], jnp.int32)
        if eos_id >= 0 and eos_id in emitted:
            # done: everything past the first eos is trim fodder —
            # stop paying target passes for it (on the pod this also
            # frees the lockstep frontend sooner). SPMD-safe: the
            # check reads the same replicated values every process
            # fetched for acceptance.
            break

    tokens = jnp.asarray([out[:max_new_tokens]], jnp.int32)
    stats = {
        "rounds": rounds,
        "accepted_drafts": accepted_total,
        "tokens": len(out[:max_new_tokens]),
        "mean_accepted": accepted_total / rounds if rounds else 0.0,
    }
    return tokens, stats


def warm_speculative(
    params: Params,
    draft_params: Params,
    cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    speculate: int,
    max_len: int,
) -> None:
    """Compile the speculative path's whole program set.

    Greedy spec traffic dispatches, data-dependently per request, the
    draft and target prefills plus a per-k draft/verify round for every
    k in 1..``speculate`` (acceptance decides each round's k at run
    time) — any variant left uncompiled stalls a live request mid-way
    through a beat-less round. Both servers call this inside their
    startup grace so the no-post-grace-compiles invariant holds for
    ``--draft-layers`` too; one tiny end-to-end generation covers the
    glue programs around the rounds.
    """
    plen = 4
    prompt = jnp.zeros((1, plen), jnp.int32)
    # clamp to what the config can actually serve: a small max_len
    # relative to speculate is a valid configuration (requests clamp k
    # per round), so warmup must not crash on the e2e call's
    # plen + max_new <= max_len contract
    max_new = min(speculate + 2, max_len - plen)
    if max_new >= 1:
        speculative_generate(
            params, draft_params, prompt, cfg, draft_cfg,
            max_new_tokens=max_new, max_len=max_len,
            speculate=speculate,
        )
    _logits, tcache = prefill(params, prompt, cfg, max_len)
    _dlogits, dcache = prefill(draft_params, prompt, draft_cfg, max_len)
    prev = jnp.zeros((1,), jnp.int32)
    # requests clamp k to max_len - pos - 1 with pos >= 1, so no round
    # can ever dispatch k beyond max_len - 2 — warm exactly the
    # dispatchable variants
    for k in range(1, min(speculate, max_len - 2) + 1):
        _jit_draft_round(draft_cfg, k)(draft_params, dcache, prev)
        # verify chunks are k+1 tokens ([prev, drafts])
        _jit_verify_round(cfg, k + 1)(
            params, tcache, jnp.zeros((1, k + 1), jnp.int32)
        )


# ---------------------------------------------------------------------------
# step-program face: draft/verify rounds under the slot engine
# ---------------------------------------------------------------------------


class SpeculativeStepProgram:
    """Speculative decoding as a slot-engine step program
    (models/stepprog.py's protocol), replacing the legacy one-shot
    ``serve_strategies.run_speculative`` path: the engine owns
    admission/queueing/streaming/cancel/tracing, this program owns
    the draft/verify round — and multi-token emission per dispatch
    comes for free through the protocol's ``valid`` counts.

    Shape discipline matches ``speculative_generate`` exactly: batch
    1 (``slots`` must be 1 — the verify rollback is a per-sequence
    pos rewind, not a per-slot mask), greedy only (the engine routes
    only temperature<=0, penalty-free, bias-free requests here), one
    draft round + one verify chunk per dispatch (``dispatch_cost``
    2), k clamped per round by the remaining budget and the cache
    horizon so every emitted token is byte-identical to
    ``speculative_generate`` — and therefore to plain greedy decode —
    on the same prompt.

    ``supports_lookahead`` is False: round N+1's draft starts from
    round N's accepted frontier, a host-side decision, so the engine
    serializes dispatch->fetch per round exactly like the standalone
    loop (whose per-round host trip is the same cadence a vanilla
    decode pays)."""

    supports_lookahead = False
    dispatch_cost = 2  # one draft scan + one verify chunk
    rounds = 1

    def __init__(
        self,
        cfg: TransformerConfig,
        draft_cfg: TransformerConfig,
        params: Params,
        draft_params: Params,
        max_len: int,
        speculate: int = 4,
    ) -> None:
        if speculate < 1:
            raise ValueError("speculate must be >= 1")
        if cfg.window > 0 or draft_cfg.window > 0:
            raise ValueError(
                "speculative decoding does not compose with sliding-"
                "window attention (ring-cache writes are destructive)"
            )
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError("draft and target must share a vocab")
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.params = params
        self.draft_params = draft_params
        self.max_len = max_len
        self.speculate = speculate
        self.slots = 1
        # max tokens one dispatch can emit: k accepted drafts + the
        # target's correction/bonus token
        self.chunk = speculate + 1
        self.reset()

    def reset(self) -> None:
        self._cache = None
        self._dcache = None
        self._prev = None
        self._pos = 0

    def admit(self, slot: int, req, logits, row_cache) -> int:
        """The engine prefilled the TARGET (``row_cache``); prefill
        the draft here and take the target's greedy prefill argmax as
        token 0 — ``speculative_generate``'s exact first step. The
        greedy routing contract means first_sample would compute the
        same argmax; using argmax directly keeps this byte-locked to
        the standalone loop."""
        if slot != 0:
            raise ValueError("speculative program serves one slot")
        prompt = jnp.asarray([req.tokens], jnp.int32)
        _dlogits, self._dcache = prefill(
            self.draft_params, prompt, self.draft_cfg, self.max_len
        )
        self._cache = row_cache
        prev = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [1]
        self._prev = prev
        self._pos = len(req.tokens)
        return int(jax.device_get(prev)[0])

    def retire(self, slot: int) -> None:
        self.reset()

    # cpcheck: hotpath — one draft + one verify dispatch, no syncs
    def dispatch(self, budgets, fused: bool):
        # the SAME per-round geometry as speculative_generate, by
        # shared helper (_clamp_k): budgets[0] is max_new minus
        # tokens already emitted — the standalone loop's
        # ``max_new_tokens - len(out)``
        k = _clamp_k(
            self.speculate, int(budgets[0]), self.max_len, self._pos
        )
        assert k >= 1, (self._pos, budgets)
        drafts, target_choice, self._cache, self._dcache = (
            _dispatch_round(
                self.params, self.draft_params, self.cfg,
                self.draft_cfg, self._cache, self._dcache,
                self._prev, k,
            )
        )
        return drafts, target_choice, k

    # cpcheck: hotpath — the acceptance fetch, the round's one sync
    def tokens(self, handle):
        import numpy as np

        drafts, target_choice, k = handle
        drafts_h, target_h = jax.device_get((drafts, target_choice))  # cpcheck: disable=CP-HOTSYNC the per-round acceptance fetch
        emitted = _accept_round(drafts_h, target_h, k)
        self._pos += len(emitted)
        self._cache, self._dcache = _rewind_caches(
            self._cache, self._dcache, self._pos
        )
        self._prev = jnp.asarray([emitted[-1]], jnp.int32)
        toks = np.zeros((1, self.chunk), np.int64)
        toks[0, : len(emitted)] = emitted
        valid = np.full((1,), len(emitted), np.int64)
        return toks, valid, 1
