"""Metric actors: bus subscribers that record user-defined measurements.

Capability parity with the reference (reference: telemetry/metrics.go):
``{METRIC, "<name>|<value>"}`` events (published by the control plane's
``PutMetric`` endpoint) are matched by full metric name and recorded
into the Prometheus collector — counters Add, gauges Set,
histograms/summaries Observe.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Optional

from prometheus_client import Counter, Gauge, Histogram, Summary

from ..events import (
    EventBus,
    EventCode,
    EventHandler,
    GLOBAL_SHUTDOWN,
    QUIT_BY_TEST,
)
from ..utils.tasks import spawn
from .config import MetricConfig

log = logging.getLogger("containerpilot.telemetry")


class Metric(EventHandler):
    def __init__(self, cfg: MetricConfig) -> None:
        super().__init__()
        self.name = cfg.full_name
        self.type = cfg.type
        self.collector = cfg.collector
        self._task: Optional["asyncio.Task[None]"] = None

    def run(self, bus: EventBus) -> "asyncio.Task[None]":
        self.subscribe(bus)
        self.register(bus)
        self._task = spawn(self._loop(), name=f"metric:{self.name}")
        return self._task

    def stop(self) -> None:
        """Cancel the loop (the app stops metrics once all jobs have
        completed, mirroring generation-context cancellation)."""
        if self._task is not None and not self._task.done():
            self._task.cancel()

    async def _loop(self) -> None:
        try:
            while True:
                event = await self.next_event()
                if event in (GLOBAL_SHUTDOWN, QUIT_BY_TEST):
                    return
                if event.code == EventCode.METRIC:
                    self.process_metric(event.source)
        except asyncio.CancelledError:
            pass
        finally:
            self.unsubscribe()
            self.unregister()

    def process_metric(self, measurement: str) -> None:
        """Parse "<name>|<value>" (reference: metrics.go:47-57)."""
        parts = measurement.split("|")
        if len(parts) < 2:
            log.error("metric: invalid metric format: %s", measurement)
            return
        key, value = parts[0], parts[1]
        if key == self.name:
            self.record(value)

    def record(self, raw_value: str) -> None:
        try:
            val = float(raw_value.strip())
        except ValueError:
            log.error("metric produced non-numeric value: %r", raw_value)
            return
        if isinstance(self.collector, Counter):
            self.collector.inc(val)
        elif isinstance(self.collector, Gauge):
            self.collector.set(val)
        elif isinstance(self.collector, (Histogram, Summary)):
            self.collector.observe(val)
