"""The telemetry server: Prometheus /metrics + /status JSON.

Capability parity with the reference (reference: telemetry/telemetry.go,
telemetry/status.go): a TCP HTTP server (default :9090) exposing

- ``/metrics``: the Prometheus exposition (built-in supervisor metrics
  plus user-defined metric collectors), and
- ``/status``: JSON of job/service/watch state, with live job status
  resolved at request time (reference: status.go:47-69).

The server advertises itself in the catalog via the synthetic
``containerpilot`` job (see config.py), exactly like the reference.
Bind retries tolerate a lingering port from a prior generation
(reference: telemetry/telemetry.go:82-88).
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import TYPE_CHECKING, Any, Dict, List, Optional

from prometheus_client import REGISTRY

from ..utils.http import HTTPServer, Request, Response
from ..utils.prom import ensure_build_info, exposition
from ..version import VERSION
from .config import TelemetryConfig
from .metrics import Metric

if TYPE_CHECKING:  # pragma: no cover
    from ..jobs import Job
    from ..watches import Watch

log = logging.getLogger("containerpilot.telemetry")

BIND_RETRIES = 10
BIND_RETRY_DELAY = 1.0  # reference: telemetry.go:82-88 / control.go:130-137


class Telemetry:
    def __init__(self, cfg: TelemetryConfig) -> None:
        self.cfg = cfg
        self.metrics: List[Metric] = [Metric(m) for m in cfg.metrics]
        # the shared identity gauge every /metrics surface exports
        ensure_build_info(REGISTRY, "supervisor")
        self._server = HTTPServer()
        self._server.route("GET", "/metrics", self._handle_metrics)
        self._server.route("GET", "/status", self._handle_status)
        # /status sources (reference: telemetry/status.go:72-103)
        self._jobs: List["Job"] = []
        self._watch_names: List[str] = []

    def monitor_jobs(self, jobs: List["Job"]) -> None:
        self._jobs = [j for j in jobs if j.name != "containerpilot"]

    def monitor_watches(self, watches: List["Watch"]) -> None:
        self._watch_names = [w.name for w in watches]

    async def _handle_metrics(self, _req: Request) -> Response:
        # ONE exposition convention for every /metrics surface in-tree
        # (supervisor, serving, fleet gateway): utils/prom.py
        payload, content_type = exposition(REGISTRY)
        return Response(200, payload, content_type=content_type)

    async def _handle_status(self, _req: Request) -> Response:
        jobs_out: List[Dict[str, Any]] = []
        services_out: List[Dict[str, Any]] = []
        for job in self._jobs:
            status = str(job.get_status())
            jobs_out.append({"Name": job.name, "Status": status})
            if job.service is not None:
                services_out.append(
                    {
                        "Name": job.service.name,
                        "Address": job.service.registration.address,
                        "Port": job.service.registration.port,
                        "Status": status,
                    }
                )
        body = json.dumps(
            {
                "Version": VERSION,
                "Jobs": jobs_out,
                "Services": services_out,
                "Watches": self._watch_names,
            }
        ).encode()
        return Response(200, body, content_type="application/json")

    async def run(self) -> None:
        """Bind with retries (a prior generation's socket may linger)."""
        for attempt in range(BIND_RETRIES):
            try:
                await self._server.start_tcp(self.cfg.address, self.cfg.port)
                log.info(
                    "telemetry: serving on %s:%d", self.cfg.address, self.cfg.port
                )
                return
            except OSError as exc:
                if attempt == BIND_RETRIES - 1:
                    raise
                log.warning(
                    "telemetry: bind failed (%s), retrying in %.0fs",
                    exc,
                    BIND_RETRY_DELAY,
                )
                await asyncio.sleep(BIND_RETRY_DELAY)

    async def stop(self) -> None:
        await self._server.stop()
