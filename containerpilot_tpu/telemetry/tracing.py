"""Cross-hop request tracing: where did my TTFT go?

The fleet's verdicts used to be aggregate — counters on ``/metrics``,
a pass/fail goodput number from the chaos scorer. When one request
misses its TTFT SLO, aggregates cannot say whether the time went to
the admission queue, a pool/mux dial, replica-side slot queueing,
prefill, or the SSE relay. This module is the per-request answer:

- **Spans, not logs.** A ``Trace`` is a list of ``(stage, start,
  end)`` monotonic-clock spans plus a little identity. Recording a
  span is an append to a plain Python list — no locks (every recording
  site runs on one event loop, and CPython appends are atomic
  besides), no I/O, no formatting; the cost is two ``monotonic()``
  calls and a tuple.
- **A ring, not a database.** Completed traces land in a fixed-size
  ring (most-recent-N) plus a small slowest-N board, exposed as JSON
  on each process's ``GET /v1/traces``. Memory is bounded by
  construction; an unsampled 100%-tracing fleet stays cheap because
  retention is what's sampled, not recording.
- **Context, carried.** The active trace rides a ``contextvars``
  ContextVar, so spans recorded three calls deep (or in a hedge leg's
  task — task creation snapshots the context) attach to the right
  request without threading a handle through every signature. A
  second ContextVar carries the serving mux stream id for log
  correlation.
- **Cross-hop, without a second RPC.** The gateway mints a
  ``trace_id`` and forwards it upstream (an ``X-CP-Trace`` header on
  the classic pooled path, a HEADERS field on cp-mux/1 streams). The
  replica records its own spans under that id and returns a compact
  **digest** — ``stage~offset_ms~dur_ms;...`` relative to its own
  trace start — in an ``X-CP-Span-Digest`` response header (buffered)
  or in the final SSE ``done`` event (streams). The gateway splices
  those spans into its own timeline as ``replica.*`` children aligned
  at the upstream-dispatch span, so one ``/v1/traces`` entry shows
  the whole request: queue wait, dial, replica prefill, decode,
  relay.
- **Hot paths record nothing per token.** The slot engine's decode
  round is ``# cpcheck: hotpath``; it never touches this module. Slot
  timings are a handful of floats written at admission/harvest
  boundaries (see ``serve_slots``) and converted to spans once, when
  the request finishes — batched per request, not per token or per
  round.

Stage glossary (docs/90-observability.md is the runbook):

==========================  =========================================
stage                       meaning
==========================  =========================================
``admission_queue_wait``    gateway: admission enqueue -> slot grant
``upstream_connect``        gateway: pool/mux acquire + stream open
``upstream_ttfb``           gateway: request sent -> response head
``upstream_body``           gateway: response head -> body read
``relay``                   gateway: SSE head -> relay close
``replica.slot_queue_wait`` replica: engine submit -> slot admission
``replica.kv``              replica: spill-tier readmit (host->device
                            KV copy) ahead of the suffix extend
``replica.prefill``         replica: prefill + first-token sample
``replica.decode``          replica: decode rounds to completion
``replica.stream_relay``    replica: first SSE delta -> done event
``replica.compute``         replica: non-slot decode dispatch
==========================  =========================================
"""
from __future__ import annotations

import json
import os
import time
from asyncio import CancelledError
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "DIGEST_HEADER",
    "TRACE_HEADER",
    "Trace",
    "TraceRecorder",
    "activate",
    "add_engine_spans",
    "current_stream_id",
    "current_trace",
    "current_trace_id",
    "deactivate",
    "dominant_stage",
    "encode_digest",
    "mint_trace_id",
    "now",
    "parse_digest",
    "safe_id",
    "set_stream_id",
    "span",
    "stage_totals",
]

#: request header carrying the trace id across hops (and echoed on
#: every answer, refusals included, so a client-reported failure is
#: findable in /v1/traces even when nothing was dispatched)
TRACE_HEADER = "X-CP-Trace"
#: response header carrying the compact span digest back downstream
DIGEST_HEADER = "X-CP-Span-Digest"

#: spans kept per trace; a retry/hedge storm cannot balloon one
#: trace's memory (the cap is far above any sane request's span count)
MAX_SPANS = 128
#: digest entries accepted from a peer (same ceiling, other direction)
MAX_DIGEST_SPANS = 64

#: replica-refinement mapping for dominance: these gateway stages are
#: the parent window the ``replica.*`` spans refine (see
#: ``dominant_stage``)
_REFINABLE = ("upstream_ttfb", "upstream_body", "relay")


def now() -> float:
    """The one tracing clock. Spans, engine timings, and admission
    stamps must all read it so cross-source spans subtract cleanly."""
    return time.monotonic()


def mint_trace_id() -> str:
    """16 hex chars of OS randomness; hex-only by construction, so
    ids splice into JSON/digest wire formats without escaping."""
    return os.urandom(8).hex()


#: characters a peer-supplied trace id may use: the splice-safe set
#: (mux head templates insert the id into pre-encoded JSON, and ids
#: are echoed in response headers — neither path re-escapes)
_SAFE_ID_CHARS = frozenset(
    "0123456789abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ-_"
)
MAX_ID_LEN = 64


def safe_id(raw: Optional[str]) -> Optional[str]:
    """Validate a peer-supplied trace id. Returns the id when it is a
    short token of splice-safe characters, else None (the caller
    mints a fresh one). Every adoption point MUST go through this: a
    hostile ``X-CP-Trace`` would otherwise ride unescaped into the
    cached mux HEADERS template (request smuggling / co-resident
    stream teardown) and into echoed answer headers."""
    if not raw or len(raw) > MAX_ID_LEN:
        return None
    if all(ch in _SAFE_ID_CHARS for ch in raw):
        return raw
    return None


# -- context ----------------------------------------------------------

_current: "ContextVar[Optional[Trace]]" = ContextVar(
    "cp_trace", default=None
)
_stream: "ContextVar[int]" = ContextVar("cp_stream_id", default=0)


def current_trace() -> Optional["Trace"]:
    return _current.get()


def current_trace_id() -> str:
    trace = _current.get()
    return trace.trace_id if trace is not None else ""


def activate(trace: Optional["Trace"]):
    """Bind ``trace`` to the current context; returns the reset
    token. Binding None is allowed (explicitly no trace)."""
    return _current.set(trace)


def deactivate(token) -> None:
    _current.reset(token)


def set_stream_id(stream_id: int):
    """Bind the serving mux stream id (log correlation); returns the
    reset token. Called by the HTTP server's per-stream task, so the
    binding is naturally stream-scoped."""
    return _stream.set(stream_id)


def current_stream_id() -> int:
    return _stream.get()


class _SpanCtx:
    """``with span("stage"):`` — records one span on exit. Reusable
    only per entry (allocate one per use; they are tiny)."""

    __slots__ = ("trace", "stage", "t0")

    def __init__(self, trace: Optional["Trace"], stage: str) -> None:
        self.trace = trace
        self.stage = stage
        self.t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, _exc, _tb) -> None:
        # a CANCELLED span records nothing: a hedge's losing leg (or
        # an abandoned client's task) exits its upstream spans via
        # CancelledError, and recording them would both misalign the
        # digest-stitch anchor (last_span_start picks the loser's
        # dispatch) and double-count the stage in dominance. A span
        # that exits via a real failure still records — time spent
        # failing is exactly what the trace must show.
        if exc_type is not None and issubclass(
            exc_type, CancelledError
        ):
            return
        if self.trace is not None:
            self.trace.add_span(self.stage, self.t0, time.monotonic())


def span(stage: str) -> _SpanCtx:
    """Span context manager over the CURRENT trace; a no-op (beyond
    two clock reads) when no trace is active."""
    return _SpanCtx(_current.get(), stage)


# -- the trace itself -------------------------------------------------


class Trace:
    """One request's timeline: identity + append-only span list.
    Created by a ``TraceRecorder``; ``finish()`` is idempotent and
    files the trace into the recorder's ring exactly once."""

    __slots__ = (
        "trace_id", "endpoint", "started", "ended", "status",
        "spans", "stream_id", "_recorder",
    )

    def __init__(
        self,
        recorder: Optional["TraceRecorder"],
        trace_id: str,
        endpoint: str,
    ) -> None:
        self.trace_id = trace_id
        self.endpoint = endpoint
        self.started = time.monotonic()
        self.ended: Optional[float] = None
        self.status = 0
        #: (stage, start, end, meta-or-None) — absolute monotonic
        self.spans: List[Tuple[str, float, float, Optional[dict]]] = []
        self.stream_id = 0
        self._recorder = recorder

    # -- recording ----------------------------------------------------

    def add_span(
        self, stage: str, start: float, end: float, **meta: Any
    ) -> None:
        if len(self.spans) >= MAX_SPANS:
            return
        self.spans.append((stage, start, end, meta or None))

    def span(self, stage: str) -> _SpanCtx:
        return _SpanCtx(self, stage)

    def add_child_digest(
        self, digest: str, base: float, prefix: str = "replica."
    ) -> None:
        """Splice a peer's relative-offset digest into this timeline,
        aligned so the child's t=0 lands at ``base`` (the moment this
        hop dispatched upstream — clock skew between hops is bounded
        by the network latency already inside the parent span)."""
        for stage, off_s, dur_s in parse_digest(digest):
            self.add_span(
                prefix + stage, base + off_s, base + off_s + dur_s
            )

    def last_span_start(self, stage: str) -> Optional[float]:
        """Start of the most recent span named ``stage`` (the
        alignment anchor for a replica digest: the LAST upstream
        dispatch is the one whose response carried it)."""
        for name, start, _end, _meta in reversed(self.spans):
            if name == stage:
                return start
        return None

    def finish(self, status: int) -> None:
        if self.ended is not None:
            return
        self.ended = time.monotonic()
        self.status = status
        if self._recorder is not None:
            self._recorder.record(self)

    # -- reporting ----------------------------------------------------

    @property
    def duration_s(self) -> float:
        end = self.ended if self.ended is not None else time.monotonic()
        return max(end - self.started, 0.0)

    def digest(self) -> str:
        """This trace's spans as the compact wire digest (offsets
        relative to trace start). Child (``replica.``-prefixed) spans
        are included — a stitched gateway digest hands the full
        breakdown to the client in one header."""
        return encode_digest(
            (stage, start - self.started, end - start)
            for stage, start, end, _meta in self.spans
        )

    def stage_totals(self) -> Dict[str, float]:
        """Summed seconds per stage (a stage dispatched twice — a
        retry, both hedge legs — reports its total)."""
        totals: Dict[str, float] = {}
        for stage, start, end, _meta in self.spans:
            totals[stage] = totals.get(stage, 0.0) + max(end - start, 0.0)
        return totals

    def as_dict(self) -> Dict[str, Any]:
        entry: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "endpoint": self.endpoint,
            "status": self.status,
            "complete": self.ended is not None,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "spans": [
                {
                    "stage": stage,
                    "offset_ms": round((start - self.started) * 1e3, 3),
                    "dur_ms": round((end - start) * 1e3, 3),
                    **(meta or {}),
                }
                for stage, start, end, meta in self.spans
            ],
        }
        if self.stream_id:
            entry["stream_id"] = self.stream_id
        dominant = dominant_stage(self.stage_totals())
        if dominant is not None:
            entry["dominant_stage"] = dominant
        return entry


# -- the digest wire format -------------------------------------------


def encode_digest(
    spans: Iterable[Tuple[str, float, float]]
) -> str:
    """``stage~offset_ms~dur_ms;...`` — stage names are fixed
    identifiers (no ``~``/``;``), offsets relative to the emitting
    hop's trace start. Header-safe ASCII by construction."""
    return ";".join(
        f"{stage}~{off_s * 1e3:.3f}~{dur_s * 1e3:.3f}"
        for stage, off_s, dur_s in spans
    )


def parse_digest(digest: str) -> List[Tuple[str, float, float]]:
    """Inverse of ``encode_digest``; tolerant — malformed entries are
    skipped, not fatal (a peer's telemetry must never fail a
    request). Returns (stage, offset_s, dur_s) tuples."""
    out: List[Tuple[str, float, float]] = []
    if not digest:
        return out
    for part in digest.split(";"):
        fields = part.split("~")
        if len(fields) != 3 or not fields[0]:
            continue
        try:
            off_ms, dur_ms = float(fields[1]), float(fields[2])
        except ValueError:
            continue
        out.append((fields[0], off_ms / 1e3, max(dur_ms, 0.0) / 1e3))
        if len(out) >= MAX_DIGEST_SPANS:
            break
    return out


def stage_totals(digest: str) -> Dict[str, float]:
    """Summed seconds per stage straight from a wire digest (the
    chaos client's view — it never holds Trace objects)."""
    totals: Dict[str, float] = {}
    for stage, _off, dur in parse_digest(digest):
        totals[stage] = totals.get(stage, 0.0) + dur
    return totals


def dominant_stage(totals: Mapping[str, float]) -> Optional[str]:
    """Name the stage that ate the request. Dominance is judged over
    the NON-overlapping top-level stages (``replica.*`` spans are a
    refinement nested inside the upstream spans — summing both would
    double-count); when the winner is an upstream span that carries a
    replica refinement, descend and blame the dominant replica stage
    instead, so the answer is 'replica prefill', not 'the upstream
    took a while'."""
    top = {
        stage: dur
        for stage, dur in totals.items()
        if not stage.startswith("replica.") and dur > 0.0
    }
    if not top:
        # replica-only breakdown (e.g. a trace recorded at a replica)
        nested = {s: d for s, d in totals.items() if d > 0.0}
        if not nested:
            return None
        return max(nested.items(), key=lambda kv: (kv[1], kv[0]))[0]
    winner = max(top.items(), key=lambda kv: (kv[1], kv[0]))[0]
    if winner in _REFINABLE:
        nested = {
            stage: dur
            for stage, dur in totals.items()
            if stage.startswith("replica.") and dur > 0.0
        }
        if nested:
            return max(
                nested.items(), key=lambda kv: (kv[1], kv[0])
            )[0]
    return winner


# -- engine-timings bridge --------------------------------------------


def add_engine_spans(trace: Trace, timings: Mapping[str, float]) -> None:
    """Convert the slot engine's batched boundary stamps (see
    serve_slots: enqueued/admitted/prefill_done/done + rounds) into
    replica spans. Called ONCE per request after the engine future
    resolves — the decode hot path itself never records."""
    enq = timings.get("enqueued")
    adm = timings.get("admitted")
    pf = timings.get("prefill_done")
    done = timings.get("done")
    if pf is not None and done is None:
        # an abandoned stream converts its timings (stream-close
        # callback) before the engine's cancel-retire path stamps
        # ``done``/``rounds`` at the next chunk boundary — account
        # decode up to the abandon instant rather than dropping the
        # stage, or dominance would misattribute seconds of decode
        done = now()
    if enq is not None and adm is not None:
        trace.add_span("slot_queue_wait", enq, adm)
    if adm is not None and pf is not None:
        kv = timings.get("kv")
        if kv is not None and kv > 0.0:
            # spill-tier readmit (host->device KV copy) carved out of
            # the admission window so the stages stay non-overlapping:
            # kv + prefill together still span admitted -> prefill_done
            kv_end = min(adm + kv, pf)
            trace.add_span("kv", adm, kv_end)
            trace.add_span("prefill", kv_end, pf)
        else:
            trace.add_span("prefill", adm, pf)
    if pf is not None and done is not None:
        rounds = timings.get("rounds")
        if rounds is not None:
            trace.add_span("decode", pf, done, rounds=int(rounds))
        else:
            trace.add_span("decode", pf, done)


# -- the recorder -----------------------------------------------------


class TraceRecorder:
    """Per-process (per-server, really: a test harness boots several
    servers in one process) retention of completed traces: a
    most-recent-N ring plus a slowest-N board. The record path is a
    deque append and a bounded insertion into a 16-element list — no
    locks, loop-thread-only by construction."""

    def __init__(
        self, role: str, recent: int = 64, slowest: int = 16
    ) -> None:
        self.role = role
        self.recent_cap = recent
        self.slowest_cap = slowest
        self._recent: "deque[Trace]" = deque(maxlen=recent)
        #: ascending by duration; [0] is the cheapest seat on the board
        self._slowest: List[Trace] = []
        self.recorded = 0

    def start(
        self, trace_id: Optional[str] = None, endpoint: str = ""
    ) -> Trace:
        return Trace(self, trace_id or mint_trace_id(), endpoint)

    def record(self, trace: Trace) -> None:
        self.recorded += 1
        self._recent.append(trace)
        board = self._slowest
        duration = trace.duration_s
        if len(board) >= self.slowest_cap:
            if duration <= board[0].duration_s:
                return
            board.pop(0)
        lo = 0
        for lo, held in enumerate(board):  # noqa: B007 — tiny list
            if held.duration_s >= duration:
                break
        else:
            lo = len(board)
        board.insert(lo, trace)

    # -- queries ------------------------------------------------------

    def recent(self) -> List[Trace]:
        """Newest first."""
        return list(reversed(self._recent))

    def slowest(self) -> List[Trace]:
        """Slowest first."""
        return list(reversed(self._slowest))

    def find(self, trace_id: str) -> List[Trace]:
        seen = []
        for trace in list(self._recent) + self._slowest:
            if trace.trace_id == trace_id and trace not in seen:
                seen.append(trace)
        return seen

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /v1/traces`` body."""
        recent = self.recent()
        slowest = self.slowest()
        if limit is not None:
            recent = recent[:limit]
            slowest = slowest[:limit]
        return {
            "role": self.role,
            "recorded": self.recorded,
            "recent_cap": self.recent_cap,
            "slowest_cap": self.slowest_cap,
            "recent": [t.as_dict() for t in recent],
            "slowest": [t.as_dict() for t in slowest],
        }

    def snapshot_json(
        self, query: Mapping[str, List[str]]
    ) -> bytes:
        """The ``GET /v1/traces`` response body, shared by every
        surface (gateway, replica, pod frontend): ``?n=`` bounds
        both lists; anything non-numeric is ignored."""
        raw = (query.get("n") or [""])[0]
        limit = int(raw) if raw.isdigit() else None
        return json.dumps(self.snapshot(limit)).encode()

    def fleet_summary(self, limit: int = 4) -> Dict[str, Any]:
        """Compact slice for the gateway's ``/fleet`` JSON: the
        slowest few timelines, one line each."""
        return {
            "recorded": self.recorded,
            "slowest": [
                {
                    "trace_id": t.trace_id,
                    "endpoint": t.endpoint,
                    "status": t.status,
                    "duration_ms": round(t.duration_s * 1e3, 3),
                    "dominant_stage": dominant_stage(t.stage_totals()),
                }
                for t in self.slowest()[:limit]
            ],
        }
