"""The device-time ledger: where did the fleet's device-seconds go?

PR 9's tracing answers "where did THIS request's TTFT go"; nothing
answered "where did the replica's *wall-seconds* go". Following the
ML-Productivity-Goodput framing (PAPERS.md), every second a replica
is alive is either **goodput** (the device advanced someone's
request) or **badput** (it compiled, booted, idled, re-copied KV, or
drained) — and a fleet that cannot decompose its badput cannot drive
it down. This module is the accounting layer:

- **A state machine, not a profiler.** A ``DeviceTimeLedger``
  attributes every wall-second of a replica's life to exactly ONE
  stage: ``boot`` (process start -> warmup begins),
  ``compile_warmup`` (XLA compiles before /health flips 200),
  ``idle`` (no slot decoding), ``prefill`` (admission prefill +
  first sample), ``decode`` (chunk rounds), ``kv_readmit`` (spill-
  tier host->device KV copies, carved out of prefill), and ``drain``
  (maintenance: capacity leaving the fleet, in-flight rows
  included). Transitions happen at the request boundaries the slot
  engine already stamps for tracing — a few ``monotonic()`` floats
  per REQUEST, nothing per token or per round, so the
  ``# cpcheck: hotpath`` decode loop stays untouched.
- **Sums to wall time by construction.** The running segment is
  closed and re-opened at every transition; ``snapshot()`` folds the
  open segment in, so the per-stage totals always sum to exactly
  ``now - t0``. The 2%% tolerance the acceptance states is for
  cross-surface reads (scrape skew), not for the ledger itself.
- **Overrides for the lifecycle stages.** ``warmup()`` and the
  maintenance hook set a stage *override* (``compile_warmup`` /
  ``drain``): the engine's prefill/decode stamps keep tracking the
  underlying state, but attribution goes to the override — so a
  warmup dummy request's compile seconds land in ``compile_warmup``
  (stamped BEFORE ``/health`` flips 200: a scale-up replica's badput
  is visible from its very first scrape, never an ``idle`` lie), and
  a draining replica's last in-flight decodes are costed as drain.
- **One wire format.** ``note()`` encodes the cumulative totals as a
  ``gp=`` field on the TTL heartbeat (the duck-typed channel
  occupancy and ``kv=`` already ride); ``parse_note`` is the
  tolerant reader and ``merge_note_max`` the torn-note discipline
  (cumulative seconds only grow — elementwise max, exactly like the
  ``kv=`` counters).
- **The fleet view.** ``sum_stage_totals`` folds live + departed
  replicas into one per-stage map; ``productive_fraction`` is
  goodput's headline number: (prefill + decode) / total.

Surfaces: ``cp_device_seconds_total{stage}`` on every replica and
pod ``/metrics``, ``GET /v1/goodput`` JSON (replica, pod frontend,
gateway fleet view), the ``goodput`` block on the gateway's
``/fleet``, and the ``goodput_ledger`` blob in every chaos scenario
report. docs/90-observability.md is the runbook.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "BADPUT_STAGES",
    "DeviceTimeLedger",
    "NOTE_FIELDS",
    "PRODUCTIVE_STAGES",
    "STAGES",
    "find_scheduling_gaps",
    "merge_note_max",
    "parse_note",
    "productive_fraction",
    "sum_stage_totals",
]

#: every wall-second lands in exactly one of these
STAGES = (
    "boot", "compile_warmup", "idle", "prefill", "decode",
    "kv_readmit", "drain",
)
#: the goodput numerator: the device advanced someone's request
PRODUCTIVE_STAGES = ("prefill", "decode")
#: overhead the fleet pays to exist (idle is neither: it is unused
#: capacity, in the denominator but not "work done badly")
BADPUT_STAGES = ("boot", "compile_warmup", "kv_readmit", "drain")

#: stages the engine drives; lifecycle stages are entered by the
#: server (boot is implicit, compile_warmup/drain are overrides)
_ENGINE_STAGES = ("idle", "prefill", "decode")

#: positional field order of the ``gp=`` heartbeat note — the seven
#: stage seconds, then the dispatch/token counters
NOTE_FIELDS = STAGES + ("dispatches", "tokens_out")

#: recent idle segments retained for scheduling-gap detection (each
#: is two floats; the ring bounds memory like the trace rings do)
IDLE_SPANS_KEPT = 128


class DeviceTimeLedger:
    """Per-replica monotonic-clock stage accounting. Thread-safe: the
    event loop enters lifecycle stages (warmup, drain) while the slot
    engine's worker thread enters prefill/decode/idle — transitions
    are boundary events (a handful per request), so the lock is never
    contended on a hot path and nothing here runs per token."""

    def __init__(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.t0 = now
        self._totals: Dict[str, float] = {s: 0.0 for s in STAGES}
        self._stage = "boot"
        self._override: Optional[str] = None
        self._since = now
        self._lock = threading.Lock()
        #: transitions recorded — the no-per-token contract's witness
        #: (a 100k-token decode moves this by a constant, not 100k)
        self.transitions = 0
        #: monotonic stamp of the first productive (prefill) second —
        #: the replica half of time-to-first-routed-token
        self.first_productive_at: Optional[float] = None
        #: recent idle segments (start, end), recorded when idle is
        #: left — read by the scheduling-gap detector, never on a hot
        #: path
        self._idle_spans: "deque[Tuple[float, float]]" = deque(
            maxlen=IDLE_SPANS_KEPT
        )
        #: set by freeze(): reads clamp to this instant, so a
        #: stopped/killed replica's ledger stops accruing (in
        #: production the process dies and its note stops updating;
        #: in-process harnesses must see the same final totals)
        self._frozen: Optional[float] = None

    # -- recording (boundary events only) ------------------------------

    def _active(self) -> str:
        return self._override or self._stage

    def _close(self, now: float) -> None:
        seg = now - self._since
        if seg > 0.0:
            active = self._active()
            self._totals[active] += seg
            if active == "idle":
                self._idle_spans.append((self._since, now))
        self._since = now

    def _now(self, now: float) -> float:
        """Clamp a write/read instant to the freeze point (lock
        held): a late stamp from the engine worker racing stop()
        must not accrue past 'death', or totals exceed the frozen
        uptime and the sums-to-wall invariant breaks."""
        if self._frozen is not None:
            return min(now, self._frozen)
        return now

    def enter(self, stage: str, now: Optional[float] = None) -> None:
        """Close the running segment and start attributing to
        ``stage``. Under an override the underlying stage still
        moves (attribution stays with the override until it clears)."""
        if stage not in self._totals:
            raise ValueError(f"unknown ledger stage {stage!r}")
        now = time.monotonic() if now is None else now
        with self._lock:
            now = self._now(now)
            self._close(now)
            self._stage = stage
            self.transitions += 1
            if (
                stage == "prefill"
                and self.first_productive_at is None
                and self._override is None
            ):
                self.first_productive_at = now

    def engine_idle(self, now: Optional[float] = None) -> None:
        """The engine's fully-idle transition: flips to ``idle`` only
        from an engine-driven stage, so an engine worker blocking
        before the server even warmed cannot cut ``boot`` short."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._stage not in _ENGINE_STAGES[1:]:
                return
            now = self._now(now)
            self._close(now)
            self._stage = "idle"
            self.transitions += 1

    def carve(
        self, stage: str, seconds: float, now: Optional[float] = None
    ) -> None:
        """Re-attribute the most recent ``seconds`` of the RUNNING
        segment to ``stage`` (the kv_readmit carve: a spill-tier
        readmit happened inside the admission window; those seconds
        are a KV copy, not prefill compute). Clamped to the open
        segment so totals can never exceed wall time."""
        if stage not in self._totals:
            raise ValueError(f"unknown ledger stage {stage!r}")
        now = time.monotonic() if now is None else now
        with self._lock:
            now = self._now(now)
            seconds = max(0.0, min(seconds, now - self._since))
            if seconds <= 0.0:
                return
            self._totals[stage] += seconds
            self._since += seconds
            self.transitions += 1

    def set_override(
        self, stage: str, now: Optional[float] = None
    ) -> None:
        """Attribute everything to ``stage`` until cleared, whatever
        the engine stamps underneath (warmup's dummy request must
        cost ``compile_warmup``; a draining replica's last in-flight
        decodes cost ``drain``)."""
        if stage not in self._totals:
            raise ValueError(f"unknown ledger stage {stage!r}")
        now = time.monotonic() if now is None else now
        with self._lock:
            now = self._now(now)
            self._close(now)
            self._override = stage
            self.transitions += 1

    def clear_override(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            now = self._now(now)
            self._close(now)
            self._override = None
            self.transitions += 1

    def freeze(self, now: Optional[float] = None) -> None:
        """Stop the clock: every read from here on sees the totals as
        of ``now``. Called when the server stops or aborts —
        idempotent (the first freeze wins)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._frozen is None:
                self._frozen = now

    # -- reading -------------------------------------------------------

    @property
    def stage(self) -> str:
        """The stage currently accumulating."""
        with self._lock:
            return self._active()

    def stage_seconds(self, stage: str) -> float:
        """Live total for one stage, open segment included — the
        ``cp_device_seconds_total{stage}`` gauge body."""
        now = time.monotonic()
        with self._lock:
            if self._frozen is not None:
                now = self._frozen
            total = self._totals.get(stage, 0.0)
            if self._active() == stage:
                total += max(now - self._since, 0.0)
            return total

    def totals(self, now: Optional[float] = None) -> Dict[str, float]:
        """Per-stage seconds, open segment folded in. Sums to
        ``now - t0`` exactly (``freeze()`` clamps now)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._frozen is not None:
                now = min(now, self._frozen)
            out = dict(self._totals)
            out[self._active()] += max(now - self._since, 0.0)
            return out

    def idle_spans(self) -> List[Tuple[float, float]]:
        """Recent closed idle segments plus the open one if idle is
        running now — the scheduling-gap detector's input."""
        now = time.monotonic()
        with self._lock:
            spans = list(self._idle_spans)
            if self._active() == "idle" and now > self._since:
                spans.append((self._since, now))
            return spans

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The JSON core of ``GET /v1/goodput``."""
        now = time.monotonic() if now is None else now
        if self._frozen is not None:
            now = min(now, self._frozen)
        totals = self.totals(now)
        total_s = max(now - self.t0, 0.0)
        return {
            "stage": self.stage,
            "uptime_s": round(total_s, 3),
            "stages_s": {
                stage: round(totals[stage], 3) for stage in STAGES
            },
            "productive_s": round(
                sum(totals[s] for s in PRODUCTIVE_STAGES), 3
            ),
            "productive_fraction": productive_fraction(totals),
            "transitions": self.transitions,
            "first_productive_at": self.first_productive_at,
        }

    def note(
        self,
        dispatches: int = 0,
        tokens_out: int = 0,
        now: Optional[float] = None,
    ) -> str:
        """The ``gp=`` heartbeat field's VALUE: seven cumulative
        stage seconds (3 decimals — a small model's whole productive
        story can be milliseconds) then the dispatch/token counters,
        positional like ``kv=``. The ``gp=`` name itself is owned by
        ``fleet/notes.py``, the wire-schema registry."""
        totals = self.totals(now)
        parts = [f"{totals[s]:.3f}" for s in STAGES]
        parts.append(str(int(dispatches)))
        parts.append(str(int(tokens_out)))
        return ",".join(parts)


# -- wire format -------------------------------------------------------


def parse_note(raw: object) -> Dict[str, float]:
    """Decode a ``gp=`` note value: nine comma-separated numbers in
    ``NOTE_FIELDS`` order. Tolerant like ``parse_kv_counters``: a
    short or torn value yields the fields that DID parse, zero-filled
    — a half-written note must never throw on the poll path."""
    out = {name: 0.0 for name in NOTE_FIELDS}
    if not isinstance(raw, str) or not raw:
        return out
    for name, part in zip(NOTE_FIELDS, raw.split(",")):
        try:
            value = float(part)
        except ValueError:
            break
        if value != value or value in (float("inf"), float("-inf")):
            break  # NaN/inf from a hostile note must not propagate
        out[name] = max(0.0, value)
    return out


def merge_note_max(
    prev: Mapping[str, float], new: Mapping[str, float]
) -> Dict[str, float]:
    """The torn-note discipline: every field is CUMULATIVE, so a
    truncated read's zero-filled tail must not regress the best-known
    value. Elementwise max, exactly like the ``kv=`` counters."""
    return {
        name: max(float(new.get(name, 0.0)), float(prev.get(name, 0.0)))
        for name in NOTE_FIELDS
    }


# -- aggregation -------------------------------------------------------


def productive_fraction(totals: Mapping[str, float]) -> Optional[float]:
    """(prefill + decode) / all stages; None before any time accrued."""
    total = sum(totals.get(s, 0.0) for s in STAGES)
    if total <= 0.0:
        return None
    good = sum(totals.get(s, 0.0) for s in PRODUCTIVE_STAGES)
    return round(good / total, 4)


def sum_stage_totals(
    many: Iterable[Mapping[str, float]]
) -> Dict[str, float]:
    """Fold per-replica stage maps (live and departed alike) into one
    fleet map over ``NOTE_FIELDS`` — missing fields count zero."""
    out = {name: 0.0 for name in NOTE_FIELDS}
    for totals in many:
        for name in NOTE_FIELDS:
            out[name] += float(totals.get(name, 0.0))
    return out


def fleet_summary(
    many: Iterable[Mapping[str, float]]
) -> Dict[str, Any]:
    """The fleet-level ``goodput`` block: summed stage seconds,
    productive fraction, and dispatches/token."""
    totals = sum_stage_totals(many)
    tokens = totals.pop("tokens_out")
    dispatches = totals.pop("dispatches")
    return {
        "stages_s": {s: round(totals[s], 3) for s in STAGES},
        "device_seconds": round(sum(totals.values()), 3),
        "productive_fraction": productive_fraction(totals),
        "dispatches": int(dispatches),
        "tokens_out": int(tokens),
        "dispatches_per_token": (
            round(dispatches / tokens, 4) if tokens else None
        ),
    }


def goodput_payload(
    ledger: "DeviceTimeLedger",
    tracer: Any,
    dispatches: int,
    tokens_out: int,
    *,
    role: str,
    ready: bool,
    draining: bool,
) -> Dict[str, Any]:
    """The ONE ``GET /v1/goodput`` body both serving surfaces
    (single-host replica, pod frontend) answer with — ledger
    snapshot + the dispatches/token pair + scheduling-gap detection
    over the process's own trace ring. Centralized so the two
    surfaces cannot drift, like ``ensure_goodput_gauges`` for the
    metrics face."""
    payload = ledger.snapshot()
    payload.update(
        role=role,
        ready=ready,
        draining=draining,
        dispatches=dispatches,
        tokens_out=tokens_out,
        dispatches_per_token=(
            round(dispatches / tokens_out, 4) if tokens_out else None
        ),
        scheduling_gaps=find_scheduling_gaps(
            tracer.recent(), ledger.idle_spans()
        ),
    )
    return payload


# -- the scheduling-gap detector ---------------------------------------


def find_scheduling_gaps(
    traces: Iterable[Any],
    idle_spans: List[Tuple[float, float]],
    min_overlap_s: float = 0.005,
    limit: int = 8,
) -> List[Dict[str, Any]]:
    """Cross-check traces against the ledger: a request whose
    dominant stage was ``slot_queue_wait`` while the SAME replica's
    ledger shows idle seconds inside that wait window means the
    request queued while decode capacity sat unused — the smoking
    gun for the ROADMAP's EDF/chunked-prefill scheduling item (slots
    were free in aggregate but admission didn't interleave). Runs on
    the ``/v1/goodput`` read path only, never on record paths.

    ``traces`` are tracing.Trace objects from the replica's own ring
    (their ``slot_queue_wait`` spans share the ledger's monotonic
    clock); ``idle_spans`` come from ``DeviceTimeLedger.idle_spans``.
    """
    from .tracing import dominant_stage

    gaps: List[Dict[str, Any]] = []
    if not idle_spans:
        return gaps
    for trace in traces:
        if len(gaps) >= limit:
            break
        totals = trace.stage_totals()
        if dominant_stage(totals) != "slot_queue_wait":
            continue
        overlap = 0.0
        wait_s = 0.0
        for stage, start, end, _meta in trace.spans:
            if stage != "slot_queue_wait":
                continue
            wait_s += max(end - start, 0.0)
            for idle_start, idle_end in idle_spans:
                lo = max(start, idle_start)
                hi = min(end, idle_end)
                if hi > lo:
                    overlap += hi - lo
        if overlap >= min_overlap_s:
            gaps.append({
                "trace_id": trace.trace_id,
                "endpoint": trace.endpoint,
                "slot_queue_wait_ms": round(wait_s * 1e3, 2),
                "idle_overlap_ms": round(overlap * 1e3, 2),
            })
    return gaps
