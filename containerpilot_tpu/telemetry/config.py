"""Telemetry configuration (reference: telemetry/telemetry_config.go,
telemetry/metrics_config.go)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from prometheus_client import (
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Summary,
)

from ..config.services import get_ip
from ..version import VERSION

DEFAULT_PORT = 9090  # reference: telemetry/telemetry_config.go:34
# hardcoded self-advertisement health cadence
# (reference: telemetry/telemetry_config.go:76-80)
SELF_HEARTBEAT = 5
SELF_TTL = 15


class TelemetryConfigError(ValueError):
    pass


_METRIC_CLASSES = {
    "counter": Counter,
    "gauge": Gauge,
    "histogram": Histogram,
    "summary": Summary,
}


class MetricConfig:
    """One user-defined metric (reference: metrics_config.go:12-23)."""

    def __init__(self, raw: Dict[str, Any]) -> None:
        unknown = set(raw) - {"namespace", "subsystem", "name", "help", "type"}
        if unknown:
            raise TelemetryConfigError(
                f"metric[{raw.get('name', '?')}]: unknown keys {sorted(unknown)}"
            )
        self.namespace = raw.get("namespace", "")
        self.subsystem = raw.get("subsystem", "")
        self.name = raw.get("name", "")
        self.help = raw.get("help", "") or self.name
        self.type = raw.get("type", "")
        if not self.name:
            raise TelemetryConfigError("metric must have a name")
        if self.type not in _METRIC_CLASSES:
            raise TelemetryConfigError(f"invalid metric type: {self.type}")
        self.full_name = "_".join(
            p for p in (self.namespace, self.subsystem, self.name) if p
        )
        # unregister-then-register so config reloads don't collide
        # (reference: metrics_config.go:85-88)
        existing = REGISTRY._names_to_collectors.get(self.full_name)  # noqa: SLF001
        if existing is not None:
            try:
                REGISTRY.unregister(existing)
            except KeyError:
                pass
        cls = _METRIC_CLASSES[self.type]
        self.collector = cls(
            self.name,
            self.help,
            namespace=self.namespace,
            subsystem=self.subsystem,
        )


class TelemetryConfig:
    """The telemetry section (reference: telemetry_config.go:16-68)."""

    def __init__(self, raw: Dict[str, Any]) -> None:
        if not isinstance(raw, dict):
            raise TelemetryConfigError("telemetry configuration must be a mapping")
        unknown = set(raw) - {"port", "interfaces", "tags", "metrics"}
        if unknown:
            raise TelemetryConfigError(f"telemetry: unknown keys {sorted(unknown)}")
        self.port = int(raw.get("port", DEFAULT_PORT) or DEFAULT_PORT)
        self.interfaces = raw.get("interfaces")
        self.tags: List[str] = list(raw.get("tags") or [])
        interfaces = self.interfaces
        if isinstance(interfaces, str):
            interfaces = [interfaces]
        try:
            self.address = get_ip(interfaces)
        except ValueError as exc:
            raise TelemetryConfigError(str(exc)) from None
        self.metrics = [MetricConfig(m) for m in (raw.get("metrics") or [])]

    def to_job_config_raw(self) -> Dict[str, Any]:
        """The synthetic self-advertising job
        (reference: telemetry_config.go:71-86)."""
        tags = list(self.tags)
        if VERSION:
            tags.append(VERSION)
        raw: Dict[str, Any] = {
            "name": "containerpilot",
            "port": self.port,
            "health": {"interval": SELF_HEARTBEAT, "ttl": SELF_TTL},
            "tags": tags,
        }
        if self.interfaces is not None:
            raw["interfaces"] = self.interfaces
        return raw
