"""Telemetry: Prometheus metrics + status server
(reference: telemetry/ package), plus cross-hop request tracing
(tracing.py — not the reference's; see docs/90-observability.md)."""
from . import tracing
from .config import MetricConfig, TelemetryConfig, TelemetryConfigError
from .metrics import Metric
from .telemetry import Telemetry
from .tracing import Trace, TraceRecorder

__all__ = [
    "Metric",
    "MetricConfig",
    "Telemetry",
    "TelemetryConfig",
    "TelemetryConfigError",
    "Trace",
    "TraceRecorder",
    "tracing",
]
