"""Telemetry: Prometheus metrics + status server
(reference: telemetry/ package), plus cross-hop request tracing
(tracing.py) and the device-time goodput ledger (goodput.py) — not
the reference's; see docs/90-observability.md."""
from . import goodput, tracing
from .config import MetricConfig, TelemetryConfig, TelemetryConfigError
from .goodput import DeviceTimeLedger
from .metrics import Metric
from .telemetry import Telemetry
from .tracing import Trace, TraceRecorder

__all__ = [
    "DeviceTimeLedger",
    "Metric",
    "MetricConfig",
    "Telemetry",
    "TelemetryConfig",
    "TelemetryConfigError",
    "Trace",
    "TraceRecorder",
    "goodput",
    "tracing",
]
