"""Telemetry: Prometheus metrics + status server
(reference: telemetry/ package)."""
from .config import MetricConfig, TelemetryConfig, TelemetryConfigError
from .metrics import Metric
from .telemetry import Telemetry

__all__ = [
    "Metric",
    "MetricConfig",
    "Telemetry",
    "TelemetryConfig",
    "TelemetryConfigError",
]
