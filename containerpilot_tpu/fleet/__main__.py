"""``python -m containerpilot_tpu.fleet`` runs the gateway CLI."""
from .gateway import main

raise SystemExit(main())
