"""The heartbeat note-wire schema, in ONE place.

A fleet member's TTL heartbeat carries its entire advertisement as
the check output — a single line of ``name=value`` fields::

    ok occ=0.50 role=standby cc=<digest>:<dir> kv=1,2,3,4,5
    pd=v7:deadbeef... gp=0.1,...,12,340 mg=2,3,0,0,1;aabbccdd:r2

Through PR 17 each field was hand-rolled twice: a producer somewhere
in workload/ or telemetry/ prepended its own ``"x=" +`` prefix, and
``gateway._apply_notes`` (plus ``member._survivors`` and
``modelcfg.adopt_fleet_compile_cache``) re-spelled the name to pull
it back out. Six fields in, producer and parser had nothing keeping
them aligned but grep. This module is the fix: every field is a
:class:`NoteField` — name, producer, tolerant parser — registered in
``FIELDS``, and both ends of the wire are driven from it. The
CP-NOTEWIRE rule (``analysis/callgraph.py``) statically enforces
that no ``"x=" +`` concatenation bypasses the registry and that
nothing parses a field the registry doesn't carry.

Producers duck-type the server surface exactly as ``FleetMember``
always has: a field whose accessor is missing (or returns empty)
is simply omitted from the note. Parsers are TOLERANT — a torn,
truncated, or hostile value decodes to a harmless zero value, never
an exception on the routing path (see ``kvtier/digest.py`` for the
discipline's rationale).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, Optional, Tuple
from urllib.parse import quote, unquote

from ..kvtier.digest import (
    parse_digest,
    parse_kv_counters,
    parse_kv_note,
    parse_migration_note,
)
from ..telemetry.goodput import parse_note as _parse_goodput_note

#: the role value that is advertised by OMISSION: an active replica's
#: note carries no ``role=`` field, so the first post-promotion beat
#: flips a gateway's view back to active without a new field value
ROLE_ACTIVE = "active"


@dataclass(frozen=True)
class NoteField:
    """One ``name=value`` heartbeat field: how a member produces the
    value (empty string = omit this beat) and how any consumer
    decodes it (tolerantly — garbage in, zero value out)."""

    name: str
    produce: Callable[[Any], str]
    parse: Callable[[object], Any]
    doc: str = ""


def _duck(server: Any, attr: str) -> str:
    """Call an optional server accessor; absent or empty -> omit."""
    fn = getattr(server, attr, None)
    if not callable(fn):
        return ""
    return str(fn() or "")


def _produce_occ(server: Any) -> str:
    occupancy = getattr(server, "occupancy", None)
    if isinstance(occupancy, (int, float)):
        return f"{occupancy:.2f}"
    return ""


def parse_occ(raw: object) -> Optional[float]:
    """Tolerant ``occ=`` reader: a fraction in [0, 1], or None."""
    if not isinstance(raw, str) or not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    if math.isnan(value) or math.isinf(value):
        return None
    return min(1.0, max(0.0, value))


def _produce_role(server: Any) -> str:
    # active replicas advertise by omission (see ROLE_ACTIVE)
    role = getattr(server, "role", "")
    if role and role != ROLE_ACTIVE:
        return str(role)
    return ""


def parse_role(raw: object) -> str:
    """Tolerant ``role=`` reader: the advertised role name, or ``""``
    (caller decides the default — the gateway treats unknown and
    absent alike as active, because role is advice, not authority)."""
    return raw.strip() if isinstance(raw, str) else ""


def _produce_cc(server: Any) -> str:
    return _duck(server, "compile_cache_note")


def encode_compile_cache(digest: str, cache_dir: str) -> str:
    """``cc=`` value: ``<config digest>:<percent-encoded dir>``. The
    dir is quoted so the note stays one whitespace-free token."""
    if not cache_dir:
        return ""
    return f"{digest}:{quote(str(cache_dir), safe='')}"


def parse_compile_cache(raw: object) -> Tuple[str, str]:
    """Tolerant ``cc=`` reader -> ``(digest, cache_dir)``; malformed
    input yields ``("", "")``, never an exception."""
    if not isinstance(raw, str) or ":" not in raw:
        return "", ""
    digest, _, quoted = raw.partition(":")
    if not digest or not quoted:
        return "", ""
    try:
        return digest, unquote(quoted)
    except Exception:
        return "", ""


def _produce_kv(server: Any) -> str:
    return _duck(server, "kv_note")


def _produce_pd(server: Any) -> str:
    return _duck(server, "prefix_digest_note")


def _produce_gp(server: Any) -> str:
    return _duck(server, "goodput_note")


def _produce_mg(server: Any) -> str:
    return _duck(server, "migrate_note")


#: the wire schema, in member-emission order. CP-NOTEWIRE extracts
#: this tuple by AST, so every entry must be a literal NoteField(...)
#: call with literal ``name=`` and non-None ``produce=``/``parse=``.
FIELDS: Tuple[NoteField, ...] = (
    NoteField(
        name="occ",
        produce=_produce_occ,
        parse=parse_occ,
        doc="slot occupancy fraction, 2 decimals",
    ),
    NoteField(
        name="role",
        produce=_produce_role,
        parse=parse_role,
        doc="replica role; active advertises by omission",
    ),
    NoteField(
        name="cc",
        produce=_produce_cc,
        parse=parse_compile_cache,
        doc="compile-cache advert: <digest>:<quoted dir>",
    ),
    NoteField(
        name="kv",
        produce=_produce_kv,
        parse=parse_kv_counters,
        doc="KV-reuse counters: hits,misses,tokens_reused,"
            "spilled,readmitted (cumulative)",
    ),
    NoteField(
        name="pd",
        produce=_produce_pd,
        parse=parse_digest,
        doc="prefix fingerprint digest: v<version>:<hex8...>",
    ),
    NoteField(
        name="gp",
        produce=_produce_gp,
        parse=_parse_goodput_note,
        doc="device-time ledger: 7 stage seconds + dispatches"
            " + tokens_out (cumulative)",
    ),
    NoteField(
        name="mg",
        produce=_produce_mg,
        parse=parse_migration_note,
        doc="drain-migration progress: counters;fp:target landings",
    ),
)

_BY_NAME: Dict[str, NoteField] = {f.name: f for f in FIELDS}


def field_names() -> FrozenSet[str]:
    """The registered field names — the whole legal wire vocabulary."""
    return frozenset(_BY_NAME)


def member_note(server: Any) -> str:
    """Assemble a member's full heartbeat check output: the literal
    ``ok`` plus every registered field whose producer yields a value.
    This is the ONLY place a note is built — emitting a field any
    other way trips CP-NOTEWIRE."""
    parts = ["ok"]
    for spec in FIELDS:
        value = spec.produce(server)
        if value:
            parts.append(spec.name + "=" + value)
    return " ".join(parts)


def split_note(notes: object) -> Dict[str, str]:
    """Split a check output into raw ``{name: value}`` fields (bare
    words dropped, last duplicate wins). Values are NOT decoded —
    pass each through :func:`parse_field`."""
    return parse_kv_note(notes)


def parse_field(name: str, raw: object) -> Any:
    """Decode one field's raw value with its registered tolerant
    parser. Unregistered names raise KeyError — consumers must not
    invent fields the wire never carries (CP-NOTEWIRE enforces the
    static face of this)."""
    return _BY_NAME[name].parse(raw)
