"""Autoscaler actor: launch capacity into a burst, drain it back out.

The supervisor already knows how to spawn, health-check, and drain
things (jobs/ + the PR 3 drain path); what nothing did until now is
*decide* when the serving fleet needs more or fewer of them. This
actor closes the loop:

- **Signals.** Each tick reads one ``FleetLoad`` snapshot — the
  gateway's admission queue depth plus per-replica DISPATCHED load
  (queued work lives in the depth term only, so nothing is counted
  twice). Utilization is ``(Σ load + queue_depth) /
  (replicas * slots_per_replica)``: queued work counts, so a burst
  registers before a single replica saturates.
- **Scale up.** Utilization at/above ``high_water`` for a sustained
  ``up_sustain_s`` launches one replica (up to ``max_replicas``).
  Launching goes through a ``launcher`` the caller provides — the
  chaos harness spawns in-process replicas; a production deployment
  submits a supervisor job (the jobs machinery already spawns and
  health-checks processes, and a launched replica registers itself
  exactly like any FleetMember).
- **Scale down.** Utilization at/below ``low_water`` for a sustained
  ``down_sustain_s`` retires the least-loaded replica (down to
  ``min_replicas``) through the launcher, whose retire path is PR 3's
  drain: deregister, finish in-flight, stop — zero client-visible 5xx.
- **Repair.** The managed set below ``min_replicas`` (a replica
  SIGKILLed under burst) relaunches immediately — min is a floor, not
  a suggestion.
- **Hysteresis + cooldown.** The high/low-water gap, the sustain
  windows, and a post-event ``cooldown_s`` mean one decision per
  burst edge. Catalog flaps can't thrash it: the managed count comes
  from the launcher (its children don't vanish when a poll tears),
  and the gateway's hold-down keeps the load signal continuous.

The actor is pure asyncio (no threads, no locks); wired to an event
bus it announces scale events as METRIC events and stops on
GLOBAL_SHUTDOWN.
"""
from __future__ import annotations

import asyncio
import logging
import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from ..events import Event, EventBus, EventCode
from ..utils.tasks import spawn
from .standby import equal_jitter

log = logging.getLogger("containerpilot.fleet")


class FleetLoad(NamedTuple):
    """One tick's demand snapshot, as the gateway sees it."""

    queue_depth: int
    per_replica: Dict[str, float]


@dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    #: capacity unit per replica (its decode slots): the denominator
    #: of utilization
    slots_per_replica: int = 2
    high_water: float = 0.75
    low_water: float = 0.25
    up_sustain_s: float = 0.3
    down_sustain_s: float = 1.5
    cooldown_s: float = 1.0
    tick_interval: float = 0.2
    #: failed-launch retry backoff (equal-jitter, doubling to the
    #: cap): a launcher that keeps raising — bad image, full host —
    #: must not be hammered every tick, but the fleet keeps trying
    #: and converges to min the moment launches heal
    launch_backoff_s: float = 0.5
    launch_backoff_cap_s: float = 5.0
    #: seed for the backoff jitter (chaos reproducibility)
    jitter_seed: Optional[int] = None

    def __post_init__(self) -> None:
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if not 0.0 <= self.low_water < self.high_water:
            raise ValueError("need 0 <= low_water < high_water")
        if self.slots_per_replica < 1:
            raise ValueError("slots_per_replica must be >= 1")
        if self.launch_backoff_s <= 0 or (
            self.launch_backoff_cap_s < self.launch_backoff_s
        ):
            raise ValueError(
                "need 0 < launch_backoff_s <= launch_backoff_cap_s"
            )


class Autoscaler:
    """``launcher`` duck type: ``count() -> int`` and
    ``ids() -> list[str]`` (the replicas this actor manages and
    believes alive), ``async launch() -> str`` (spawn + register one
    replica, returning its id), ``async retire(id)`` (drain + stop
    one). ``signals`` returns a FleetLoad per call."""

    def __init__(
        self,
        launcher: Any,
        signals: Callable[[], FleetLoad],
        cfg: Optional[AutoscalerConfig] = None,
        *,
        bus: Optional[EventBus] = None,
        registry: Any = None,
        pool: str = "",
    ) -> None:
        self.launcher = launcher
        self.signals = signals
        self.cfg = cfg or AutoscalerConfig()
        self.bus = bus
        #: which pool this actor sizes ("" = the whole mixed fleet;
        #: a disaggregated fleet runs one autoscaler per role with
        #: pool="prefill"/"decode") — stamped into scale_log entries
        #: and stats so /fleet attributes every decision to its pool.
        #: Only ONE of the co-attached autoscalers may pass the
        #: gateway registry (the metric names would collide).
        self.pool = pool
        self.scale_ups = 0
        self.scale_downs = 0
        #: launches that raised (or replicas that died during their
        #: warmup, surfacing as a raise from launch()): each one
        #: decrements nothing — the failed replica never joined the
        #: managed count — and arms the equal-jitter retry backoff so
        #: a broken launcher can't be hammered every tick
        self.launch_failures = 0
        #: retires whose drain raised (drainer died mid-migration,
        #: TTL-expired during its migrate window): the tick survives,
        #: the managed-count repair refills any real loss next tick
        self.retire_failures = 0
        self._launch_backoff = self.cfg.launch_backoff_s
        self._launch_retry_at = float("-inf")
        self._rng = random.Random(self.cfg.jitter_seed)
        #: every scale decision, stamped on the tick's monotonic
        #: clock — the fleet goodput ledger reads this to compute
        #: time-to-first-routed-token per launch (gateway.
        #: scale_event_report). Bounded: a marathon autoscaler must
        #: not grow an entry per event forever.
        self._scale_log: "deque[Dict[str, Any]]" = deque(maxlen=128)
        self.last_utilization = 0.0
        self.ticks = 0
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None
        # "never scaled": -inf, so the first event can't be held by a
        # cooldown measured against an arbitrary clock origin
        self._last_event = float("-inf")
        self._task: Optional["asyncio.Task[None]"] = None
        self._m_scale = self._g_replicas = self._g_util = None
        self._m_launch_failed = None
        if registry is not None:
            # live in the caller's registry (the gateway's, usually)
            # so /metrics shows admission + autoscaler side by side
            from prometheus_client import Counter, Gauge

            self._m_scale = Counter(
                "containerpilot_autoscaler_scale_events",
                "replica launches/retires decided by the autoscaler",
                ["direction"], registry=registry,
            )
            self._m_launch_failed = Counter(
                "containerpilot_autoscaler_launch_failed",
                "launch attempts that raised (or whose replica died "
                "during warmup); retried with equal-jitter backoff",
                registry=registry,
            )
            self._g_replicas = Gauge(
                "containerpilot_autoscaler_replicas",
                "replicas currently managed by the autoscaler",
                registry=registry,
            )
            self._g_replicas.set_function(self.launcher.count)
            self._g_util = Gauge(
                "containerpilot_autoscaler_utilization",
                "fleet utilization at the last autoscaler tick "
                "((load + queue depth) / (replicas * slots))",
                registry=registry,
            )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "asyncio.Task[None]":
        self._task = spawn(self._loop(), name="fleet-autoscaler")
        return self._task

    async def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self._task = None

    @property
    def scale_log(self) -> List[Dict[str, Any]]:
        """Stamped scale events, oldest first (bounded window)."""
        return list(self._scale_log)

    @property
    def stats(self) -> Dict[str, Any]:
        out = {
            "pool": self.pool or "fleet",
            "replicas": self.launcher.count(),
            "min_replicas": self.cfg.min_replicas,
            "max_replicas": self.cfg.max_replicas,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "launch_failures": self.launch_failures,
            "retire_failures": self.retire_failures,
            "utilization": round(self.last_utilization, 4),
            "high_water": self.cfg.high_water,
            "low_water": self.cfg.low_water,
            "cooldown_s": self.cfg.cooldown_s,
        }
        # a StandbyLauncher exposes its pool (promotions, refills,
        # failures) — surfaced here so /fleet shows the whole
        # promote-instead-of-launch story in one block
        standby = getattr(self.launcher, "standby_stats", None)
        if callable(standby):
            out["standby"] = standby()
        return out

    # -- the control loop -----------------------------------------------

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.tick_interval)
            try:
                await self.tick()
            except Exception as exc:
                # a failed launch/flaky signal must not kill the
                # loop: a dead autoscaler silently strands the fleet
                # at its current size
                log.warning("autoscaler: tick failed: %s", exc)

    async def tick(self, now: Optional[float] = None) -> None:
        """One observe-decide-act round (public so tests and external
        schedulers can drive it without the timer loop)."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        load = self.signals()
        n = self.launcher.count()
        if n < self.cfg.min_replicas:
            # repair path: a managed replica died (SIGKILL under
            # burst). No sustain window and NO cooldown — min is an
            # invariant, and a production-scale cooldown must not
            # leave the fleet under-floor for a minute. launch() is
            # awaited inline and count() reflects it immediately, so
            # repairs can't storm — and a FAILING launcher is gated
            # by the launch-retry backoff, so repairs can't storm
            # through failures either.
            if now >= self._launch_retry_at:
                await self._scale_up(now, reason="below min")
            return
        capacity = max(1, n * self.cfg.slots_per_replica)
        util = (
            sum(load.per_replica.values()) + load.queue_depth
        ) / capacity
        self.last_utilization = util
        if self._g_util is not None:
            self._g_util.set(util)
        if util >= self.cfg.high_water:
            self._under_since = None
            if self._over_since is None:
                self._over_since = now
            sustained = now - self._over_since >= self.cfg.up_sustain_s
            cooled = now - self._last_event >= self.cfg.cooldown_s
            if (
                sustained and cooled and n < self.cfg.max_replicas
                and now >= self._launch_retry_at
            ):
                await self._scale_up(now, reason=f"util {util:.2f}")
        elif util <= self.cfg.low_water:
            self._over_since = None
            if self._under_since is None:
                self._under_since = now
            sustained = now - self._under_since >= self.cfg.down_sustain_s
            cooled = now - self._last_event >= self.cfg.cooldown_s
            if sustained and cooled and n > self.cfg.min_replicas:
                await self._scale_down(now, load)
        else:
            # hysteresis band: demand is roughly matched, hold
            self._over_since = None
            self._under_since = None

    async def _scale_up(self, now: float, reason: str) -> None:
        # the decision instant, stamped BEFORE the launch await: the
        # ledger's time-to-first-routed-token must charge the whole
        # cold start (spawn + boot + compile + register + route) to
        # the scale event, not just the post-launch tail
        decided = time.monotonic()
        try:
            replica_id = await self.launcher.launch()
        except Exception as exc:
            # a launch that raised (launcher bug, full host, replica
            # died during its own warmup) must not leak a managed
            # slot or be re-hammered every tick: count it, arm the
            # equal-jitter retry backoff (the gateway's discipline),
            # and let the next eligible tick try again — repair and
            # pressure paths both honor _launch_retry_at
            self.launch_failures += 1
            if self._m_launch_failed is not None:
                self._m_launch_failed.inc()
            delay = equal_jitter(self._launch_backoff, self._rng)
            self._launch_retry_at = now + delay
            self._launch_backoff = min(
                self._launch_backoff * 2, self.cfg.launch_backoff_cap_s
            )
            log.warning(
                "autoscaler: launch failed (%s): %s; retrying in "
                "%.2fs", reason, exc, delay,
            )
            return
        self._launch_backoff = self.cfg.launch_backoff_s
        self._launch_retry_at = float("-inf")
        self.scale_ups += 1
        entry = {"direction": "up", "replica": replica_id, "at": decided}
        if self.pool:
            entry["pool"] = self.pool
        # a StandbyLauncher reports HOW the launch happened
        # ("promoted" vs "cold"): the split the TTFRT report — and
        # the promoted-path chaos bound — are judged on
        last = getattr(self.launcher, "last_launch", None)
        if isinstance(last, dict) and last.get("mode"):
            entry["mode"] = last["mode"]
        self._scale_log.append(entry)
        self._last_event = now  # the tick's clock, not the wall's
        self._over_since = None
        if self._m_scale is not None:
            self._m_scale.labels("up").inc()
        log.info(
            "autoscaler: launched %s (%s, %s; fleet now %d)",
            replica_id, reason, entry.get("mode", "cold"),
            self.launcher.count(),
        )
        self._announce("scale-up", replica_id)

    async def _scale_down(self, now: float, load: FleetLoad) -> None:
        victim = self._least_loaded(load)
        if victim is None:
            return
        decided = time.monotonic()
        try:
            await self.launcher.retire(victim)
        except Exception as exc:
            # the drainer can die MID-retire (TTL expiry inside its
            # migrate window, a SIGKILL racing the drain): the tick
            # must survive it. Count the failure, don't record a
            # scale-down that didn't cleanly happen, and leave the
            # cooldown armed — if the victim really is gone the
            # managed count falls below min and the ordinary repair
            # path relaunches next tick (no slot leak); sessions the
            # partial migration already landed keep their repointed
            # pins (the gateway applied those as they beat).
            self.retire_failures += 1
            self._last_event = now
            self._under_since = None
            log.warning(
                "autoscaler: retire of %s failed mid-drain: %s",
                victim, exc,
            )
            return
        self.scale_downs += 1
        entry = {"direction": "down", "replica": victim, "at": decided}
        if self.pool:
            entry["pool"] = self.pool
        self._scale_log.append(entry)
        self._last_event = now  # the tick's clock, not the wall's
        self._under_since = None
        if self._m_scale is not None:
            self._m_scale.labels("down").inc()
        log.info(
            "autoscaler: retired %s (fleet now %d)",
            victim, self.launcher.count(),
        )
        self._announce("scale-down", victim)

    def _least_loaded(self, load: FleetLoad) -> Optional[str]:
        """The managed replica with the least folded load; replicas
        the gateway has no signal for count as idle."""
        managed = self.launcher.ids()
        if not managed:
            return None
        return min(
            managed,
            key=lambda rid: (load.per_replica.get(rid, 0.0), rid),
        )

    def _announce(self, what: str, replica_id: str) -> None:
        if self.bus is not None:
            self.bus.publish(
                Event(EventCode.METRIC, f"autoscaler.{what}:{replica_id}")
            )
