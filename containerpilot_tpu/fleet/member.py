"""FleetMember: one serving replica's registration + drain lifecycle.

The supervisor registers *jobs* in discovery (discovery/service.py);
the serving half used to run as a lone replica nothing registered,
watched, or drained. A FleetMember closes that gap for an in-process
``InferenceServer``:

- **Registration + heartbeats.** The replica is advertised under a
  service name with a TTL check (the exact ServiceRegistration /
  ServiceDefinition machinery jobs use, FIFO catalog queue included).
  Heartbeats fire only while the replica is genuinely serveable
  (``server.ready`` and not draining), so a wedged or warming replica
  goes catalog-critical by TTL expiry exactly like a wedged job.
  Because catalog ops drain through the discovery FIFO's long-lived
  thread, an HTTP backend (consul) serves every TTL refresh over ONE
  persistent keep-alive connection instead of dialing each beat.
- **Drain = migrate, then deregister.** ``drain()`` flips the server
  into maintenance (health 503, new generate/completions rejected with
  503 + Retry-After), then — before the catalog record vanishes —
  evacuates the replica's cached KV prefixes to the digest-coldest
  healthy survivors over the handoff wire in reverse
  (``server.migrate_sessions``, bounded by ``migrate_window``),
  heartbeating ``mg=`` progress so the gateway repoints sticky pins as
  each session lands. Only then does it deregister and wait for
  in-flight requests — including running slot-engine rows — to finish.
  Migration failure of any kind (no survivors, dead targets, window
  expiry) falls back to today's behavior: deregister and let the
  survivors re-prefill. ``resume()`` undoes maintenance; the next
  heartbeat lazily re-registers.
- **Control plane.** ``attach_bus(bus)`` subscribes to the event
  bus's maintenance events, so the supervisor's
  ``POST /v3/maintenance/enable|disable`` drains/resumes the replica
  the same way it deregisters jobs.

The ``server`` only needs the drain surface (``ready``, ``draining``,
``enter_maintenance``/``exit_maintenance``, ``inflight``, ``port``) —
anything duck-typing it (tests, future pod frontends) can join a
fleet.
"""
from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, Iterable, Optional

from ..discovery import Backend, ServiceDefinition, ServiceRegistration
from ..events import (
    EventBus,
    EventHandler,
    GLOBAL_ENTER_MAINTENANCE,
    GLOBAL_EXIT_MAINTENANCE,
    GLOBAL_SHUTDOWN,
    QUIT_BY_TEST,
)
from ..utils.tasks import spawn
from . import notes

log = logging.getLogger("containerpilot.fleet")


class FleetMember(EventHandler):
    def __init__(
        self,
        server: Any,
        backend: Backend,
        service_name: str = "inference",
        *,
        ttl: int = 10,
        heartbeat_interval: float = 0.0,
        address: str = "127.0.0.1",
        instance_id: str = "",
        tags: Iterable[str] = (),
        advertise_port: Optional[int] = None,
        migrate_window: float = 5.0,
    ) -> None:
        super().__init__()
        if ttl < 1:
            raise ValueError("ttl must be >= 1 second")
        if migrate_window < 0:
            raise ValueError("migrate_window must be >= 0 seconds")
        self.server = server
        self.backend = backend
        self.service_name = service_name
        self.ttl = ttl
        # default cadence: two beats per TTL window, like the
        # reference's heartbeat guidance — one missed beat never
        # flips a healthy replica critical
        self.heartbeat_interval = heartbeat_interval or ttl / 2.0
        self.instance_id = (
            instance_id or f"{service_name}-{uuid.uuid4().hex[:8]}"
        )
        # advertise a different port than the server's bind (NAT'd
        # deployments; the chaos harness's transport proxies)
        self.advertise_port = advertise_port
        #: seconds a drain spends evacuating KV to survivors before
        #: deregistering; 0 disables migration (today's drain)
        self.migrate_window = float(migrate_window)
        # True only while drain() is inside its migrate window: the
        # ONE draining state that still heartbeats (carrying mg=
        # progress) — after deregister the flag is down again, so a
        # drained replica can never lazily re-register itself
        self._evacuating = False
        self.service = ServiceDefinition(
            ServiceRegistration(
                id=self.instance_id,
                name=service_name,
                port=int(
                    advertise_port
                    or getattr(server, "port", 0) or 0
                ),
                ttl=ttl,
                tags=list(tags),
                address=address,
            ),
            backend,
        )
        self._beat_task: Optional["asyncio.Task[None]"] = None
        self._bus_task: Optional["asyncio.Task[None]"] = None

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        """Start heartbeating. Call after ``server.run()`` so a
        port-0 bind has resolved to the real port."""
        self.service.registration.port = int(
            self.advertise_port
            or getattr(self.server, "port", 0) or 0
        )
        self._beat_task = spawn(
            self._beat_loop(), name=f"fleet-member:{self.instance_id}"
        )

    async def stop(self, deregister: bool = True) -> None:
        for task in (self._beat_task, self._bus_task):
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self._beat_task = self._bus_task = None
        if deregister:
            await self._deregister()

    async def _beat_loop(self) -> None:
        while True:
            try:
                self._beat_once()
            except Exception as exc:
                # a flaky catalog must not kill the heartbeat task: a
                # dead loop silently TTL-expires a HEALTHY replica out
                # of every gateway's routing set within one window
                log.warning(
                    "%s: heartbeat failed: %s", self.instance_id, exc
                )
            await asyncio.sleep(self.heartbeat_interval)

    def _beat_once(self) -> None:
        if (
            getattr(self.server, "draining", False)
            and not self._evacuating
        ):
            return  # drained replicas stay out of the catalog
        if getattr(self.server, "ready", False):
            # lazy-register + TTL refresh; enqueued FIFO off-loop.
            # The beat carries the replica's whole advertisement as
            # the check output — occupancy, role, compile cache,
            # KV-reuse counters, prefix digest, device-time ledger,
            # migration progress — assembled field-by-field from the
            # note-wire registry (``fleet/notes.py``), which owns
            # every field name and its producer/parser pair. The
            # registry duck-types the server surface the way this
            # method always did: an accessor a server doesn't grow
            # simply omits its field, costing zero note bytes.
            self.service.send_heartbeat(
                output=notes.member_note(self.server)
            )
        # not ready (warming, or wedged enough that ready regressed):
        # no beat — an existing record's TTL expiry flips it critical

    async def _deregister(self) -> None:
        future = self.service.deregister()
        if future is not None:
            try:
                await asyncio.wrap_future(future)
            except Exception as exc:  # catalog gone is not fatal here
                log.warning(
                    "%s: deregister failed: %s", self.instance_id, exc
                )

    # -- drain ----------------------------------------------------------

    async def drain(
        self, wait: bool = True, timeout: float = 30.0
    ) -> bool:
        """Maintenance: stop accepting, MIGRATE cached KV to the
        survivors, then stop advertising and finish in-flight.
        Returns True once the replica is idle (always True for
        ``wait=False``; False only on timeout).

        The ordering is the tentpole: migrate -> deregister ->
        in-flight completion. During the bounded migrate window the
        catalog record stays alive and heartbeats ``mg=`` progress,
        so the gateway repoints each landed session's pin BEFORE the
        record vanishes; any migration failure degrades to exactly
        the old drain (deregister + survivor re-prefill), never an
        error."""
        self.server.enter_maintenance()
        if self.migrate_window > 0 and callable(
            getattr(self.server, "migrate_sessions", None)
        ):
            self._evacuating = True
            try:
                targets = await self._survivors()
                if targets:
                    reg = self.service.registration
                    summary = await self.server.migrate_sessions(
                        targets,
                        window_s=self.migrate_window,
                        authority=f"{reg.address}:{reg.port}",
                    )
                    # flush the final landings into the catalog, then
                    # linger two beats — long enough for one full
                    # gateway poll cycle to read them (gateways poll
                    # at least as often as members beat) before the
                    # record deregisters
                    self._beat_once()
                    if int(summary.get("done", 0) or 0) > 0:
                        await asyncio.sleep(
                            min(self.heartbeat_interval * 2.0, 1.0)
                        )
            except Exception as exc:
                # migration is an accelerator for the drain, never a
                # blocker: any failure here means survivors re-prefill
                log.warning(
                    "%s: drain migration failed (%s); falling back "
                    "to plain drain", self.instance_id, exc,
                )
            finally:
                self._evacuating = False
        await self._deregister()
        if not wait:
            return True
        deadline = time.monotonic() + timeout
        while getattr(self.server, "inflight", 0) > 0:
            if time.monotonic() >= deadline:
                log.warning(
                    "%s: drain timed out with %d in flight",
                    self.instance_id,
                    self.server.inflight,
                )
                return False
            await asyncio.sleep(0.02)
        log.info("%s: drained", self.instance_id)
        return True

    async def _survivors(self) -> list:
        """The healthy peers a drain may migrate KV toward:
        ``(instance_id, address, port, fingerprint_set)`` per catalog
        record, excluding self, standbys and the prefill pool (a
        session's KV belongs where decode runs), and peers that are
        themselves mid-migration. Catalog errors return [] — the
        drain then falls back to a plain deregister."""
        loop = asyncio.get_event_loop()
        try:
            instances = await loop.run_in_executor(
                None, self.backend.instances, self.service_name
            )
        except Exception as exc:
            log.warning(
                "%s: survivor discovery failed: %s",
                self.instance_id, exc,
            )
            return []
        out = []
        for inst in instances or []:
            if inst.id == self.instance_id:
                continue
            fields = notes.split_note(getattr(inst, "notes", ""))
            if fields.get("role", "") in ("standby", "prefill"):
                continue
            mg, _landed = notes.parse_field("mg", fields.get("mg", ""))
            if mg["active"]:
                continue
            _ver, fps = notes.parse_field("pd", fields.get("pd", ""))
            out.append(
                (inst.id, inst.address, int(inst.port), fps)
            )
        out.sort(key=lambda t: t[0])
        return out

    def resume(self) -> None:
        """Exit maintenance; the next heartbeat lazily re-registers
        (deregister reset ``was_registered``)."""
        self.server.exit_maintenance()

    # -- control-plane hookup -------------------------------------------

    def attach_bus(self, bus: EventBus) -> "asyncio.Task[None]":
        """Subscribe to the supervisor bus so the control plane's
        maintenance verbs drain/resume this replica."""
        self.subscribe(bus)
        self.register(bus)
        self._bus_task = spawn(
            self._bus_loop(), name=f"fleet-member-bus:{self.instance_id}"
        )
        return self._bus_task

    async def _bus_loop(self) -> None:
        try:
            while True:
                event = await self.next_event()
                if event in (GLOBAL_SHUTDOWN, QUIT_BY_TEST):
                    return
                if event == GLOBAL_ENTER_MAINTENANCE:
                    await self.drain()
                elif event == GLOBAL_EXIT_MAINTENANCE:
                    self.resume()
        except asyncio.CancelledError:
            pass
        finally:
            self.unsubscribe()
            self.unregister()

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return f"fleet.FleetMember[{self.instance_id}]"
