"""Gateway admission control: the fleet's defense against its own users.

The gateway routes well when demand fits capacity; this module decides
what happens when it doesn't. Following the ML-fleet-goodput framing
(PAPERS.md: requests meeting TTFT/TPOT SLOs per chip-second are the
only work that counts), overload is handled by shedding *early and
honestly* instead of queueing until a replica wedges and everything
times out:

- **Bounded dispatch + queue.** At most ``capacity`` requests are in
  flight to replicas (the gateway updates capacity as the healthy set
  changes: ``replicas * per_replica_inflight``); excess work waits in
  a bounded FIFO per priority class. A full queue fast-fails new
  arrivals — a 429 in a millisecond beats a 504 in thirty seconds.
- **Per-request deadlines.** A queued request that can no longer meet
  its TTFT budget is answered 504 the moment the budget expires,
  WITHOUT ever dispatching upstream: decode capacity is never spent
  on an answer the client has already written off.
- **Priority classes.** ``interactive`` (default) outranks ``batch``
  (header-selected): granted first when a slot frees, and batch is
  shed at the queue's high-water mark while interactive still queues
  — exactly the work to sacrifice first in a burst.
- **Per-session token buckets.** One chatty tenant session cannot
  monopolize the queue; over-rate sessions get 429 + the bucket's
  actual refill time.
- **Honest Retry-After.** Every shed carries a Retry-After derived
  from the *observed* queue drain rate (an EWMA-free completion-stamp
  window), so clients that honor it re-arrive roughly when capacity
  exists instead of in a synchronized storm one constant second later.

The controller is asyncio-single-threaded (no locks to publish under)
and holds no HTTP types: the gateway maps its exceptions onto
429/504 responses and mirrors its counters into prometheus.
"""
from __future__ import annotations

import asyncio
import math
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, Optional, Tuple

PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1
PRIORITY_NAMES = {PRIORITY_INTERACTIVE: "interactive",
                  PRIORITY_BATCH: "batch"}

#: completion stamps kept for the drain-rate window
_RATE_WINDOW = 64
#: sessions tracked before the least-recently-seen bucket is evicted
_MAX_SESSIONS = 4096


def delta_seconds(seconds: float) -> int:
    """The ONE Retry-After shaping policy: ceil to integer HTTP
    delta-seconds, floored at 1 (a zero tells clients to hammer),
    capped at 60 (a stall never quotes an hour)."""
    return max(1, min(60, math.ceil(seconds)))


class AdmissionError(Exception):
    """Base: every admission rejection carries an honest retry hint
    and a stable machine label (``label``) — metric buckets must not
    depend on the human-facing reason wording."""

    def __init__(
        self, reason: str, retry_after_s: float, label: str
    ) -> None:
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.label = label


class ShedError(AdmissionError):
    """Load shed (HTTP 429): the queue is past its high-water mark
    (batch, label ``high_water``) or completely full (any priority,
    label ``queue_full``)."""


class SessionLimited(AdmissionError):
    """Per-session token bucket exhausted (HTTP 429, label
    ``session``)."""

    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(reason, retry_after_s, "session")


class DeadlineExpired(Exception):
    """A queued request outlived its TTFT budget (HTTP 504); it was
    never dispatched upstream."""

    def __init__(self, waited_s: float) -> None:
        super().__init__(f"deadline expired after {waited_s:.3f}s queued")
        self.waited_s = waited_s


class Ticket:
    """One admitted request's claim on a dispatch slot. The holder
    must call ``AdmissionController.release(ticket)`` exactly once."""

    __slots__ = ("priority", "enqueued_at", "granted_at", "queued")

    def __init__(self, priority: int, enqueued_at: float) -> None:
        self.priority = priority
        self.enqueued_at = enqueued_at
        self.granted_at = enqueued_at
        self.queued = False


class _Waiter:
    __slots__ = ("ticket", "future", "handle")

    def __init__(
        self,
        ticket: Ticket,
        future: "asyncio.Future[None]",
        handle: Optional[asyncio.TimerHandle],
    ) -> None:
        self.ticket = ticket
        self.future = future
        self.handle = handle


class TokenBucket:
    """Classic token bucket; ``take()`` returns None on admit or the
    seconds until a token exists."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now

    def take(self, now: float) -> Optional[float]:
        self.tokens = min(
            self.burst, self.tokens + (now - self.stamp) * self.rate
        )
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        return (1.0 - self.tokens) / self.rate


class AdmissionController:
    def __init__(
        self,
        *,
        max_queue_depth: int = 256,
        high_water: Optional[int] = None,
        deadline_s: Optional[float] = None,
        per_replica_inflight: int = 64,
        session_rate: float = 0.0,
        session_burst: Optional[float] = None,
    ) -> None:
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if per_replica_inflight < 1:
            raise ValueError("per_replica_inflight must be >= 1")
        self.max_queue_depth = max_queue_depth
        # default high-water: half the queue — batch sheds while the
        # interactive half of the buffer is still open (clamped so a
        # depth-1 queue still constructs)
        self.high_water = (
            high_water
            if high_water is not None
            else max(1, max_queue_depth // 2)
        )
        if not 0 < self.high_water <= max_queue_depth:
            raise ValueError("high_water must be in (0, max_queue_depth]")
        self.deadline_s = deadline_s
        self.per_replica_inflight = per_replica_inflight
        self.session_rate = session_rate
        self.session_burst = (
            session_burst
            if session_burst is not None
            else max(1.0, 2.0 * session_rate)
        )
        # capacity is pushed by the gateway as the healthy set moves;
        # start permissive so requests racing the first poll queue
        # instead of shedding
        self.capacity = per_replica_inflight
        self.inflight = 0
        self._queues: Tuple[Deque[_Waiter], Deque[_Waiter]] = (
            deque(), deque(),
        )
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._completions: Deque[float] = deque(maxlen=_RATE_WINDOW)
        # plain counters, mirrored into prometheus by the gateway and
        # into /fleet verbatim
        self.admitted = 0
        self.queued_total = 0
        self.shed_overload = 0
        self.shed_session = 0
        self.expired = 0
        self.completed = 0

    # -- observability --------------------------------------------------

    @property
    def depth(self) -> int:
        return sum(len(q) for q in self._queues)

    def stats(self) -> Dict[str, object]:
        return {
            "capacity": self.capacity,
            "inflight": self.inflight,
            "depth": self.depth,
            "max_queue_depth": self.max_queue_depth,
            "high_water": self.high_water,
            "deadline_s": self.deadline_s,
            "admitted": self.admitted,
            "queued_total": self.queued_total,
            "shed_overload": self.shed_overload,
            "shed_session": self.shed_session,
            "deadline_expired": self.expired,
            "drain_rate_rps": round(self.drain_rate(), 3),
        }

    # -- drain rate + Retry-After ---------------------------------------

    def drain_rate(self) -> float:
        """Observed completions per second over the recent window. The
        optimistic prior (capacity per second) applies until real
        completions exist — an idle gateway must not tell its first
        shed victim to come back in a minute."""
        stamps = self._completions
        if len(stamps) >= 2:
            span = stamps[-1] - stamps[0]
            if span > 1e-6:
                observed = (len(stamps) - 1) / span
                idle = time.monotonic() - stamps[-1]
                if idle > 2.0 * max(1.0 / observed, 0.1):
                    if self.inflight > 0 or self.depth > 0:
                        # work is pending but completions STOPPED:
                        # the fleet is stalling — the estimate must
                        # decay DOWN, so a wedged fleet quotes long
                        # honest Retry-Afters, not capacity-optimism
                        observed = observed / (1.0 + idle)
                    else:
                        # quiet because there's no demand: the stale
                        # window is ancient history, quote the
                        # optimistic prior
                        observed = max(float(self.capacity), observed)
                return max(observed, 0.1)
        return max(float(self.capacity), 1.0)

    def retry_after_s(self) -> int:
        """Seconds until the CURRENT backlog (queue + in-flight, plus
        the caller's own request) should have drained, at the
        observed completion rate, shaped by ``delta_seconds``."""
        backlog = self.depth + self.inflight + 1
        return delta_seconds(backlog / self.drain_rate())

    # -- admission ------------------------------------------------------

    def set_capacity(self, replicas: int) -> None:
        """Called by the gateway after each catalog poll; growth
        grants queued waiters immediately."""
        self.capacity = max(1, replicas) * self.per_replica_inflight
        self._pump()

    def check_session(self, session: Optional[str]) -> None:
        """Per-session token bucket; raises SessionLimited over rate.
        Disabled when ``session_rate`` is 0."""
        if self.session_rate <= 0.0 or not session:
            return
        now = time.monotonic()
        bucket = self._buckets.get(session)
        if bucket is None:
            bucket = TokenBucket(self.session_rate, self.session_burst, now)
            self._buckets[session] = bucket
            while len(self._buckets) > _MAX_SESSIONS:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(session)
        wait = bucket.take(now)
        if wait is not None:
            self.shed_session += 1
            # same shaping as every other refusal: a tiny rate must
            # not quote an hour-scale Retry-After
            raise SessionLimited(
                f"session {session!r} over rate",
                float(delta_seconds(wait)),
            )

    async def admit(
        self,
        priority: int = PRIORITY_INTERACTIVE,
        session: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> Ticket:
        """Admit one request: grant a dispatch slot now, queue for one,
        or reject. Raises SessionLimited/ShedError (→ 429) and
        DeadlineExpired (→ 504, never dispatched)."""
        if priority not in PRIORITY_NAMES:
            raise ValueError(f"unknown priority {priority!r}")
        self.check_session(session)
        now = time.monotonic()
        ticket = Ticket(priority, now)
        # serve the queue before ourselves: a fast-path grant past
        # waiting requests would invert arrival order under churn
        self._pump()
        if self.inflight < self.capacity and self.depth == 0:
            self.inflight += 1
            self.admitted += 1
            ticket.granted_at = now
            return ticket
        depth = self.depth
        if depth >= self.max_queue_depth:
            self.shed_overload += 1
            raise ShedError(
                "queue full", self.retry_after_s(), "queue_full"
            )
        if depth >= self.high_water and priority >= PRIORITY_BATCH:
            self.shed_overload += 1
            raise ShedError(
                "queue past high-water; batch shed",
                self.retry_after_s(), "high_water",
            )
        loop = asyncio.get_event_loop()
        future: "asyncio.Future[None]" = loop.create_future()
        budget = deadline_s if deadline_s is not None else self.deadline_s
        waiter = _Waiter(ticket, future, None)
        if budget is not None:
            waiter.handle = loop.call_later(
                budget, self._expire, waiter
            )
        ticket.queued = True
        self.queued_total += 1
        self._queues[priority].append(waiter)
        try:
            await future
        except asyncio.CancelledError:
            if future.done() and not future.cancelled() and (
                future.exception() is None
            ):
                # granted in the same tick the awaiting task was
                # cancelled: the grant bumped inflight, and no one
                # will ever release this ticket — take the slot back
                self.inflight -= 1
                self._pump()
            else:
                # still queued: leave no ghost behind
                self._discard(waiter)
            raise
        ticket.granted_at = time.monotonic()
        return ticket

    def release(self, ticket: Ticket, completed: bool = True) -> None:
        """Return ``ticket``'s slot. ``completed`` feeds the drain-rate
        window (a request that failed upstream is not evidence the
        queue drains)."""
        self.inflight -= 1
        if completed:
            self.completed += 1
            self._completions.append(time.monotonic())
        self._pump()

    # -- internals ------------------------------------------------------

    def _expire(self, waiter: _Waiter) -> None:
        if waiter.future.done():
            return
        self._discard(waiter)
        self.expired += 1
        waiter.future.set_exception(
            DeadlineExpired(time.monotonic() - waiter.ticket.enqueued_at)
        )

    def _discard(self, waiter: _Waiter) -> None:
        if waiter.handle is not None:
            waiter.handle.cancel()
            waiter.handle = None
        for q in self._queues:
            try:
                q.remove(waiter)
                return
            except ValueError:
                continue

    def _pump(self) -> None:
        """Grant queued waiters while capacity exists, interactive
        first; FIFO within a class."""
        while self.inflight < self.capacity:
            waiter = None
            for q in self._queues:
                while q:
                    candidate = q.popleft()
                    if not candidate.future.done():
                        waiter = candidate
                        break
                if waiter is not None:
                    break
            if waiter is None:
                return
            if waiter.handle is not None:
                waiter.handle.cancel()
                waiter.handle = None
            self.inflight += 1
            self.admitted += 1
            waiter.future.set_result(None)


def parse_priority(raw: str) -> int:
    """Map the ``X-Priority`` header onto a class; anything not
    explicitly ``batch`` is interactive (fail-open for end users)."""
    return (
        PRIORITY_BATCH
        if raw.strip().lower() == "batch"
        else PRIORITY_INTERACTIVE
    )
