"""FleetGateway: health-aware HTTP routing over discovered replicas.

The fleet's data plane. A gateway process discovers healthy
``InferenceServer`` replicas through a watches-style poll on the
discovery Backend (the same ``check_for_upstream_changes`` discipline
supervisor Watch actors use) and proxies the inference API over them:

- **Routing**: least-outstanding-requests across the healthy set,
  with optional affinity — requests carrying a ``session_id`` (or an
  ``X-Affinity-Key`` header, or — in ``prefix`` mode — sharing a
  prompt prefix) stick to one replica so its prefix KV cache keeps
  hitting. A sticky key whose replica drained away is re-routed and
  counted (``drained_away``).
- **Cache-contents-aware routing**: replicas advertise a versioned
  fingerprint digest of their warm prompt prefixes (kvtier/digest.py)
  through heartbeat notes, the same channel occupancy travels. When a
  request has no live sticky pin — a fresh session, a re-pin after a
  drain, a retry exclusion — ``_pick`` prefers a replica whose digest
  contains the request's prefix fingerprint, bounded by a load slack
  (``cache_slack``) so a wedged-but-warm replica is never chosen over
  a healthy cold one. ``cache_hint_hits``/``cache_hint_misses`` and a
  fleet-wide ``tokens_reused`` gauge land on ``/metrics`` + ``/fleet``.
- **Retries**: generation requests are idempotent under a fixed seed,
  so a transport failure or a 503 (a draining or warming replica)
  retries on a DIFFERENT replica with capped exponential backoff —
  the drain path's client-visible half: zero 5xx while a replica
  leaves the fleet.
- **Hedging**: once enough latency samples exist, a buffered request
  still unanswered at the observed tail quantile dispatches a hedge
  to a second replica; first success wins, the loser is cancelled
  (its connection closes, and the replica's continuous-batching loop
  absorbs the wasted decode).
- **Streaming**: SSE responses (``"stream": true``) relay chunk-by-
  chunk; retries apply only BEFORE the first upstream byte, never
  mid-stream.
- **Multiplexed transport**: with ``mux=True`` (default) each
  replica's traffic — buffered and SSE alike — rides interleaved
  cp-mux/1 streams on ONE warm upgraded connection (pool.py's
  MuxConnection over utils/http's frame codec), so in-flight
  concurrency per replica stops being bounded by socket count, a
  hedge loser or abandoned client costs a CANCEL frame instead of a
  connection teardown (``mux_cancels`` / ``conns_saved_by_mux``
  counters), and a slow SSE consumer stalls only its own stream's
  window. Replicas that decline the upgrade fall back per-replica to
  the classic pooled path below, negotiated transparently.
- **Connection pooling**: buffered hops to non-mux replicas reuse a
  bounded LIFO pool of keep-alive connections per replica (pool.py)
  instead of dialing per request; pooled connections are evicted when
  a replica leaves the healthy set or fails a request, a stale pooled
  connection gets ONE transparent redial, and hedged/retried legs
  always take distinct connections.
- **Metrics**: per-replica counters (routed, retried, hedged,
  drained_away, pool_hit/pool_miss/pool_evicted) plus request/latency
  series in a private registry on ``GET /metrics`` (utils/prom
  exposition), and a ``GET /fleet`` JSON snapshot for runbooks.

The gateway holds no model state: it is restartable at will, N
gateways can front one fleet, and every later scale PR (autoscaling,
multi-backend, spillover) slots in behind this surface.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import random
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterable, List, Optional, Set, Tuple

from ..discovery import Backend
from ..kvtier import FP_TOKENS, prefix_fingerprint
from ..analysis.loopcheck import LoopLagProbe
from ..telemetry import goodput as goodput_mod
from ..telemetry import tracing
from ..utils.http import (
    HTTPServer,
    Request,
    Response,
    StreamingResponse,
    timed_read,
)
from ..utils.prom import (
    ensure_build_info,
    ensure_loop_lag_gauge,
    exposition,
)
from ..utils.tasks import spawn
from ..watches import poll_upstream
from . import notes as notes_mod
from .admission import (
    AdmissionController,
    AdmissionError,
    DeadlineExpired,
    PRIORITY_NAMES,
    delta_seconds,
    parse_priority,
)
from .pool import (
    ConnectionPool,
    MuxStream,
    MuxStreamError,
    PooledConnection,
    StaleConnection,
    StaleMuxConnection,
    UpstreamError,
)
from .standby import (
    ROLE_ACTIVE,
    ROLE_DECODE,
    ROLE_PREFILL,
    ROLE_STANDBY,
    equal_jitter,
)

log = logging.getLogger("containerpilot.fleet")

# upstream statuses worth moving to another replica for: 503 is a
# draining/warming replica by this repo's own convention
RETRYABLE_STATUSES = frozenset({503})
#: roles a heartbeat note may carry; anything else (a newer replica
#: speaking a role this gateway predates) routes as active — advice
#: degrades, it never partitions
_KNOWN_ROLES = frozenset(
    {ROLE_ACTIVE, ROLE_STANDBY, ROLE_PREFILL, ROLE_DECODE}
)
#: every role that serves traffic (standby is parked capacity)
_SERVING_ROLES = (ROLE_ACTIVE, ROLE_PREFILL, ROLE_DECODE)
# replica endpoints the disaggregated handoff drives (serve.py):
# seed a prefill replica's cache, then have the decode replica pull
# the KV prefix replica-to-replica (kvtier/handoff.py)
PREFILL_PATH = "/v1/prefill"
KV_PULL_PATH = "/v1/kv/pull"
AFFINITY_MODES = ("none", "session", "prefix")
STICKY_CAPACITY = 4096
PREFIX_TOKENS = 16  # ids of the prompt prefix hashed in "prefix" mode
PREFIX_CHARS = 64   # chars of a text prompt hashed in "prefix" mode
HEDGE_MIN_SAMPLES = 20
# bound on a single upstream response body, Content-Length-declared or
# accumulated on the read-to-EOF (close-delimited) path: a replica that
# lies about its framing can't balloon the gateway's memory
MAX_UPSTREAM_BODY = 64 * 1024 * 1024


@dataclass
class Replica:
    """One healthy replica as the router sees it."""

    id: str
    address: str
    port: int
    outstanding: int = 0
    #: admission-queued requests whose sticky key pins here: work this
    #: replica WILL absorb that hasn't dispatched yet. Folded into the
    #: routing load signal — counting only dispatched requests made a
    #: replica absorbing queued work look idle the moment it wedged
    #: mid-burst, and least-outstanding kept feeding it.
    queued: int = 0
    first_seen: float = field(default_factory=time.monotonic)
    #: prefix fingerprints this replica advertised as warm (its
    #: heartbeat's ``pd=`` digest) — what cache-aware routing scores
    digest: frozenset = frozenset()
    digest_version: int = -1
    #: monotonic stamp of the last digest update (staleness signal)
    digest_at: float = 0.0
    #: last-seen reuse counters from the ``kv=`` note field
    kv: Dict[str, int] = field(default_factory=dict)
    #: last-seen device-time ledger totals from the ``gp=`` note
    #: field (cumulative stage seconds + dispatches/tokens; merged
    #: elementwise-max against torn notes, like ``kv``)
    goodput: Dict[str, float] = field(default_factory=dict)
    #: monotonic stamp of the first 200 a generate/completions got
    #: from this replica — the gateway half of time-to-first-routed-
    #: token after a scale event
    first_ok_at: Optional[float] = None
    #: fleet role from the ``role=`` heartbeat field: a ``standby``
    #: replica is warm, promotable capacity — catalog-visible and
    #: heartbeating, but excluded from ``_pick`` and from admission
    #: capacity until its post-promotion beat drops the field
    role: str = ROLE_ACTIVE
    #: compile-cache advertisement (``cc=<digest>:<dir>``, raw):
    #: same-host launches adopt the dir; surfaced on /fleet
    compile_cache: str = ""
    #: True while this replica is evacuating its sessions (``mg=``
    #: note, active flag): routing avoids NEW pins on it whenever
    #: any alternative exists — it is about to leave, and a fresh
    #: session there would need migrating right back
    migrating: bool = False
    #: last-seen cumulative ``mg=`` counters (the delta source for
    #: the fleet migration accounting; elementwise-max merged like
    #: the kv counters, so torn notes never regress them)
    migration: Dict[str, int] = field(default_factory=dict)
    #: fp -> survivor id landings already applied (so each landing
    #: repoints pins exactly once however many beats re-carry it)
    migrated: Dict[int, str] = field(default_factory=dict)

    @property
    def load(self) -> int:
        return self.outstanding + self.queued

    @property
    def authority(self) -> str:
        return f"{self.address}:{self.port}"


async def _send_on(
    conn: PooledConnection,
    method: str,
    path: str,
    body: bytes,
    read_timeout: float,
) -> Tuple[int, Dict[str, str]]:
    """Send one request on an already-open connection and parse the
    status line + headers. The caller keeps ownership of ``conn`` (and
    decides pool release vs discard after the body).

    The status line is bounded by ``read_timeout`` — the replica's
    HTTP server writes it after the handler finishes, so for a
    buffered generation it arrives only once the whole decode is done
    (seconds to minutes). Failures on a REUSED connection before any
    response byte raise StaleConnection: the server answered nothing,
    so resending on a fresh dial cannot double-apply the request."""
    reader, writer = conn.reader, conn.writer
    try:
        # cross-hop trace propagation: the replica records its spans
        # under the SAME id and hands back a digest (tracing.py)
        trace_id = tracing.current_trace_id()
        trace_line = (
            f"{tracing.TRACE_HEADER}: {trace_id}\r\n" if trace_id else ""
        )
        head = (
            f"{method} {path} HTTP/1.1\r\n"
            f"Host: {conn.authority}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{trace_line}"
            f"Connection: keep-alive\r\n\r\n"
        )
        writer.write(head.encode() + body)
        await writer.drain()
        # ONE timed read for the whole response head: a wait_for per
        # header line costs a Task + timer each, which is measurable
        # on this hot path
        try:
            head_blob = await timed_read(
                reader, reader.readuntil(b"\r\n\r\n"), read_timeout
            )
        except asyncio.IncompleteReadError as exc:
            if not exc.partial:
                # EOF before any response byte
                if conn.reused:
                    raise StaleConnection(
                        f"{conn.authority}: pooled connection was "
                        f"closed by the server"
                    ) from None
                raise UpstreamError(
                    f"{conn.authority}: closed before the status line"
                ) from None
            # EOF inside the status line or header block: a replica
            # that died after the status line is a FAILED request,
            # never an empty-header success — surfacing it here is
            # what arms the retry/hedge path
            raise UpstreamError(
                f"{conn.authority}: EOF inside response headers "
                f"({exc.partial[:80]!r})"
            ) from None
        except asyncio.LimitOverrunError:
            raise UpstreamError(
                f"{conn.authority}: response head too large"
            ) from None
        lines = head_blob.split(b"\r\n")
        parts = lines[0].decode("latin-1").split(None, 2)
        if (
            len(parts) < 2
            or not parts[1].isascii()
            or not parts[1].isdigit()
        ):
            raise UpstreamError(
                f"{conn.authority}: malformed status line "
                f"{lines[0]!r}"
            )
        status = int(parts[1])
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            key, _, value = line.decode("latin-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        return status, headers
    except (ConnectionResetError, BrokenPipeError) as exc:
        # a write that bounced off a dead pooled connection: the
        # server reaped it while idle (its FIN can race our send)
        if conn.reused:
            raise StaleConnection(f"{conn.authority}: {exc}") from None
        raise UpstreamError(f"{conn.authority}: {exc}") from None
    except (OSError, asyncio.TimeoutError, UnicodeDecodeError) as exc:
        raise UpstreamError(f"{conn.authority}: {exc}") from None


def _parse_content_length(headers: Dict[str, str]) -> Optional[int]:
    """Strict Content-Length: ASCII decimal digits only. ``int()`` and
    ``str.isdigit()`` both accept Unicode digits ("١٢٣"), and the old
    isdigit() gate silently fell back to read-to-EOF on garbage — a
    malformed value now fails the request instead of mis-framing it."""
    raw = headers.get("content-length")
    if raw is None:
        return None
    if not raw.isascii() or not raw.isdigit():
        raise UpstreamError(f"malformed Content-Length {raw!r}")
    return int(raw)


async def _read_body(
    reader: asyncio.StreamReader, headers: Dict[str, str], timeout: float
) -> bytes:
    """Read a buffered response body: Content-Length when present,
    else until EOF (close-delimited). Both paths are capped at
    MAX_UPSTREAM_BODY; every failure mode raises UpstreamError."""
    length = _parse_content_length(headers)
    if length is not None:
        if length > MAX_UPSTREAM_BODY:
            raise UpstreamError(f"Content-Length {length} exceeds cap")
        try:
            return await timed_read(
                reader, reader.readexactly(length), timeout
            )
        except (
            OSError, asyncio.TimeoutError, asyncio.IncompleteReadError,
        ) as exc:
            raise UpstreamError(f"body read failed: {exc}") from None
    chunks: List[bytes] = []
    total = 0
    while True:
        try:
            chunk = await timed_read(reader, reader.read(65536), timeout)
        except (OSError, asyncio.TimeoutError) as exc:
            raise UpstreamError(f"body read failed: {exc}") from None
        if not chunk:
            return b"".join(chunks)
        total += len(chunk)
        if total > MAX_UPSTREAM_BODY:
            raise UpstreamError("close-delimited body exceeds cap")
        chunks.append(chunk)


#: bytes of relayed SSE kept for the final ``done`` frame's span
#: digest; events are small, so this comfortably holds the last one
_TAIL_KEEP = 4096


def _keep_tail(tail: bytearray, chunk: bytes) -> None:
    """Retain the last ``_TAIL_KEEP`` bytes of a relayed stream —
    enough to recover the terminal SSE event after EOF without ever
    buffering the stream itself."""
    tail += chunk
    if len(tail) > _TAIL_KEEP:
        del tail[:len(tail) - _TAIL_KEEP]


def _tail_digest(tail: bytes) -> str:
    """The replica span digest off a relayed stream's final ``done``
    event, or "" when the stream ended without one (abandon,
    truncation) — telemetry extraction must never fail a relay."""
    idx = tail.rfind(b"data: ")
    if idx < 0:
        return ""
    raw = tail[idx + len(b"data: "):].split(b"\n\n", 1)[0]
    try:
        event = json.loads(raw)
    except ValueError:
        return ""
    if not isinstance(event, dict) or not event.get("done"):
        return ""
    digest = event.get("spans")
    return digest if isinstance(digest, str) else ""


def _reusable(headers: Dict[str, str]) -> bool:
    """A connection goes back to the pool only when the response was
    Content-Length-framed (so the body had a definite end) and the
    server didn't announce ``Connection: close``."""
    return (
        "content-length" in headers
        and "close" not in headers.get("connection", "").lower()
    )


class FleetGateway:
    def __init__(
        self,
        backend: Backend,
        service_name: str = "inference",
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        tag: str = "",
        poll_interval: float = 1.0,
        retries: int = 2,
        retry_backoff: float = 0.05,
        retry_backoff_cap: float = 0.5,
        retry_jitter: float = 0.5,
        jitter_seed: Optional[int] = None,
        empty_poll_threshold: int = 3,
        hedge: bool = True,
        hedge_quantile: float = 0.95,
        hedge_min_ms: float = 50.0,
        hedge_after_ms: Optional[float] = None,
        affinity: str = "session",
        cache_routing: bool = True,
        cache_slack: int = 2,
        sticky_capacity: int = STICKY_CAPACITY,
        connect_timeout: float = 5.0,
        request_timeout: float = 600.0,
        pool_max_idle: int = 8,
        pool_idle_ttl: float = 30.0,
        pool_max_uses: int = 1000,
        mux: bool = True,
        trace: bool = True,
        admission: Optional[Dict[str, Any]] = None,
    ) -> None:
        if affinity not in AFFINITY_MODES:
            raise ValueError(f"affinity must be one of {AFFINITY_MODES}")
        self.backend = backend
        self.service_name = service_name
        self.host = host
        self.port = port
        self.tag = tag
        self.poll_interval = poll_interval
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.retry_backoff_cap = retry_backoff_cap
        # jittered backoff: when a replica dies under load, every
        # in-flight request fails in the same instant — identical
        # backoffs would re-dispatch them as one synchronized wave
        # onto the survivors. Seedable so chaos runs are reproducible.
        if not 0.0 <= retry_jitter <= 1.0:
            raise ValueError("retry_jitter must be in [0, 1]")
        self.retry_jitter = retry_jitter
        self._rng = random.Random(jitter_seed)
        # catalog-flap hold-down: a previously non-empty routing table
        # is wiped only after this many CONSECUTIVE empty polls — one
        # torn/empty catalog read must not turn into client-visible
        # "no healthy replicas" 503s
        if empty_poll_threshold < 1:
            raise ValueError("empty_poll_threshold must be >= 1")
        self.empty_poll_threshold = empty_poll_threshold
        self._empty_polls = 0
        self.flaps_damped = 0  # plain mirror of the counter for /fleet
        self.hedge = hedge
        self.hedge_quantile = hedge_quantile
        self.hedge_min_ms = hedge_min_ms
        # fixed hedge deadline override (tests, known-SLO deployments);
        # None = learn the tail from observed latencies
        self.hedge_after_ms = hedge_after_ms
        self.affinity = affinity
        # cache-contents-aware routing: when a request has no live
        # sticky pin, prefer a replica advertising the request's
        # prefix fingerprint — but only within ``cache_slack`` extra
        # load of the least-loaded candidate, so warmth never
        # overrides a wedged/overloaded replica's load signal
        self.cache_routing = cache_routing
        if cache_slack < 0:
            raise ValueError("cache_slack must be >= 0")
        self.cache_slack = cache_slack
        if sticky_capacity < 1:
            raise ValueError("sticky_capacity must be >= 1")
        self.sticky_capacity = sticky_capacity
        self.sticky_evicted = 0  # plain mirror for /fleet
        self.hint_hits = 0       # plain mirrors of the hint counters
        self.hint_misses = 0
        #: plain mirrors of the KV-handoff counters for /fleet
        #: (docs/60 § disaggregated serving): completed transfers,
        #: bytes moved, failures (fell back to local prefill), and
        #: handoffs skipped because the decode target was already
        #: digest-warm (the multiturn follow-up fast path); ms_sum
        #: accumulates per-transfer wall ms so total/ms_sum yields
        #: the mean handoff cost without scraping the histogram
        self.handoffs: Dict[str, float] = {
            "total": 0, "bytes": 0, "failed": 0, "skipped_warm": 0,
            "ms_sum": 0.0,
        }
        #: plain mirrors of the drain-migration counters for /fleet
        #: (docs/60 § drain runbook): sessions landed on a survivor,
        #: failed pushes (fell back to re-prefill), window-expiry
        #: timeouts, sticky pins repointed off landings, and 503
        #: drain answers that carried X-CP-Migrated-To
        self.migrations: Dict[str, int] = {
            "sessions_migrated": 0, "failed": 0, "timeout": 0,
            "pins_repointed": 0, "drain_answers": 0,
        }
        #: sticky key -> prefix fingerprint, recorded as pins form:
        #: the join the migration repoint needs (an ``mg=`` landing
        #: names an fp; this maps it back to the pinned sessions)
        self._session_fp: Dict[str, int] = {}
        #: final tokens_reused advertised by replicas that have LEFT
        #: the fleet, keyed by id — the fleet-wide gauge must not
        #: forget a drained replica's contribution, and keying by id
        #: lets a flapped-then-rejoined replica reclaim its own entry
        #: instead of being double-counted
        self._reuse_departed: Dict[str, int] = {}
        #: final ledger totals of replicas that LEFT the fleet, keyed
        #: by id — the fleet device-time ledger folds departed
        #: replicas in exactly like ``tokens_reused`` does (their
        #: boot/compile badput happened; a drain must not erase it),
        #: and a flapped-then-rejoined id reclaims its entry
        self._goodput_departed: Dict[str, Dict[str, float]] = {}
        #: first-200 stamps per replica id, surviving departure (a
        #: scale-up that served traffic and then drained still has a
        #: time-to-first-routed-token worth reporting)
        self._first_ok: Dict[str, float] = {}
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout

        self.mux = mux
        # request tracing: on by default (the bench pins its cost at
        # effectively-free); --no-trace is the bench's A/B control,
        # not an operational recommendation
        self.trace = trace
        self._tracer = tracing.TraceRecorder("gateway")
        # staleness signal for flap triage: monotonic stamp of the
        # last catalog poll that RETURNED (empty or not); None until
        # the first one lands
        self._last_poll: Optional[float] = None
        self._replicas: Dict[str, Replica] = {}
        self._pool = ConnectionPool(
            max_idle=pool_max_idle,
            idle_ttl=pool_idle_ttl,
            max_uses=pool_max_uses,
            on_event=self._pool_event,
            mux=mux,
        )
        # admission control in front of routing: bounded queue,
        # deadlines, priorities, token buckets, shedding. The default
        # knobs are pass-through-permissive (huge per-replica inflight,
        # no deadline), so a gateway that doesn't configure overload
        # behaves exactly as before while the counters still exist.
        self._admission = AdmissionController(**(admission or {}))
        # graceful shutdown: stop admitting, finish queued + in-flight
        self.draining = False
        #: attached autoscalers, in attach order — a mixed fleet has
        #: one; a disaggregated fleet attaches one per pool so the
        #: prefill and decode pools size independently
        self._autoscalers: List[Any] = []
        self._sticky: "OrderedDict[str, str]" = OrderedDict()
        # per-endpoint pools of recent 200-latencies (seconds): the
        # hedge threshold for generate must not be poisoned by
        # millisecond score/model samples sharing one tail estimate
        self._latencies: Dict[str, Deque[float]] = {}
        self._poll_task: Optional["asyncio.Task[None]"] = None

        # private registry: N gateways (or a gateway next to a
        # supervisor) in one process must not collide (utils/prom.py)
        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Gauge,
            Histogram,
        )

        self._registry = CollectorRegistry()
        self._m_requests = Counter(
            "containerpilot_gateway_requests",
            "gateway requests by endpoint and status code",
            ["endpoint", "code"], registry=self._registry,
        )
        self._m_latency = Histogram(
            "containerpilot_gateway_request_seconds",
            "gateway request wall time, by endpoint",
            ["endpoint"], registry=self._registry,
            buckets=(.005, .02, .05, .1, .25, .5, 1, 2.5, 5, 10, 30, 60),
        )
        self._m_routed = Counter(
            "containerpilot_gateway_routed",
            "requests dispatched to a replica",
            ["replica"], registry=self._registry,
        )
        self._m_retried = Counter(
            "containerpilot_gateway_retried",
            "requests retried away from a replica "
            "(transport failure or retryable status)",
            ["replica"], registry=self._registry,
        )
        self._m_hedged = Counter(
            "containerpilot_gateway_hedged",
            "hedge dispatches launched against a slow replica",
            ["replica"], registry=self._registry,
        )
        self._m_drained = Counter(
            "containerpilot_gateway_drained_away",
            "sticky keys re-routed because their replica left the fleet",
            ["replica"], registry=self._registry,
        )
        self._g_replicas = Gauge(
            "containerpilot_gateway_healthy_replicas",
            "replicas currently in the healthy routing set",
            registry=self._registry,
        )
        self._g_standby = Gauge(
            "containerpilot_gateway_standby_replicas",
            "healthy replicas parked in the standby role: warm, "
            "promotable, excluded from routing and admission "
            "capacity (fleet/standby.py)",
            registry=self._registry,
        )
        self._g_role = Gauge(
            "containerpilot_gateway_replicas_by_role",
            "healthy replicas by fleet role (active/prefill/decode/"
            "standby) — the disaggregated pool-size view (docs/60)",
            ["role"], registry=self._registry,
        )
        self._m_handoffs = Counter(
            "containerpilot_gateway_handoffs_total",
            "prefill->decode KV handoffs completed (prefix prefilled "
            "on the prefill pool, pulled by the decode target over "
            "cp-mux/1, readmitted through reuse_admission)",
            registry=self._registry,
        )
        self._m_handoff_failed = Counter(
            "containerpilot_gateway_handoffs_failed",
            "KV handoffs that failed any leg (prefill seed, pull, "
            "digest verify); the request fell back to local prefill "
            "on its routed replica — never a client-visible error",
            registry=self._registry,
        )
        self._m_handoff_bytes = Counter(
            "containerpilot_gateway_handoff_bytes",
            "KV bytes moved replica-to-replica by completed handoffs",
            registry=self._registry,
        )
        self._m_handoff_ms = Histogram(
            "containerpilot_gateway_handoff_ms",
            "wall milliseconds per completed KV handoff (prefill "
            "seed + replica-to-replica pull), the cost bound the "
            "disagg bench pins",
            registry=self._registry,
            buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                     2500, 5000),
        )
        self._m_migrated = Counter(
            "containerpilot_gateway_sessions_migrated",
            "sessions landed on a survivor by a drain migration "
            "(KV pushed — or already warm — and the fingerprint "
            "advertised as landed over the mg= note channel)",
            registry=self._registry,
        )
        self._m_migration_failed = Counter(
            "containerpilot_gateway_migration_failed",
            "drain-migration pushes that failed (dead target, "
            "poisoned chunk, declined adoption); the session fell "
            "back to re-prefill on its survivor — never a client "
            "error",
            registry=self._registry,
        )
        self._m_migration_timeout = Counter(
            "containerpilot_gateway_migration_timeout",
            "sessions left unmoved when a drain's migrate window "
            "expired; they fell back to cache-aware re-pin + "
            "re-prefill, today's drain behavior",
            registry=self._registry,
        )
        self._m_flaps_damped = Counter(
            "containerpilot_gateway_catalog_flaps_damped",
            "empty catalog polls absorbed by the hold-down instead of "
            "wiping a previously non-empty routing table",
            registry=self._registry,
        )
        self._m_pool_hits = Counter(
            "containerpilot_gateway_pool_hit",
            "proxied requests served over a reused pooled connection",
            ["replica"], registry=self._registry,
        )
        self._m_pool_misses = Counter(
            "containerpilot_gateway_pool_miss",
            "proxied requests that had to dial a fresh connection",
            ["replica"], registry=self._registry,
        )
        self._m_pool_evicted = Counter(
            "containerpilot_gateway_pool_evicted",
            "pooled connections dropped (replica left the healthy "
            "set, failed a request, or the connection went stale)",
            ["replica"], registry=self._registry,
        )
        self._m_mux_streams = Counter(
            "containerpilot_gateway_mux_streams",
            "proxied requests carried as cp-mux streams on a shared "
            "upgraded connection",
            ["replica"], registry=self._registry,
        )
        self._m_mux_cancels = Counter(
            "containerpilot_gateway_mux_cancels",
            "streams aborted with a CANCEL frame (hedge losers, "
            "abandoned clients, per-stream deadlines) with the shared "
            "connection left in service",
            ["replica"], registry=self._registry,
        )
        self._m_conns_saved = Counter(
            "containerpilot_gateway_conns_saved_by_mux",
            "upstream connections kept alive where the HTTP/1.1 path "
            "would have discarded one (cancelled legs, completed "
            "close-delimited streams)",
            ["replica"], registry=self._registry,
        )
        self._m_admitted = Counter(
            "containerpilot_gateway_admitted",
            "requests granted a dispatch slot, by priority class",
            ["priority"], registry=self._registry,
        )
        self._m_shed = Counter(
            "containerpilot_gateway_shed",
            "requests answered 429 by admission control, by reason "
            "(high_water / queue_full / session)",
            ["reason"], registry=self._registry,
        )
        self._m_expired = Counter(
            "containerpilot_gateway_deadline_expired",
            "queued requests 504'd at their TTFT deadline without "
            "ever dispatching upstream",
            registry=self._registry,
        )
        self._g_admission_depth = Gauge(
            "containerpilot_gateway_admission_depth",
            "requests waiting in the admission queue",
            registry=self._registry,
        )
        self._g_admission_depth.set_function(
            lambda: self._admission.depth
        )
        self._g_admission_inflight = Gauge(
            "containerpilot_gateway_admission_inflight",
            "requests holding a dispatch slot",
            registry=self._registry,
        )
        self._g_admission_inflight.set_function(
            lambda: self._admission.inflight
        )
        self._m_hint_hits = Counter(
            "containerpilot_gateway_cache_hint_hits",
            "routing picks that landed on a replica advertising the "
            "request's prefix fingerprint (cache-aware routing)",
            registry=self._registry,
        )
        self._m_hint_misses = Counter(
            "containerpilot_gateway_cache_hint_misses",
            "fingerprinted requests routed cold: no digest-advertising "
            "replica was warm (or the warm ones exceeded cache_slack)",
            registry=self._registry,
        )
        self._m_sticky_evicted = Counter(
            "containerpilot_gateway_sticky_evicted",
            "sticky-affinity pins evicted by the LRU capacity bound",
            registry=self._registry,
        )
        self._g_fleet_reused = Gauge(
            "containerpilot_gateway_fleet_tokens_reused",
            "fleet-wide prefix-cache tokens_reused: live replicas' "
            "last-advertised counters plus departed replicas' final "
            "ones (the SLO-goodput yardstick for KV reuse)",
            registry=self._registry,
        )
        self._g_fleet_reused.set_function(self._fleet_tokens_reused)
        self._g_fleet_productive = Gauge(
            "cp_fleet_productive_fraction",
            "fleet device-time ledger: (prefill + decode) seconds "
            "over all attributed seconds, live + departed replicas "
            "(docs/90-observability.md § device-time ledger)",
            registry=self._registry,
        )
        self._g_fleet_productive.set_function(
            self._fleet_productive_fraction
        )
        # per-stage latency decomposition: one histogram row per
        # tracing stage (admission_queue_wait, upstream_ttfb,
        # replica.prefill, ...) — the aggregate face of /v1/traces
        self._m_stage = Histogram(
            "cp_request_stage_seconds",
            "per-stage request latency decomposition "
            "(docs/90-observability.md has the stage glossary)",
            ["stage"], registry=self._registry,
            buckets=(.001, .005, .02, .05, .1, .25, .5, 1, 2.5, 5,
                     10, 30, 60),
        )
        ensure_build_info(self._registry, "gateway")
        # event-loop health sentinel (analysis/loopcheck.py): the
        # gateway loop carries every mux stream, admission timer, and
        # catalog poll on the box — one blocking call stalls them all
        # at once, and cp_loop_lag_ms is how that stall gets a name
        # instead of surfacing as unattributed TTFT jitter
        self._loop_probe = LoopLagProbe()
        ensure_loop_lag_gauge(self._registry, self._loop_probe)

        self._server = HTTPServer()
        self._server.route("GET", "/health", self._health)
        self._server.route("GET", "/metrics", self._metrics)
        self._server.route("GET", "/fleet", self._fleet_status)
        self._server.route("GET", "/v1/traces", self._traces)
        self._server.route("GET", "/v1/goodput", self._goodput)
        self._server.route("GET", "/v1/model", self._model_info)
        for path, endpoint in (
            ("/v1/generate", "generate"),
            ("/v1/completions", "completions"),
            ("/v1/score", "score"),
        ):
            self._server.route("POST", path, self._api(endpoint, path))

    # -- lifecycle ------------------------------------------------------

    async def run(self) -> None:
        await self._server.start_tcp(self.host, self.port)
        self.port = self._server.bound_port or self.port
        self._loop_probe.start()
        await self._poll_once()  # first routing set before traffic
        self._poll_task = spawn(
            self._poll_loop(), name=f"fleet-gateway:{self.service_name}"
        )
        log.info(
            "gateway: %s:%d fronting service %r (%d replicas)",
            self.host, self.port, self.service_name, len(self._replicas),
        )

    async def stop(self) -> None:
        self._loop_probe.stop()
        if self._poll_task is not None and not self._poll_task.done():
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
            self._poll_task = None
        self._pool.close_all()
        await self._server.stop()

    async def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown, the replica drain invariant mirrored at
        the gateway: stop admitting (new API requests answer 503 +
        honest Retry-After immediately), let everything already queued
        or in flight — streams included — finish, then return. True
        once idle; False when ``timeout`` expired with work still
        running (the caller stops anyway; the window is a bound, not a
        promise). Idempotent; ``stop()`` still closes the listener."""
        if not self.draining:
            log.info(
                "gateway: draining (%d in flight, %d queued)",
                self._admission.inflight, self._admission.depth,
            )
        self.draining = True
        deadline = time.monotonic() + timeout
        while (
            self._admission.inflight > 0 or self._admission.depth > 0
        ):
            if time.monotonic() >= deadline:
                log.warning(
                    "gateway: drain timed out with %d in flight, "
                    "%d queued",
                    self._admission.inflight, self._admission.depth,
                )
                return False
            await asyncio.sleep(0.02)
        log.info("gateway: drained")
        return True

    @property
    def admission(self) -> AdmissionController:
        return self._admission

    @property
    def registry(self):
        """The gateway's private prometheus registry, so co-located
        actors (the autoscaler) can expose counters on this /metrics."""
        return self._registry

    def attach_autoscaler(self, autoscaler: Any) -> None:
        """Surface an autoscaler's stats on ``GET /fleet`` (its
        prometheus side joins via ``registry=gateway.registry``).
        Call once per pool in a disaggregated fleet — every attached
        autoscaler's stats and scale events are reported; only the
        FIRST should pass the gateway registry (the per-pool metric
        names would collide)."""
        self._autoscalers.append(autoscaler)

    def pool_load(self, role: str = "") -> "FleetLoad":
        """One pool's demand snapshot for its autoscaler's
        ``signals`` hook. ``role=""`` folds every serving replica
        (the classic mixed-fleet signal). The admission queue depth
        rides the PREFILL pool's signal (and the mixed one's):
        queued work is work nobody has prefilled yet, i.e. TTFT
        deadline pressure on admissions — while the decode pool
        scales on pure slot occupancy (TPOT pressure), which is what
        lets the two pools size independently (docs/60)."""
        from .autoscaler import FleetLoad

        if role:
            members = self._role_members(role)
        else:
            members = [
                r for r in self._replicas.values()
                if r.role != ROLE_STANDBY
            ]
        depth = (
            self._admission.depth if role != ROLE_DECODE else 0
        )
        return FleetLoad(
            queue_depth=depth,
            per_replica={r.id: float(r.load) for r in members},
        )

    def _pool_event(self, event: str, replica_id: str) -> None:
        """Mirror pool bookkeeping into the prometheus registry."""
        counter = {
            "hit": self._m_pool_hits,
            "miss": self._m_pool_misses,
            "evicted": self._m_pool_evicted,
        }.get(event)
        if counter is not None:
            counter.labels(replica_id).inc()

    @property
    def replica_count(self) -> int:
        return len(self._replicas)

    # -- discovery ------------------------------------------------------

    async def _poll_loop(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval)
            try:
                await self._poll_once()
            except Exception as exc:  # a flaky catalog isn't fatal
                log.warning("gateway: catalog poll failed: %s", exc)

    async def _poll_once(self) -> None:
        loop = asyncio.get_event_loop()
        did_change, healthy = await poll_upstream(
            self.backend, self.service_name, self.tag
        )
        # the poll RETURNED (it may still be empty): the staleness
        # clock on /fleet resets here, so a wedged/flapping catalog
        # shows up as a growing catalog_poll_age_s
        self._last_poll = time.monotonic()
        # change detection already scanned the catalog; re-list only
        # when membership moved (or when this gateway holds nothing a
        # freshly-shared backend considers unchanged, or the healthy
        # set emptied) — steady state costs ONE catalog scan per poll
        if not did_change:
            if healthy and self._replicas:
                # a healthy steady-state poll closes any hold-down
                # window: only CONSECUTIVE empty polls may wipe
                self._empty_polls = 0
                return
            if not healthy and not self._replicas:
                return
        instances = await loop.run_in_executor(
            None, self.backend.instances, self.service_name, self.tag
        )
        fresh: Dict[str, Replica] = {}
        for inst in instances:
            address = inst.address or "127.0.0.1"
            known = self._replicas.get(inst.id)
            if known is not None and (known.address, known.port) == (
                address, inst.port,
            ):
                fresh[inst.id] = known  # keep live outstanding counts
            else:
                fresh[inst.id] = Replica(inst.id, address, inst.port)
            # refresh the KV-reuse advertisement (digest + counters)
            # off the catalog notes — notes changes flip did_change,
            # so a replica whose cache contents moved re-lists here
            self._apply_notes(fresh[inst.id], inst.notes)
        if not fresh and self._replicas:
            # catalog-flap hold-down: an empty healthy set right after
            # a non-empty one is more often a torn read / flapping
            # catalog than a simultaneous fleet-wide death. Keep the
            # current routing table (and its pools) until the emptiness
            # persists for empty_poll_threshold consecutive polls.
            self._empty_polls += 1
            if self._empty_polls < self.empty_poll_threshold:
                self._m_flaps_damped.inc()
                self.flaps_damped += 1
                log.warning(
                    "gateway: empty catalog poll %d/%d damped "
                    "(holding %d replicas)",
                    self._empty_polls, self.empty_poll_threshold,
                    len(self._replicas),
                )
                return
            log.warning(
                "gateway: %d consecutive empty polls; dropping all "
                "replicas", self._empty_polls,
            )
        if fresh:
            self._empty_polls = 0
        if did_change or set(fresh) != set(self._replicas):
            log.info(
                "gateway: healthy set -> %s",
                sorted(f"{r.id}@{r.authority}" for r in fresh.values()),
            )
        for rid, gone in self._replicas.items():
            if rid not in fresh and gone.kv.get("tokens_reused", 0):
                # keep a departed replica's reuse contribution in the
                # fleet-wide gauge (its counter dies with its record);
                # zero contributions aren't parked — a long-lived
                # gateway over an autoscaled no-reuse fleet must not
                # grow an entry per departed id forever
                self._reuse_departed[rid] = gone.kv["tokens_reused"]
            if rid not in fresh and any(gone.goodput.values()):
                # same fold-in for the device-time ledger: a retired
                # replica's boot/compile/serve seconds happened, and
                # the fleet's badput decomposition must keep them
                self._goodput_departed[rid] = dict(gone.goodput)
        for rid in fresh:
            # a replica that FLAPPED out and rejoined (wedge heal,
            # TTL-starved heartbeat, catalog flap) advertises the same
            # cumulative counter again — drop the parked copy or the
            # gauge double-counts it on every flap
            self._reuse_departed.pop(rid, None)
            self._goodput_departed.pop(rid, None)
        self._replicas = fresh
        self._g_replicas.set(len(fresh))
        # admission capacity tracks the SERVING healthy set — a parked
        # standby contributes no dispatch slots until its promotion
        # beat lands, at which point capacity grows and queued
        # waiters are granted immediately (the promote-into-a-burst
        # fast path). Phase-specialized replicas (prefill/decode)
        # serve traffic and count like active ones.
        serving = sum(
            1 for r in fresh.values() if r.role != ROLE_STANDBY
        )
        self._g_standby.set(len(fresh) - serving)
        for role in _KNOWN_ROLES:
            self._g_role.labels(role).set(
                sum(1 for r in fresh.values() if r.role == role)
            )
        self._admission.set_capacity(serving)
        # pooled connections to a replica that LEFT the healthy set
        # (drained, deregistered, TTL-expired) are evicted, never
        # reused: a draining replica would answer them 503, a dead one
        # not at all
        self._pool.prune(set(fresh))

    def _apply_notes(self, replica: Replica, notes: str) -> None:
        """Decode a replica's heartbeat check output (``ok occ=0.50
        kv=... pd=v3:...``) into its routing state, field-by-field
        through the note-wire registry (``fleet/notes.py``) — the
        single schema both this consumer and the member's producer
        are driven from. Tolerant: a torn or digest-free note leaves
        the previous advertisement in place rather than blanking a
        warm replica."""
        fields = notes_mod.split_note(notes)
        if "kv" in fields:
            parsed = notes_mod.parse_field("kv", fields["kv"])
            # the counters are CUMULATIVE: a torn note's zero-filled
            # tail (or a truncated digit) must not regress them — a
            # regressed tokens_reused parked by a departure would
            # permanently drop the replica's contribution from the
            # fleet-wide gauge. Elementwise max keeps the best-known
            # cumulative value per field.
            replica.kv = {
                name: max(value, replica.kv.get(name, 0))
                for name, value in parsed.items()
            }
        if "gp" in fields:
            # device-time ledger totals: cumulative like the kv
            # counters, so the same elementwise-max torn-note
            # discipline applies — a truncated note's zero-filled
            # tail must never regress a stage's known seconds
            replica.goodput = goodput_mod.merge_note_max(
                replica.goodput,
                notes_mod.parse_field("gp", fields["gp"]),
            )
        if "pd" in fields:
            version, fps = notes_mod.parse_field("pd", fields["pd"])
            if version is not None and version != replica.digest_version:
                replica.digest = fps
                replica.digest_version = version
                replica.digest_at = time.monotonic()
        if "mg" in fields:
            # drain-migration progress: cumulative counters (same
            # elementwise-max torn-note discipline as kv=) whose
            # deltas feed the fleet accounting, plus fp->target
            # landings — each NEW landing repoints the drainer's
            # matching sticky pins onto the survivor immediately
            counters, landed = notes_mod.parse_field(
                "mg", fields["mg"]
            )
            prev = replica.migration
            merged = {
                name: max(counters.get(name, 0), prev.get(name, 0))
                for name in ("done", "total", "failed", "timeout")
            }
            moved = merged["done"] - prev.get("done", 0)
            failed = merged["failed"] - prev.get("failed", 0)
            timed_out = merged["timeout"] - prev.get("timeout", 0)
            if moved:
                self._m_migrated.inc(moved)
                self.migrations["sessions_migrated"] += moved
            if failed:
                self._m_migration_failed.inc(failed)
                self.migrations["failed"] += failed
            if timed_out:
                self._m_migration_timeout.inc(timed_out)
                self.migrations["timeout"] += timed_out
            replica.migration = merged
            replica.migrating = bool(counters.get("active", 0))
            for landed_fp, target in landed.items():
                if replica.migrated.get(landed_fp) == target:
                    continue
                replica.migrated[landed_fp] = target
                self._repoint_sessions(replica.id, landed_fp, target)
        # role rides every beat of a non-active replica (standby,
        # prefill, decode) and is ABSENT from an active one's note —
        # the first post-promotion beat flips the routing view back
        # to active by omission. Omission only counts on a note that
        # PARSED (a real beat always carries at least occ=): a
        # torn/empty read must keep the previous role, or one
        # half-written catalog record routes a poll interval of
        # traffic into a standby's 503s. An UNKNOWN role value (a
        # newer replica generation) routes as active: role is advice,
        # and degrading to mixed routing beats partitioning traffic.
        if fields:
            role = fields.get("role", ROLE_ACTIVE)
            replica.role = (
                role if role in _KNOWN_ROLES else ROLE_ACTIVE
            )
        if "cc" in fields:
            replica.compile_cache = fields["cc"]

    def _repoint_sessions(
        self, source_id: str, fp: int, target_id: str
    ) -> None:
        """Apply one migration landing: every sticky key pinned to
        the draining ``source_id`` whose recorded session fingerprint
        matches moves to the survivor NOW — the client's next turn
        lands where its KV already is, warm, instead of bouncing off
        the drainer's 503 or re-prefilling cold after deregister.
        A landing naming a target this gateway can't see (not yet
        polled, already gone) is skipped; the pin falls back to the
        ordinary drained-away re-pin path."""
        if target_id == source_id or target_id not in self._replicas:
            return
        for k, rid in self._sticky.items():
            if rid == source_id and self._session_fp.get(k) == fp:
                self._sticky[k] = target_id
                self.migrations["pins_repointed"] += 1

    def _fleet_tokens_reused(self) -> int:
        """Fleet-wide tokens_reused: live replicas' last-advertised
        counters plus what departed replicas took with them."""
        return sum(self._reuse_departed.values()) + sum(
            r.kv.get("tokens_reused", 0)
            for r in self._replicas.values()
        )

    def _fleet_productive_fraction(self) -> float:
        """Gauge body: the fleet ledger's headline number (0.0 until
        any ledger note has arrived — gauges can't carry None)."""
        fraction = goodput_mod.productive_fraction(
            goodput_mod.sum_stage_totals(
                [r.goodput for r in self._replicas.values()]
                + list(self._goodput_departed.values())
            )
        )
        return fraction if fraction is not None else 0.0

    def scale_event_report(self) -> List[Dict[str, Any]]:
        """Scale events stamped into the fleet ledger: each autoscaler
        launch/retire with — for launches — the time-to-first-routed-
        token, measured from the launch decision to the first 200 a
        generate/completions got from the new replica. None until the
        replica actually serves (the cold-start collapse item's
        yardstick: this number must fall release-over-release)."""
        if not self._autoscalers:
            return []
        events: List[Dict[str, Any]] = []
        for scaler in self._autoscalers:
            for event in getattr(scaler, "scale_log", ()):
                self._scale_event(events, event)
        return events

    def _scale_event(
        self, events: List[Dict[str, Any]], event: Dict[str, Any]
    ) -> None:
        entry = {
            "direction": event["direction"],
            "replica": event["replica"],
        }
        if "mode" in event:
            # how the launch happened: "promoted" (warm standby
            # flipped active) vs "cold" (full boot) — the split
            # the cold-start-collapse yardstick is judged on
            entry["mode"] = event["mode"]
        if "pool" in event:
            # which pool's autoscaler decided it (disaggregated
            # fleets size prefill and decode independently)
            entry["pool"] = event["pool"]
        if event["direction"] == "up":
            first_ok = self._first_ok.get(event["replica"])
            entry["ttfrt_s"] = (
                round(first_ok - event["at"], 3)
                if first_ok is not None
                and first_ok >= event["at"] else None
            )
        events.append(entry)

    def fleet_goodput(self) -> Dict[str, Any]:
        """The fleet device-time ledger: per-stage seconds summed
        over live AND departed replicas, productive fraction,
        dispatches/token, the per-replica breakdown, and scale-event
        TTFRT — the ``goodput`` block on ``/fleet`` and the body of
        the gateway's ``/v1/goodput``."""
        live = {
            rid: dict(r.goodput) for rid, r in self._replicas.items()
        }
        summary = goodput_mod.fleet_summary(
            list(live.values())
            + list(self._goodput_departed.values())
        )
        summary["replicas"] = {
            rid: {
                "productive_fraction": (
                    goodput_mod.productive_fraction(totals)
                ),
                "stages_s": {
                    s: round(totals.get(s, 0.0), 3)
                    for s in goodput_mod.STAGES
                },
            }
            for rid, totals in sorted(live.items())
        }
        summary["departed"] = {
            rid: {
                "productive_fraction": (
                    goodput_mod.productive_fraction(totals)
                ),
                "stages_s": {
                    s: round(totals.get(s, 0.0), 3)
                    for s in goodput_mod.STAGES
                },
            }
            for rid, totals in sorted(self._goodput_departed.items())
        }
        summary["scale_events"] = self.scale_event_report()
        return summary

    def _request_fingerprint(
        self, body: Dict[str, Any]
    ) -> Optional[int]:
        """The prefix fingerprint cache-aware routing scores against:
        computed from a single token row exactly the way replicas
        fingerprint their cached keys (kvtier/digest.py). Text
        prompts return None — the gateway has no tokenizer, so those
        requests keep plain sticky/least-loaded routing."""
        if not self.cache_routing:
            return None
        tokens = body.get("tokens")
        if (
            isinstance(tokens, list) and len(tokens) == 1
            and isinstance(tokens[0], list)
            and all(
                isinstance(t, int) for t in tokens[0][:FP_TOKENS]
            )
        ):
            try:
                return prefix_fingerprint(tokens[0])
            except (TypeError, ValueError, OverflowError):
                return None
        return None

    # -- routing --------------------------------------------------------

    def _pick(
        self,
        exclude: Iterable[str] = (),
        fp: Optional[int] = None,
        phase: Optional[str] = None,
    ) -> Optional[Replica]:
        """Least-loaded (dispatched + admission-queue-assigned);
        replica id breaks ties so the choice is deterministic under
        equal load. Counting only dispatched requests let a replica
        whose queued work hadn't landed yet look idle — the exact
        shape a mid-burst wedge hides behind.

        With a prefix fingerprint, a replica advertising it as warm
        is preferred — but only within ``cache_slack`` of the least
        load, so a warm-but-wedged replica never beats a healthy cold
        one; among warm candidates least-loaded still decides.

        Standby-role replicas are never candidates: they are warm
        capacity PARKED for promotion (fleet/standby.py), visible in
        the catalog and on /fleet but outside the routing set until
        their post-promotion heartbeat drops the role field.

        ``phase`` is the disaggregated fleet's soft preference:
        ``"decode"`` keeps generation off prefill-pool replicas,
        ``"prefill"`` keeps prefix seeding off decode-pool ones —
        mixed/active replicas qualify for both. SOFT by design: when
        the preferred subset is empty (a pool scaled to zero, or the
        whole pool is excluded by retries) the pick degrades to every
        serving candidate, so a disaggregated fleet losing one pool
        routes like a mixed fleet instead of 503ing."""
        excluded = set(exclude)
        candidates = [
            r for r in self._replicas.values()
            if r.id not in excluded and r.role != ROLE_STANDBY
        ]
        # a replica mid-evacuation (mg= active) takes no NEW work
        # while any alternative exists: it is leaving, and a fresh
        # session there would need migrating right back. Soft like
        # the phase preference — sole-survivor fleets still route.
        settled = [r for r in candidates if not r.migrating]
        candidates = settled or candidates
        if phase == "decode":
            preferred = [
                r for r in candidates if r.role != ROLE_PREFILL
            ]
            candidates = preferred or candidates
        elif phase == "prefill":
            preferred = [
                r for r in candidates if r.role != ROLE_DECODE
            ]
            candidates = preferred or candidates
        if not candidates:
            return None
        coldest = min(candidates, key=lambda r: (r.load, r.id))
        if fp is None:
            return coldest
        warm = [
            r for r in candidates
            if fp in r.digest
            and r.load <= coldest.load + self.cache_slack
        ]
        if warm:
            self._m_hint_hits.inc()
            self.hint_hits += 1
            return min(warm, key=lambda r: (r.load, r.id))
        if any(r.digest for r in candidates):
            # the hint existed and nobody (eligible) was warm — count
            # it only when digests are in play at all, so fleets that
            # never publish them don't log a miss per request
            self._m_hint_misses.inc()
            self.hint_misses += 1
        return coldest

    def _affinity_key(
        self, req: Request, body: Dict[str, Any]
    ) -> Optional[str]:
        if self.affinity == "none":
            return None
        session = body.get("session_id")
        if isinstance(session, (str, int)) and str(session):
            return f"s:{session}"
        header = req.headers.get("x-affinity-key", "")
        if header:
            return f"h:{header}"
        if self.affinity != "prefix":
            return None
        tokens = body.get("tokens")
        if (
            isinstance(tokens, list) and len(tokens) == 1
            and isinstance(tokens[0], list) and tokens[0]
        ):
            prefix = ",".join(map(str, tokens[0][:PREFIX_TOKENS]))
            return "p:" + hashlib.sha1(prefix.encode()).hexdigest()
        prompt = body.get("prompt")
        if isinstance(prompt, str) and prompt:
            return "p:" + hashlib.sha1(
                prompt[:PREFIX_CHARS].encode()
            ).hexdigest()
        return None

    def _route(
        self,
        key: Optional[str],
        exclude: Iterable[str] = (),
        fp: Optional[int] = None,
        phase: Optional[str] = None,
        dead: Iterable[str] = (),
    ) -> Optional[Replica]:
        """Sticky affinity first, cache-overlap-blended least-
        outstanding otherwise. A sticky target that LEFT the fleet
        (drained/crashed) re-pins and counts as drained_away; one
        that is merely excluded by this request's retry re-routes
        this request only — the pin (and the replica's warm prefix
        cache) survives a transient failure. A re-pin (or a fresh
        pick, or a retry's re-route) consults the request's prefix
        fingerprint, so a session whose replica drained lands on the
        warmest surviving replica instead of wherever least-loaded
        points.

        ``dead`` names replicas this request PROVED unreachable
        (transport failure on a handoff or proxy leg) that the
        catalog poll hasn't expired yet. A pin on one is invalidated
        and re-pinned NOW — treating it as a transient exclusion kept
        the stale pin alive for up to a poll interval, and every
        sticky retry in that window burned an attempt re-discovering
        the same dead replica."""
        excluded = set(exclude)
        dead_ids = set(dead)
        excluded |= dead_ids
        repin = True
        if key is not None:
            if fp is not None:
                # remember the session's fingerprint while it is
                # routed at all: the join a drain migration's mg=
                # landings repoint pins through
                self._session_fp[key] = fp
            pinned = self._sticky.get(key)
            if pinned is not None:
                replica = self._replicas.get(pinned)
                if replica is None or pinned in dead_ids:
                    self._m_drained.labels(pinned).inc()
                    self._sticky.pop(key, None)
                    self._session_fp.pop(key, None)
                elif pinned not in excluded:
                    self._sticky.move_to_end(key)
                    return replica
                else:
                    repin = False  # transient exclusion: keep the pin
        replica = self._pick(excluded, fp, phase)
        if replica is not None and key is not None and repin:
            self._sticky[key] = replica.id
            self._sticky.move_to_end(key)
            while len(self._sticky) > self.sticky_capacity:
                evicted_key, _rid = self._sticky.popitem(last=False)
                self._session_fp.pop(evicted_key, None)
                self._m_sticky_evicted.inc()
                self.sticky_evicted += 1
        return replica

    def _hedge_threshold(self, endpoint: str) -> Optional[float]:
        """Seconds after which a second dispatch is justified for
        ``endpoint``, or None while there's no basis to hedge on."""
        if not self.hedge or len(self._replicas) < 2:
            return None
        if self.hedge_after_ms is not None:
            return self.hedge_after_ms / 1e3
        pool = self._latencies.get(endpoint)
        if pool is None or len(pool) < HEDGE_MIN_SAMPLES:
            return None
        ordered = sorted(pool)
        idx = min(
            int(len(ordered) * self.hedge_quantile), len(ordered) - 1
        )
        return max(ordered[idx], self.hedge_min_ms / 1e3)

    # -- local handlers -------------------------------------------------

    def _retry_after(self) -> str:
        """Honest Retry-After (delta-seconds) for shed/drain/failure
        answers: derived from the admission queue's observed drain
        rate when replicas exist; with none, the catalog poll interval
        is the soonest anything can change."""
        if self._replicas:
            return str(self._admission.retry_after_s())
        return str(delta_seconds(self.poll_interval))

    async def _health(self, _req: Request) -> Response:
        if self.draining:
            return Response(
                503, b"draining\n",
                headers={"Retry-After": self._retry_after()},
            )
        if not self._replicas:
            return Response(
                503, b"no healthy replicas\n",
                headers={"Retry-After": self._retry_after()},
            )
        return Response(200, b"ok\n")

    async def _metrics(self, _req: Request) -> Response:
        body, content_type = exposition(self._registry)
        return Response(200, body, content_type=content_type)

    async def _traces(self, req: Request) -> Response:
        """Per-process trace surface: slowest-N + most-recent-N
        stitched timelines, JSON. ``?n=`` bounds either list."""
        return Response(
            200,
            self._tracer.snapshot_json(req.query),
            content_type="application/json",
        )

    async def _goodput(self, _req: Request) -> Response:
        """The fleet device-time ledger (same blob as ``/fleet``'s
        ``goodput`` block, standalone for scrapers and runbooks)."""
        return Response(
            200, json.dumps(self.fleet_goodput()).encode(),
            content_type="application/json",
        )

    async def _fleet_status(self, _req: Request) -> Response:
        body = json.dumps(
            {
                "service": self.service_name,
                "poll_interval": self.poll_interval,
                "empty_poll_threshold": self.empty_poll_threshold,
                "catalog_flaps_damped": self.flaps_damped,
                # staleness: how old the routing table's information
                # is — THE missing signal when diagnosing a flap
                # hold-down (a growing age means the catalog stopped
                # answering, not that replicas died)
                "catalog_poll_age_s": (
                    round(time.monotonic() - self._last_poll, 3)
                    if self._last_poll is not None else None
                ),
                "traces": (
                    self._tracer.fleet_summary()
                    if self.trace else None
                ),
                "draining": self.draining,
                # event-loop health: the same numbers as the
                # cp_loop_lag_ms gauge, for triage without a scrape
                "loop_lag_ms": {
                    "max": round(self._loop_probe.max_ms(), 2),
                    "p99": round(self._loop_probe.p99_ms(), 2),
                },
                # fleet-wide KV reuse: the goodput yardstick plus the
                # routing hint counters (docs/60 has the runbook rows)
                "kv": {
                    "cache_routing": self.cache_routing,
                    "cache_slack": self.cache_slack,
                    "tokens_reused": self._fleet_tokens_reused(),
                    "hint_hits": self.hint_hits,
                    "hint_misses": self.hint_misses,
                },
                # the fleet device-time ledger: where the fleet's
                # device-seconds went (goodput vs decomposed badput),
                # built from the gp= heartbeat notes — departed
                # replicas folded in, scale events TTFRT-stamped
                "goodput": self.fleet_goodput(),
                "sticky": {
                    "size": len(self._sticky),
                    "capacity": self.sticky_capacity,
                    "evicted": self.sticky_evicted,
                },
                "admission": self._admission.stats(),
                # warm-standby visibility (fleet/standby.py): which
                # healthy replicas are parked, promotable capacity
                "standby": {
                    "count": sum(
                        1 for r in self._replicas.values()
                        if r.role == ROLE_STANDBY
                    ),
                    "ids": sorted(
                        r.id for r in self._replicas.values()
                        if r.role == ROLE_STANDBY
                    ),
                },
                # disaggregated serving (docs/60): per-role pool
                # sizes and the KV-handoff counters
                "roles": {
                    role: sum(
                        1 for r in self._replicas.values()
                        if r.role == role
                    )
                    for role in _SERVING_ROLES + (ROLE_STANDBY,)
                },
                "handoff": dict(self.handoffs),
                # drain migration (docs/60 § drain runbook): sessions
                # moved to survivors over the handoff wire in reverse,
                # counted fallbacks, and the pins repointed off mg=
                # landings / X-CP-Migrated-To drain answers
                "migration": dict(self.migrations),
                "autoscaler": (
                    self._autoscalers[0].stats
                    if self._autoscalers else None
                ),
                "autoscalers": [
                    scaler.stats for scaler in self._autoscalers
                ],
                "pool": {
                    "max_idle": self._pool.max_idle,
                    "idle_ttl_s": self._pool.idle_ttl,
                    "max_uses": self._pool.max_uses,
                    "mux": self._pool.mux,
                },
                "replicas": [
                    {
                        "id": r.id,
                        "address": r.address,
                        "port": r.port,
                        "role": r.role,
                        "compile_cache": r.compile_cache or None,
                        "outstanding": r.outstanding,
                        "queued": r.queued,
                        "age_s": round(
                            time.monotonic() - r.first_seen, 1
                        ),
                        # digest size/staleness: how much of the
                        # replica's cache the gateway knows about,
                        # and how old that knowledge is
                        "kv": dict(r.kv),
                        "digest_fps": len(r.digest),
                        "digest_version": r.digest_version,
                        "digest_age_s": (
                            round(
                                time.monotonic() - r.digest_at, 3
                            )
                            if r.digest_at else None
                        ),
                        "pool": self._pool.stats(r.id),
                        "mux": self._pool.mux_stats(r.id),
                    }
                    for r in sorted(
                        self._replicas.values(), key=lambda r: r.id
                    )
                ],
            }
        ).encode()
        return Response(200, body, content_type="application/json")

    async def _model_info(self, req: Request) -> Response:
        return await self._proxy_buffered("model", "GET", "/v1/model", b"", None)

    # -- proxying -------------------------------------------------------

    def _api(self, endpoint: str, path: str):
        async def handler(req: Request) -> Response:
            t0 = time.perf_counter()
            body = req.body
            try:
                parsed = json.loads(body.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                parsed = {}  # the replica will 4xx it; just forward
            if not isinstance(parsed, dict):
                parsed = {}
            key = self._affinity_key(req, parsed)
            fp = self._request_fingerprint(parsed)
            # the single token row, for the disaggregated handoff's
            # replica-side POSTs; a non-None fp proves the shape
            row = parsed["tokens"][0] if fp is not None else None
            # mint (or adopt the client's) trace id and bind it for
            # the whole routing lifetime: spans recorded anywhere
            # downstream — admission, hedge legs, relays — attach to
            # this request without threading a handle through
            trace: Optional[tracing.Trace] = None
            token = None
            if self.trace:
                # adopt the client's id only when it is splice-safe
                # (tracing.safe_id): a hostile header must not ride
                # into the mux head template or echoed answers
                trace = self._tracer.start(
                    tracing.safe_id(req.headers.get("x-cp-trace")),
                    endpoint,
                )
                token = tracing.activate(trace)
            try:
                resp = await self._admitted(
                    endpoint, path, body, key, req,
                    stream=bool(parsed.get("stream")),
                    fp=fp,
                    tokens=row,
                )
            except asyncio.CancelledError:
                # client abandon: the server cancels the handler task
                # on disconnect. Not a gateway failure — file the
                # trace (still findable by id) with status 0, the
                # "no server verdict" convention, not a bogus 500
                if trace is not None:
                    trace.finish(0)
                    self._observe_trace(trace)
                raise
            except BaseException:
                if trace is not None:
                    trace.finish(500)
                    self._observe_trace(trace)
                raise
            finally:
                if token is not None:
                    tracing.deactivate(token)
            if trace is not None:
                # every answer — 200s, sheds, 504s, failures — carries
                # its trace id, so a client-reported failure is
                # findable in /v1/traces even when nothing dispatched
                resp.headers.setdefault(
                    tracing.TRACE_HEADER, trace.trace_id
                )
                if isinstance(resp, StreamingResponse):
                    # the relay owns the trace's tail: it adds the
                    # relay span, splices the replica digest off the
                    # final SSE frame, and finishes the trace at
                    # close. The head ships the breakdown known so
                    # far (TTFT is fully decided by this point).
                    resp.headers.setdefault(
                        tracing.DIGEST_HEADER, trace.digest()
                    )
                else:
                    trace.finish(resp.status)
                    self._observe_trace(trace)
                    resp.headers.setdefault(
                        tracing.DIGEST_HEADER, trace.digest()
                    )
            self._m_latency.labels(endpoint).observe(
                time.perf_counter() - t0
            )
            self._m_requests.labels(endpoint, str(resp.status)).inc()
            return resp

        return handler

    async def _admitted(
        self,
        endpoint: str,
        path: str,
        body: bytes,
        key: Optional[str],
        req: Request,
        *,
        stream: bool,
        fp: Optional[int] = None,
        tokens: Optional[List[int]] = None,
    ) -> Response:
        """Admission in front of routing: shed/expire before a replica
        slot is spent, then dispatch holding a ticket. A streaming
        response carries its ticket until the relay closes."""
        if self.draining:
            # graceful shutdown: new work bounces immediately; the
            # queued + in-flight work drain() is waiting on finishes
            return Response(
                503, b"gateway draining\n",
                headers={"Retry-After": self._retry_after()},
            )
        priority = parse_priority(req.headers.get("x-priority", ""))
        deadline_ms = req.headers.get("x-ttft-slo-ms", "")
        deadline_s: Optional[float] = None
        if deadline_ms:
            try:
                deadline_s = max(0.001, float(deadline_ms) / 1e3)
            except ValueError:
                deadline_s = None  # garbage header: server default
        # fold the queued request into its pinned replica's load
        # signal while it waits (see Replica.queued)
        pinned: Optional[Replica] = None
        if key is not None:
            pinned = self._replicas.get(self._sticky.get(key, ""))
            if pinned is not None:
                pinned.queued += 1
        trace = tracing.current_trace()
        try:
            ticket = await self._admission.admit(
                priority, key, deadline_s
            )
        except DeadlineExpired as exc:
            self._m_expired.inc()
            if trace is not None:
                # the request died IN the queue: its whole life was
                # queue wait, and the ledger must be able to say so
                end = tracing.now()
                trace.add_span(
                    "admission_queue_wait", end - exc.waited_s, end
                )
            return Response(
                504,
                f"admission deadline expired: {exc}\n".encode(),
                headers={"Retry-After": self._retry_after()},
            )
        except AdmissionError as exc:
            self._m_shed.labels(exc.label).inc()
            return Response(
                429,
                f"shed: {exc.reason}\n".encode(),
                headers={
                    "Retry-After": str(delta_seconds(exc.retry_after_s))
                },
            )
        finally:
            if pinned is not None:
                pinned.queued -= 1
        if trace is not None:
            # enqueued_at/granted_at are time.monotonic() stamps —
            # the same clock tracing runs on, so this span subtracts
            # cleanly against the upstream spans that follow
            trace.add_span(
                "admission_queue_wait",
                ticket.enqueued_at, ticket.granted_at,
            )
        self._m_admitted.labels(PRIORITY_NAMES[ticket.priority]).inc()
        released = False

        def release(ok: bool) -> None:
            nonlocal released
            if released:
                return
            released = True
            self._admission.release(ticket, completed=ok)

        # phase-aware routing: generation is decode-phase work — in a
        # disaggregated fleet it lands on the decode pool, with the
        # prefill pool seeding the KV prefix first (handoff below);
        # score/model stay phase-free
        phase = (
            "decode" if endpoint in ("generate", "completions")
            else None
        )
        dead: Set[str] = set()
        if phase == "decode" and fp is not None and tokens:
            dead = await self._disagg_prepare(key, fp, tokens)
        try:
            if stream:
                resp = await self._proxy_stream(
                    endpoint, path, body, key, fp, phase, dead
                )
            else:
                resp = await self._proxy_buffered(
                    endpoint, "POST", path, body, key, fp, phase, dead
                )
        except BaseException:
            release(False)
            raise
        if isinstance(resp, StreamingResponse):
            # the dispatch slot stays held while tokens stream; the
            # relay's close (completion, disconnect, upstream death)
            # releases it — both close paths are idempotent. A relay
            # the upstream killed mid-stream is NOT a completion for
            # the drain-rate window.
            inner_close = resp.close

            def close_with_release() -> None:
                try:
                    if inner_close is not None:
                        inner_close()
                finally:
                    release(
                        getattr(
                            resp, "upstream_intact", {}
                        ).get("ok", True)
                    )

            resp.close = close_with_release
        else:
            release(resp.status < 500)
        return resp

    async def _retry_pause(
        self,
        tried: Set[str],
        failed_ids: Iterable[str],
        attempt: int,
        backoff: float,
    ) -> float:
        """The ONE retry bookkeeping discipline: exclude the failed
        replicas, and — only when another attempt will actually
        happen — count the retry and pay the capped exponential
        backoff. Returns the advanced backoff."""
        retrying = attempt < self.retries
        for rid in failed_ids:
            tried.add(rid)
            if retrying:
                self._m_retried.labels(rid).inc()
        if retrying:
            await asyncio.sleep(self._jittered(backoff))
        return min(backoff * 2, self.retry_backoff_cap)

    async def _drain_bounce(
        self,
        key: Optional[str],
        replica_id: str,
        headers: Dict[str, str],
        tried: Set[str],
        attempt: int,
        backoff: float,
    ) -> float:
        """Retry bookkeeping for a retryable 503 that may be a
        DRAINING replica's migration-aware answer: when the response
        names the survivor the session already landed on
        (``X-CP-Migrated-To``), repoint the pin NOW — the retry
        reconnects warm instead of re-prefilling cold — and bill the
        bounce wait to the ``replica.kv_migrate`` trace stage so a
        TTFT violation blames the migration, not the survivor's
        prefill. Plain drain 503s take exactly the old path."""
        target = headers.get("x-cp-migrated-to", "")
        if target:
            self.migrations["drain_answers"] += 1
            if (
                key is not None
                and target in self._replicas
                and self._sticky.get(key) == replica_id
            ):
                self._sticky[key] = target
                self.migrations["pins_repointed"] += 1
        t0 = time.monotonic()
        backoff = await self._retry_pause(
            tried, {replica_id}, attempt, backoff
        )
        if target:
            trace = tracing.current_trace()
            if trace is not None:
                trace.add_span(
                    "replica.kv_migrate", t0, time.monotonic()
                )
        return backoff

    def _jittered(self, backoff: float) -> float:
        """Equal-jitter backoff (the fleet's shared shape,
        standby.equal_jitter): a deterministic floor plus a uniform
        random slice. A replica SIGKILLed under load fails every
        in-flight request in the same millisecond; without jitter the
        retries arrive at the surviving replicas as one synchronized
        storm, re-creating the spike that hedging and least-
        outstanding routing just absorbed."""
        if self.retry_jitter <= 0.0:
            return backoff
        return equal_jitter(backoff, self._rng, self.retry_jitter)

    def _failure_response(self, exc: Exception) -> Response:
        return Response(
            503,
            f"upstream failure: {exc}\n".encode(),
            headers={"Retry-After": self._retry_after()},
        )

    def _stamp_first_ok(self, replica: Replica) -> None:
        """First successful generation served by this replica: the
        other half of a scale event's time-to-first-routed-token."""
        if replica.first_ok_at is None:
            replica.first_ok_at = time.monotonic()
            self._first_ok.setdefault(
                replica.id, replica.first_ok_at
            )

    def _evict_replica_pool(self, replica_id: str) -> None:
        """A request to this replica just transport-failed: its other
        pooled connections can't be trusted either."""
        self._pool.evict(replica_id)

    async def _upstream_request(
        self,
        replica: Replica,
        method: str,
        path: str,
        body: bytes,
    ) -> Tuple[PooledConnection, int, Dict[str, str]]:
        """Acquire a connection (pooled or fresh), send one request,
        parse the response head. A REUSED connection that turns out
        stale (the server reaped it while idle) is discarded and the
        acquire repeats; the loop is bounded because each stale conn
        leaves the pool and a FRESH dial (reused=False) can never
        raise StaleConnection. The caller owns ``conn`` and must
        release/discard it after the body."""
        while True:
            try:
                with tracing.span("upstream_connect"):
                    conn = await self._pool.acquire(
                        replica, self.connect_timeout
                    )
            except UpstreamError:
                self._evict_replica_pool(replica.id)
                raise
            try:
                with tracing.span("upstream_ttfb"):
                    status, headers = await _send_on(
                        conn, method, path, body, self.request_timeout
                    )
            except StaleConnection as exc:
                self._pool.discard_stale(conn)
                log.debug("gateway: redialing stale connection: %s", exc)
                continue
            except UpstreamError:
                self._pool.discard(conn)
                self._evict_replica_pool(replica.id)
                raise
            except BaseException:
                # CancelledError (a losing hedge leg): close on the
                # way out, never pool a connection mid-request
                self._pool.discard(conn)
                raise
            return conn, status, headers

    async def _mux_request(
        self, replica: Replica, method: str, path: str, body: bytes
    ) -> Optional[MuxStream]:
        """Open one cp-mux stream to ``replica``; None means the
        replica doesn't speak mux (or mux is off) and the caller
        takes the classic pooled path. A warm shared connection that
        died between the acquire and this stream's send is redialed
        ONCE, mirroring the classic stale-conn discipline; the loop
        is bounded because a freshly dialed connection never raises
        StaleMuxConnection."""
        while True:
            try:
                with tracing.span("upstream_connect"):
                    mux = await self._pool.acquire_mux(
                        replica, self.connect_timeout
                    )
            except UpstreamError:
                self._evict_replica_pool(replica.id)
                raise
            if mux is None:
                return None
            try:
                # trace id rides the stream's HEADERS frame (pool.py
                # splices it into the cached head template)
                stream = await mux.open_stream(
                    method, path, body,
                    trace_id=tracing.current_trace_id() or None,
                )
            except StaleMuxConnection as exc:
                log.debug(
                    "gateway: redialing stale mux connection: %s", exc
                )
                continue
            except UpstreamError:
                self._evict_replica_pool(replica.id)
                raise
            self._m_mux_streams.labels(replica.id).inc()
            return stream

    def _cancel_stream(self, replica: Replica, stream: MuxStream) -> None:
        """Abort one stream with a CANCEL frame — the mux replacement
        for discarding a connection mid-request (hedge losers,
        abandoned clients, per-stream deadlines)."""
        if stream.cancel():
            self._m_mux_cancels.labels(replica.id).inc()
            self._m_conns_saved.labels(replica.id).inc()

    async def _mux_open_with_head(
        self, replica: Replica, method: str, path: str, body: bytes
    ) -> Optional[Tuple[MuxStream, int, Dict[str, str]]]:
        """Open a mux stream and await its response head, absorbing
        ONE stale-connection redial: a warm shared connection the
        replica reaped while idle fails the stream with zero response
        bytes (StaleMuxConnection), and resending on a fresh
        connection is as safe as the classic pooled redial — no
        routing retry is consumed. Error semantics otherwise follow
        the stream/connection split: a per-stream failure
        (MuxStreamError) CANCELs only this stream; a connection-level
        failure already failed every in-flight stream exactly once,
        so the eviction here is idempotent bookkeeping. None means
        the replica doesn't speak mux."""
        stream = await self._mux_request(replica, method, path, body)
        if stream is None:
            return None
        for retry in (True, False):
            try:
                with tracing.span("upstream_ttfb"):
                    status, headers = await stream.response_head(
                        self.request_timeout
                    )
                return stream, status, headers
            except StaleMuxConnection as exc:
                self._evict_replica_pool(replica.id)
                if not retry:
                    raise
                log.debug(
                    "gateway: redialing stale mux connection: %s", exc
                )
                stream = await self._mux_request(
                    replica, method, path, body
                )
                if stream is None:
                    raise UpstreamError(str(exc)) from None
            except MuxStreamError:
                self._cancel_stream(replica, stream)
                raise
            except UpstreamError:
                self._evict_replica_pool(replica.id)
                raise
            except BaseException:
                # CancelledError (a losing hedge leg / teardown): the
                # CANCEL frame replaces the old connection discard
                self._cancel_stream(replica, stream)
                raise
        raise AssertionError("unreachable")  # pragma: no cover

    async def _mux_fetch_buffered(
        self, replica: Replica, method: str, path: str, body: bytes
    ) -> Optional[Tuple[int, Dict[str, str], bytes]]:
        """One buffered exchange over a mux stream (None: no mux)."""
        opened = await self._mux_open_with_head(
            replica, method, path, body
        )
        if opened is None:
            return None
        stream, status, headers = opened
        try:
            with tracing.span("upstream_body"):
                payload = await stream.read_body(
                    self.request_timeout, MAX_UPSTREAM_BODY
                )
        except MuxStreamError:
            self._cancel_stream(replica, stream)
            raise
        except UpstreamError:
            self._evict_replica_pool(replica.id)
            raise
        except BaseException:
            self._cancel_stream(replica, stream)
            raise
        return status, headers, payload

    async def _fetch_from(
        self,
        endpoint: str,
        replica: Replica,
        method: str,
        path: str,
        body: bytes,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One buffered round trip to one replica, with routing
        accounting. Raises UpstreamError on transport failure.
        Prefers a mux stream on the replica's shared connection; on
        the classic path the connection returns to the pool only
        after the body was fully read on an intact, length-framed
        exchange."""
        self._m_routed.labels(replica.id).inc()
        replica.outstanding += 1
        t0 = time.perf_counter()
        try:
            fetched = await self._mux_fetch_buffered(
                replica, method, path, body
            )
            if fetched is not None:
                status, headers, payload = fetched
            else:
                conn, status, headers = await self._upstream_request(
                    replica, method, path, body
                )
                try:
                    with tracing.span("upstream_body"):
                        payload = await _read_body(
                            conn.reader, headers, self.request_timeout
                        )
                except UpstreamError:
                    self._pool.discard(conn)
                    self._evict_replica_pool(replica.id)
                    raise
                except BaseException:
                    # a cancelled leg may leave unread response bytes —
                    # that connection must never serve another request
                    self._pool.discard(conn)
                    raise
                if _reusable(headers):
                    self._pool.release(conn)
                else:
                    self._pool.discard(conn)
        finally:
            replica.outstanding -= 1
        if status == 200:
            self._latencies.setdefault(
                endpoint, deque(maxlen=512)
            ).append(time.perf_counter() - t0)
            if endpoint in ("generate", "completions"):
                self._stamp_first_ok(replica)
        return status, headers, payload

    async def _fetch_with_hedge(
        self,
        endpoint: str,
        replica: Replica,
        method: str,
        path: str,
        body: bytes,
        tried: Set[str],
        fp: Optional[int] = None,
        phase: Optional[str] = None,
    ) -> Tuple[int, Dict[str, str], bytes, Replica]:
        """Dispatch to ``replica``; if the response is still not back
        at the hedge threshold, race a second replica. First success
        wins; the loser is cancelled (closing its connection). The
        returned replica is the one whose response was taken, so the
        caller blames retries/exclusions on the right instance; a
        raised UpstreamError carries ``failed_ids`` naming every
        replica that transport-failed in the race."""
        primary = asyncio.ensure_future(
            self._fetch_from(endpoint, replica, method, path, body)
        )
        threshold = self._hedge_threshold(endpoint)
        if threshold is None:
            status, headers, payload = await primary
            return status, headers, payload, replica
        done, _ = await asyncio.wait({primary}, timeout=threshold)
        if done:
            return (*primary.result(), replica)
        hedge_replica = self._pick(tried | {replica.id}, fp, phase)
        if hedge_replica is None:
            status, headers, payload = await primary
            return status, headers, payload, replica
        self._m_hedged.labels(replica.id).inc()
        log.debug(
            "gateway: hedging %s after %.0fms on %s",
            path, threshold * 1e3, hedge_replica.id,
        )
        hedge = asyncio.ensure_future(
            self._fetch_from(
                endpoint, hedge_replica, method, path, body
            )
        )
        owners = {primary: replica, hedge: hedge_replica}
        pending = {primary, hedge}
        fallback: Optional[Tuple[int, Dict[str, str], bytes, Replica]] = None
        failed_ids: Set[str] = set()
        error: Optional[BaseException] = None
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    try:
                        status, headers, payload = task.result()
                    except Exception as exc:
                        # a transport-failed leg is excluded from
                        # future attempts even when the OTHER leg's
                        # response ends up being the one taken
                        failed_ids.add(owners[task].id)
                        tried.add(owners[task].id)
                        error = exc
                        continue
                    if status not in RETRYABLE_STATUSES or not pending:
                        return status, headers, payload, owners[task]
                    # a leg that answered a retryable 503 is excluded
                    # from future attempts too, even if the OTHER
                    # leg's answer wins this race
                    tried.add(owners[task].id)
                    fallback = (status, headers, payload, owners[task])
            if fallback is not None:
                return fallback
            assert error is not None
            error.failed_ids = failed_ids  # type: ignore[attr-defined]
            raise error
        finally:
            for task in (primary, hedge):
                if not task.done():
                    task.cancel()
                    try:
                        await task
                    except asyncio.CancelledError:
                        pass
                    except Exception as exc:
                        # the losing leg's transport error is expected
                        # (we closed its connection); log it so a
                        # systematic failure is still visible
                        log.debug(
                            "gateway: cancelled race leg failed: %s", exc
                        )

    # -- disaggregated prefill/decode handoff ---------------------------

    def _role_members(self, role: str) -> List[Replica]:
        return [
            r for r in self._replicas.values() if r.role == role
        ]

    async def _disagg_prepare(
        self, key: Optional[str], fp: int, row: List[int]
    ) -> Set[str]:
        """Phase-split dispatch: before a generation lands on the
        decode pool, run its prompt through the prefill pool and pull
        the resulting KV prefix onto the decode target replica-to-
        replica (serve.py's /v1/prefill + /v1/kv/pull, the cp-mux/1
        stream in kvtier/handoff.py). The generation that follows
        readmits the prefix through the SAME ``reuse_admission``
        protocol a local spill takes — byte parity by construction —
        so the decode replica never pays the cold prefill that would
        otherwise block its slot engine between decode windows.

        Best-effort by design, the degradation ladder (docs/60):
        either pool empty, the decode target already digest-warm, or
        ANY leg failing (transport, non-200, digest mismatch inside
        the pull) → return and let the routed replica prefill
        locally. Never raises; never surfaces to the client.

        Returns replica ids a leg PROVED unreachable, so the caller's
        routing retry starts with them excluded and their sticky pins
        invalidated (see ``_route``'s ``dead``)."""
        dead: Set[str] = set()
        if not self._role_members(ROLE_PREFILL) or not (
            self._role_members(ROLE_DECODE)
        ):
            # not a disaggregated fleet (or a whole pool died):
            # mixed routing handles everything
            return dead
        # pin the decode target FIRST — the pull must land on the
        # replica the generation will route to, and pinning here is
        # what makes the follow-up _route calls agree with it
        decode = self._route(key, (), fp, phase="decode")
        if decode is None or decode.role == ROLE_PREFILL:
            return dead
        if fp in decode.digest:
            # digest-warm multiturn follow-up: the target already
            # advertises this prefix — route straight to it
            self.handoffs["skipped_warm"] += 1
            return dead
        members = [
            r for r in self._role_members(ROLE_PREFILL)
            if r.id != decode.id
        ]
        if not members:
            return dead
        prefill = min(members, key=lambda r: (r.load, r.id))
        seed = json.dumps({"tokens": [row]}).encode()
        pull = json.dumps(
            {"tokens": [row], "from": prefill.authority}
        ).encode()
        t0 = time.perf_counter()
        moved: Optional[int] = None
        # one named trace stage for the whole transfer: the TTFT cost
        # of disaggregation must be attributable, not smeared into
        # upstream_ttfb (docs/90 § replica.kv_handoff)
        with tracing.span("replica.kv_handoff"):
            # blame a transport failure on whichever leg was in
            # flight: the seed runs against the prefill replica, the
            # pull against the decode target
            leg = prefill
            try:
                status, _, _ = await self._fetch_from(
                    "prefill", prefill, "POST", PREFILL_PATH, seed
                )
                if status == 200:
                    leg = decode
                    status, _, payload = await self._fetch_from(
                        "kv_pull", decode, "POST", KV_PULL_PATH, pull
                    )
                    if status == 200:
                        try:
                            moved = int(
                                json.loads(payload.decode())
                                .get("bytes", 0)
                            )
                        except (ValueError, AttributeError,
                                UnicodeDecodeError):
                            moved = 0
            except UpstreamError as exc:
                dead.add(leg.id)
                log.warning("gateway: kv handoff failed: %s", exc)
        if moved is None:
            self._m_handoff_failed.inc()
            self.handoffs["failed"] += 1
            return dead
        handoff_ms = (time.perf_counter() - t0) * 1e3
        self._m_handoffs.inc()
        self._m_handoff_bytes.inc(moved)
        self._m_handoff_ms.observe(handoff_ms)
        self.handoffs["total"] += 1
        self.handoffs["bytes"] += moved
        self.handoffs["ms_sum"] += handoff_ms
        log.debug(
            "gateway: kv handoff %s -> %s: %d bytes in %.1fms",
            prefill.id, decode.id, moved, handoff_ms,
        )
        return dead

    async def _proxy_buffered(
        self,
        endpoint: str,
        method: str,
        path: str,
        body: bytes,
        key: Optional[str],
        fp: Optional[int] = None,
        phase: Optional[str] = None,
        dead: Optional[Set[str]] = None,
    ) -> Response:
        # replicas a failed handoff already proved unreachable start
        # excluded AND invalidate their sticky pin (see _route)
        dead_ids: Set[str] = set(dead or ())
        tried: Set[str] = set(dead_ids)
        backoff = self.retry_backoff
        last: Optional[Response] = None
        for attempt in range(self.retries + 1):
            replica = self._route(key, tried, fp, phase, dead_ids)
            if replica is None:
                break
            try:
                status, headers, payload, served_by = (
                    await self._fetch_with_hedge(
                        endpoint, replica, method, path, body, tried,
                        fp, phase,
                    )
                )
            except UpstreamError as exc:
                log.warning("gateway: %s failed: %s", endpoint, exc)
                last = self._failure_response(exc)
                failed = (
                    getattr(exc, "failed_ids", None) or {replica.id}
                )
                # a transport failure is PROOF of death for the pin's
                # purposes — later attempts must re-pin, not wait out
                # the catalog poll
                dead_ids |= set(failed)
                backoff = await self._retry_pause(
                    tried, failed, attempt, backoff,
                )
                continue
            if status in RETRYABLE_STATUSES and attempt < self.retries:
                # blame the replica whose response this actually is —
                # under hedging that may be the hedge, not the primary
                last = self._relay(status, headers, payload)
                backoff = await self._drain_bounce(
                    key, served_by.id, headers, tried, attempt,
                    backoff,
                )
                continue
            self._stitch_upstream(headers)
            return self._relay(status, headers, payload)
        return last or Response(
            503, b"no healthy replicas\n",
            headers={"Retry-After": self._retry_after()},
        )

    def _stitch_upstream(self, headers: Dict[str, str]) -> None:
        """Splice the replica's span digest (if the response carried
        one) into the current trace as ``replica.*`` children, aligned
        at the moment this gateway dispatched upstream — the stitched
        timeline without a second RPC."""
        trace = tracing.current_trace()
        if trace is None:
            return
        digest = headers.get("x-cp-span-digest", "")
        if not digest:
            return
        base = trace.last_span_start("upstream_ttfb")
        trace.add_child_digest(
            digest, base if base is not None else trace.started
        )

    def _observe_trace(self, trace: "tracing.Trace") -> None:
        """Mirror a finished trace's spans into the per-stage
        histogram — the aggregate face of the same decomposition."""
        for stage, start, end, _meta in trace.spans:
            self._m_stage.labels(stage).observe(max(end - start, 0.0))

    @staticmethod
    def _relay(
        status: int, headers: Dict[str, str], payload: bytes
    ) -> Response:
        extra = {}
        if "retry-after" in headers:
            extra["Retry-After"] = headers["retry-after"]
        return Response(
            status,
            payload,
            content_type=headers.get(
                "content-type", "text/plain; charset=utf-8"
            ),
            headers=extra,
        )

    async def _proxy_stream(
        self,
        endpoint: str,
        path: str,
        body: bytes,
        key: Optional[str],
        fp: Optional[int] = None,
        phase: Optional[str] = None,
        dead: Optional[Set[str]] = None,
    ) -> Response:
        """SSE relay. Retries/re-routing apply only while nothing has
        been sent downstream; once the upstream stream starts, the
        gateway forwards bytes verbatim until EOF and mirrors client
        disconnects upstream (closing the connection sets the
        replica's cancel path at the next chunk boundary)."""
        dead_ids: Set[str] = set(dead or ())
        tried: Set[str] = set(dead_ids)
        backoff = self.retry_backoff
        last: Optional[Response] = None
        for attempt in range(self.retries + 1):
            replica = self._route(key, tried, fp, phase, dead_ids)
            if replica is None:
                break
            self._m_routed.labels(replica.id).inc()
            # count the stream as outstanding from the CONNECT on, not
            # from first byte: a burst of concurrent streams must not
            # all tie-break onto one replica while none has started
            replica.outstanding += 1
            held = True
            try:
                try:
                    opened = await self._mux_open_with_head(
                        replica, "POST", path, body
                    )
                except UpstreamError as exc:
                    log.warning(
                        "gateway: %s stream failed: %s", endpoint, exc
                    )
                    last = self._failure_response(exc)
                    dead_ids.add(replica.id)  # proven unreachable
                    backoff = await self._retry_pause(
                        tried, {replica.id}, attempt, backoff
                    )
                    continue
                if opened is not None:
                    # mux: this SSE relay is one stream among many on
                    # the replica's shared connection — it no longer
                    # pins a socket for its lifetime, and a client
                    # that hangs up costs a CANCEL frame
                    stream, status, headers = opened
                    if "text/event-stream" not in headers.get(
                        "content-type", ""
                    ):
                        # not a stream: an error body — buffer, relay,
                        # retry the retryable statuses
                        try:
                            payload = await stream.read_body(
                                self.request_timeout, MAX_UPSTREAM_BODY
                            )
                        except UpstreamError as exc:
                            if isinstance(exc, MuxStreamError):
                                self._cancel_stream(replica, stream)
                            else:
                                self._evict_replica_pool(replica.id)
                            log.warning(
                                "gateway: %s body read failed: %s",
                                endpoint, exc,
                            )
                            last = self._failure_response(exc)
                            backoff = await self._retry_pause(
                                tried, {replica.id}, attempt, backoff
                            )
                            continue
                        except BaseException:
                            self._cancel_stream(replica, stream)
                            raise
                        if (
                            status in RETRYABLE_STATUSES
                            and attempt < self.retries
                        ):
                            last = self._relay(status, headers, payload)
                            backoff = await self._drain_bounce(
                                key, replica.id, headers, tried,
                                attempt, backoff,
                            )
                            continue
                        return self._relay(status, headers, payload)
                    held = False  # ownership moves to the relay
                    if status == 200:
                        self._stamp_first_ok(replica)
                    return self._relay_mux_stream(replica, stream, status)
                try:
                    conn, status, headers = await self._upstream_request(
                        replica, "POST", path, body
                    )
                except UpstreamError as exc:
                    log.warning(
                        "gateway: %s stream failed: %s", endpoint, exc
                    )
                    last = self._failure_response(exc)
                    dead_ids.add(replica.id)  # proven unreachable
                    backoff = await self._retry_pause(
                        tried, {replica.id}, attempt, backoff
                    )
                    continue
                content_type = headers.get("content-type", "")
                if "text/event-stream" not in content_type:
                    # not a stream: a 422/503/500 error body (or a
                    # server without --slots) — buffer and relay,
                    # retrying the retryable statuses like the
                    # buffered path
                    try:
                        payload = await _read_body(
                            conn.reader, headers, self.request_timeout
                        )
                    except UpstreamError as exc:
                        self._pool.discard(conn)
                        self._evict_replica_pool(replica.id)
                        log.warning(
                            "gateway: %s body read failed: %s",
                            endpoint, exc,
                        )
                        last = self._failure_response(exc)
                        backoff = await self._retry_pause(
                            tried, {replica.id}, attempt, backoff
                        )
                        continue
                    except BaseException:
                        self._pool.discard(conn)
                        raise
                    if _reusable(headers):
                        self._pool.release(conn)
                    else:
                        self._pool.discard(conn)
                    if (
                        status in RETRYABLE_STATUSES
                        and attempt < self.retries
                    ):
                        last = self._relay(status, headers, payload)
                        backoff = await self._drain_bounce(
                            key, replica.id, headers, tried,
                            attempt, backoff,
                        )
                        continue
                    return self._relay(status, headers, payload)
                held = False  # ownership moves to the relay's close()
                if status == 200:
                    self._stamp_first_ok(replica)
                return self._relay_stream(replica, conn, status)
            finally:
                if held:
                    replica.outstanding -= 1
        return last or Response(
            503, b"no healthy replicas\n",
            headers={"Retry-After": self._retry_after()},
        )

    def _finish_stream_trace(
        self,
        trace: Optional["tracing.Trace"],
        relay_t0: float,
        tail: bytearray,
        status: int,
        intact: bool,
    ) -> None:
        """Shared relay-close tail for both stream transports: record
        the relay span, splice the replica digest off the final SSE
        ``done`` frame (the stream's version of the digest header),
        finish the trace, feed the stage histogram."""
        if trace is None:
            return
        trace.add_span("relay", relay_t0, tracing.now())
        digest = _tail_digest(bytes(tail))
        if digest:
            base = trace.last_span_start("upstream_ttfb")
            trace.add_child_digest(
                digest, base if base is not None else trace.started
            )
        trace.finish(status if intact else 0)
        self._observe_trace(trace)

    def _relay_stream(
        self,
        replica: Replica,
        conn: PooledConnection,
        status: int,
    ) -> StreamingResponse:
        """Relay an upstream SSE stream; the caller's outstanding
        count transfers here and is released by close(). Streams are
        close-delimited, so the connection never returns to the pool
        — close() discards it."""
        closed = [False]
        # whether the relay ended on an intact upstream (clean EOF vs
        # transport death): read by the admission-ticket release so a
        # fleet whose streams keep dying doesn't feed the drain-rate
        # window with phantom completions
        intact = {"ok": True}
        trace = tracing.current_trace()
        relay_t0 = tracing.now()
        tail = bytearray()

        def close() -> None:
            # idempotent: generator-finally AND the response's close
            # callback both fire on some paths
            if closed[0]:
                return
            closed[0] = True
            replica.outstanding -= 1
            self._pool.discard(conn)
            self._finish_stream_trace(
                trace, relay_t0, tail, status, intact["ok"]
            )

        async def chunks():
            try:
                while True:
                    chunk = await timed_read(
                        conn.reader,
                        conn.reader.read(65536),
                        self.request_timeout,
                    )
                    if not chunk:
                        return
                    if trace is not None:
                        _keep_tail(tail, chunk)
                    yield chunk
            except (OSError, asyncio.TimeoutError):
                # upstream died mid-stream; downstream sees EOF
                intact["ok"] = False
                return
            finally:
                close()

        resp = StreamingResponse(chunks(), status=status, close=close)
        resp.upstream_intact = intact  # type: ignore[attr-defined]
        return resp

    def _relay_mux_stream(
        self,
        replica: Replica,
        stream: MuxStream,
        status: int,
    ) -> StreamingResponse:
        """Relay an upstream SSE stream carried as a mux stream. The
        caller's outstanding count transfers here and is released by
        close(). Where the HTTP/1.1 relay discarded its (close-
        delimited) connection on every close, this one frees only the
        stream: an abandoned client turns into a CANCEL frame and the
        shared connection keeps serving its co-resident streams —
        both paths count into conns_saved_by_mux."""
        closed = [False]
        intact = {"ok": True}
        trace = tracing.current_trace()
        relay_t0 = tracing.now()
        tail = bytearray()

        def close() -> None:
            # idempotent: generator-finally AND the response's close
            # callback both fire on some paths
            if closed[0]:
                return
            closed[0] = True
            replica.outstanding -= 1
            if stream.cancel():
                # the downstream client abandoned mid-stream: CANCEL
                # frees the stream id upstream, nothing is torn down
                self._m_mux_cancels.labels(replica.id).inc()
                self._m_conns_saved.labels(replica.id).inc()
            elif intact["ok"]:
                # completed cleanly: the close-delimited HTTP/1.1
                # relay would have burned this connection instead
                self._m_conns_saved.labels(replica.id).inc()
            self._finish_stream_trace(
                trace, relay_t0, tail, status, intact["ok"]
            )

        async def chunks():
            try:
                while True:
                    chunk = await stream.read_chunk(self.request_timeout)
                    if not chunk:
                        return
                    if trace is not None:
                        _keep_tail(tail, chunk)
                    yield chunk
            except MuxStreamError:
                # this stream died (deadline, server-side abort); the
                # connection is fine — downstream sees EOF
                intact["ok"] = False
                return
            except UpstreamError:
                # the shared connection died mid-relay
                intact["ok"] = False
                self._evict_replica_pool(replica.id)
                return
            finally:
                close()

        resp = StreamingResponse(chunks(), status=status, close=close)
        resp.upstream_intact = intact  # type: ignore[attr-defined]
        return resp


def main() -> int:
    """Run a standalone gateway:
    ``python -m containerpilot_tpu.fleet --catalog file:/shared/catalog``
    """
    import argparse
    import logging as logging_mod
    import signal as signal_mod

    from ..discovery.factory import new_backend

    parser = argparse.ArgumentParser(
        description="inference fleet gateway"
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8800)
    parser.add_argument(
        "--catalog", required=True,
        help="discovery backend URI, as the supervisor's 'consul' "
        "config key: 'file:/shared/catalog' or 'consul:8500'",
    )
    parser.add_argument("--service", default="inference")
    parser.add_argument("--tag", default="")
    parser.add_argument("--poll-interval", type=float, default=1.0)
    parser.add_argument("--retries", type=int, default=2)
    parser.add_argument(
        "--retry-jitter", type=float, default=0.5,
        help="fraction of each retry backoff randomized (0 disables; "
        "desynchronizes retry storms after a replica dies under load)",
    )
    parser.add_argument(
        "--empty-poll-threshold", type=int, default=3,
        help="consecutive empty catalog polls before a previously "
        "non-empty routing table is dropped (flap hold-down)",
    )
    parser.add_argument(
        "--affinity", choices=AFFINITY_MODES, default="session"
    )
    parser.add_argument(
        "--cache-routing", default=True,
        action=argparse.BooleanOptionalAction,
        help="cache-contents-aware routing: when a request has no "
        "live sticky pin, prefer a replica whose advertised prefix "
        "digest contains the request's fingerprint (--no-cache-"
        "routing keeps pure sticky + least-outstanding)",
    )
    parser.add_argument(
        "--cache-slack", type=int, default=2,
        help="extra load a cache-warm replica may carry over the "
        "least-loaded candidate and still win the pick (0 = warmth "
        "only ever breaks exact load ties)",
    )
    parser.add_argument(
        "--sticky-capacity", type=int, default=STICKY_CAPACITY,
        help="LRU bound on sticky-affinity pins; evictions count on "
        "/metrics (sticky_evicted)",
    )
    parser.add_argument(
        "--hedge-after-ms", type=float, default=None,
        help="fixed hedge deadline; default learns the tail quantile",
    )
    parser.add_argument("--no-hedge", action="store_true")
    parser.add_argument(
        "--pool-max-idle", type=int, default=8,
        help="idle keep-alive connections kept per replica "
        "(0 disables reuse: every request dials)",
    )
    parser.add_argument(
        "--pool-idle-ttl", type=float, default=30.0,
        help="seconds an idle pooled connection stays reusable",
    )
    parser.add_argument(
        "--no-pool", action="store_true",
        help="shorthand for --pool-max-idle 0",
    )
    parser.add_argument(
        "--mux", default=True, action=argparse.BooleanOptionalAction,
        help="carry replica traffic as interleaved cp-mux/1 streams "
        "on one warm connection per replica (--no-mux forces the "
        "classic one-request-per-connection pooled path; replicas "
        "that decline the upgrade fall back per-replica either way)",
    )
    parser.add_argument(
        "--trace", default=True, action=argparse.BooleanOptionalAction,
        help="per-request cross-hop tracing (X-CP-Trace propagation, "
        "/v1/traces, cp_request_stage_seconds): on by default and "
        "effectively free (bench-pinned); --no-trace is the bench's "
        "A/B control",
    )
    parser.add_argument(
        "--admission-queue-depth", type=int, default=256,
        help="bounded admission queue in front of routing; a full "
        "queue sheds new work with 429 + Retry-After",
    )
    parser.add_argument(
        "--admission-high-water", type=int, default=None,
        help="queue depth past which BATCH-priority requests shed "
        "(default: half the queue)",
    )
    parser.add_argument(
        "--admission-deadline-ms", type=float, default=None,
        help="TTFT budget for queued work: a request still queued "
        "this long is 504'd without dispatching (default: none; "
        "clients can pass X-TTFT-SLO-Ms per request)",
    )
    parser.add_argument(
        "--per-replica-inflight", type=int, default=64,
        help="dispatch-slot capacity contributed per healthy replica",
    )
    parser.add_argument(
        "--session-rate", type=float, default=0.0,
        help="per-session token-bucket rate (requests/s; 0 disables)",
    )
    parser.add_argument(
        "--session-burst", type=float, default=None,
        help="per-session bucket burst (default: 2x rate)",
    )
    parser.add_argument(
        "--drain-window", type=float, default=30.0,
        help="seconds SIGTERM waits for queued + in-flight requests "
        "before the gateway exits",
    )
    args = parser.parse_args()

    logging_mod.basicConfig(
        level=logging_mod.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    backend = new_backend(args.catalog)
    if backend is None:
        raise SystemExit("--catalog resolved to no discovery backend")
    gateway = FleetGateway(
        backend, args.service, args.host, args.port,
        tag=args.tag, poll_interval=args.poll_interval,
        retries=args.retries, retry_jitter=args.retry_jitter,
        empty_poll_threshold=args.empty_poll_threshold,
        affinity=args.affinity,
        cache_routing=args.cache_routing,
        cache_slack=args.cache_slack,
        sticky_capacity=args.sticky_capacity,
        hedge=not args.no_hedge, hedge_after_ms=args.hedge_after_ms,
        pool_max_idle=0 if args.no_pool else args.pool_max_idle,
        pool_idle_ttl=args.pool_idle_ttl,
        mux=args.mux,
        trace=args.trace,
        admission=dict(
            max_queue_depth=args.admission_queue_depth,
            high_water=args.admission_high_water,
            deadline_s=(
                args.admission_deadline_ms / 1e3
                if args.admission_deadline_ms is not None else None
            ),
            per_replica_inflight=args.per_replica_inflight,
            session_rate=args.session_rate,
            session_burst=args.session_burst,
        ),
    )

    async def serve() -> None:
        await gateway.run()
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal_mod.SIGTERM, signal_mod.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        # graceful: new work bounces with 503 + Retry-After while
        # queued + in-flight requests finish under the drain window
        await gateway.drain(args.drain_window)
        await gateway.stop()

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
