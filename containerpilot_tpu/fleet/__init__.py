"""Inference fleet: discovery-driven serving replicas + gateway.

ContainerPilot's whole point is lifecycle — register a service,
heartbeat its health, watch upstreams, drain on maintenance — and
this package joins that supervisor half to the serving half
(workload/serve.py) as a FLEET:

- ``FleetMember`` (member.py): registers one running InferenceServer
  in a discovery Backend with a TTL check, heartbeats the TTL off the
  replica's real health state, and implements the drain path (health
  503 + reject new work + deregister while in-flight requests
  finish). Wired to a supervisor bus, the control plane's
  ``POST /v3/maintenance/enable`` drains the replica.
- ``FleetGateway`` (gateway.py): discovers healthy replicas through a
  watches-style catalog poll and proxies the inference API over them
  with least-outstanding-requests routing, optional session/prefix
  affinity, retry-on-a-different-replica, tail-latency hedging,
  per-replica keep-alive connection pooling (pool.py), and
  per-replica counters on ``/metrics``.

- ``AdmissionController`` (admission.py): overload defense in front
  of routing — bounded queue, per-request TTFT deadlines, priority
  classes, per-session token buckets, and load shedding with honest
  drain-rate-derived Retry-After.
- ``Autoscaler`` (autoscaler.py): the capacity loop — watches the
  admission queue + folded per-replica load and launches/retires
  replicas through a caller-provided launcher, with hysteresis,
  sustain windows, and a cooldown so bursts (and catalog flaps)
  don't thrash the fleet size.

Every later scale direction (multi-backend, spillover) routes
through this seam.
"""
from .admission import (
    AdmissionController,
    AdmissionError,
    DeadlineExpired,
    SessionLimited,
    ShedError,
)
from .autoscaler import Autoscaler, AutoscalerConfig, FleetLoad
from .gateway import FleetGateway, Replica
from .member import FleetMember
from .pool import ConnectionPool, StaleConnection, UpstreamError
from .standby import (
    ROLE_ACTIVE,
    ROLE_DECODE,
    ROLE_PREFILL,
    ROLE_STANDBY,
    StandbyLauncher,
    fetch_params,
)

__all__ = [
    "AdmissionController",
    "AdmissionError",
    "Autoscaler",
    "AutoscalerConfig",
    "ConnectionPool",
    "DeadlineExpired",
    "FleetGateway",
    "FleetLoad",
    "FleetMember",
    "ROLE_ACTIVE",
    "ROLE_DECODE",
    "ROLE_PREFILL",
    "ROLE_STANDBY",
    "Replica",
    "SessionLimited",
    "ShedError",
    "StaleConnection",
    "StandbyLauncher",
    "UpstreamError",
    "fetch_params",
]
